"""The native API server: FakeApiServer's API over the C++ store.

A drop-in replacement for `kubeflow_tpu.testing.fake_apiserver.FakeApiServer`
whose storage semantics (resourceVersion concurrency, spec/status
surfaces, finalizers, owner-ref cascade, namespace drain, label
selectors) live in compiled code (`native/src/store.cc`) — the reference
kept this tier native too (its controllers store through the Go
apiserver; envtest in `profile-controller/controllers/suite_test.go:29`
is the same idea for tests).

Watch delivery stays synchronous and ordered: every mutating call drains
the store's event journal and dispatches to subscribers before
returning, so controller tests behave deterministically on either
backend. Admission mutators run Python-side (the webhook is its own
component), exactly as in FakeApiServer.

Reads are copy-on-write too (docs/perf.md): the wrapper keeps a
Python-side snapshot mirror — frozen Resources per (kind, namespace),
fed from the C++ store's own journal — so get/list/kinds and every
watch delivery share one immutable materialization per commit (zero
ctypes round trips, zero JSON parses, zero copies per read). The same
handler contract as FakeApiServer applies: delivered objects are
frozen; `.thaw()` for a private mutable copy.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable

from kubeflow_tpu.api.objects import Resource
from kubeflow_tpu.native import core
from kubeflow_tpu.testing.fake_apiserver import (
    AlreadyExists,
    Conflict,
    NotFound,
    WatchHandler,
)


_log = logging.getLogger(__name__)


def _to_resource(d: dict) -> Resource:
    return Resource.from_dict(d)


class NativeApiServer:
    def __init__(self, journal_size: int = 10_000):
        self._store = core.NativeStore()
        self._cursor = 0
        self._watchers: list[tuple[str | None, WatchHandler]] = []
        self._admission: list[tuple[str | None, Callable[[Resource], Resource]]] = []
        # Serializes mutate+dispatch so event order is deterministic even
        # with concurrent controller threads (the C++ store is itself
        # thread-safe; this lock is only about dispatch ordering).
        self._dispatch_lock = threading.RLock()
        # Resumable event journal — the same bounded
        # (resourceVersion, event, object) surface FakeApiServer keeps,
        # fed from the C++ store's journal in _drain_events, so the HTTP
        # facade's watch endpoints (long-poll AND streaming) serve this
        # backend identically (drop-in means behind the facade too).
        self._journal: list[tuple[int, str, Resource]] = []
        self._journal_size = journal_size
        self._journal_cv = threading.Condition(self._dispatch_lock)
        self._rv = 0
        self._floor = 0
        # Python-side snapshot mirror (the shared KindIndex, same
        # structure FakeApiServer indexes with), fed from the C++
        # store's own event journal in _drain_events. Every
        # compiled-store mutation — including finalizer transitions,
        # owner-ref cascades, and namespace drains — emits a journal
        # event (store.cc Append sites), so after each drain the mirror
        # equals the store. get/list/kinds serve these frozen shared
        # snapshots directly: zero ctypes round trips, zero JSON
        # parses, zero copies per read (docs/perf.md).
        from kubeflow_tpu.testing.fake_apiserver import KindIndex

        self._mirror = KindIndex()
        self._mirror_lock = threading.Lock()

    # -- admission --------------------------------------------------------

    def register_admission(
        self, mutator: Callable[[Resource], Resource], kind: str | None = None
    ) -> None:
        with self._dispatch_lock:
            self._admission.append((kind, mutator))

    def _admit(self, obj: Resource) -> Resource:
        for kind, mutator in list(self._admission):
            if kind is None or kind == obj.kind:
                obj = mutator(obj.deepcopy())
        return obj

    # -- watch ------------------------------------------------------------

    def watch(self, handler: WatchHandler, kind: str | None = None) -> None:
        with self._dispatch_lock:
            self._watchers.append((kind, handler))

    def _drain_events(self) -> None:
        events, cursor = self._store.events(self._cursor)
        self._cursor = cursor
        self._store.trim(cursor)
        # Journal the WHOLE batch before any handler runs: the C++
        # cursor is already advanced and trimmed, so an event that
        # misses the journal here is gone forever — a raising handler
        # must not cost later events their only remaining record (or
        # surface to a writer whose write already committed).
        batch = []
        with self._journal_cv:
            for ev in events:
                # ONE materialization per event; the frozen snapshot is
                # then shared by the journal, the snapshot mirror, and
                # every handler (docs/perf.md).
                obj = _to_resource(ev["object"]).freeze()
                rv = obj.metadata.resource_version
                self._rv = max(self._rv, rv)
                self._journal.append((rv, ev["type"], obj))
                self._mirror_apply(ev["type"], obj)
                batch.append((ev["type"], obj))
            if len(self._journal) > self._journal_size:
                del self._journal[: -self._journal_size]
            self._journal_cv.notify_all()
        for etype, obj in batch:
            for kind, handler in list(self._watchers):
                if kind is None or kind == obj.kind:
                    try:
                        handler(etype, obj)
                    except Exception:
                        _log.exception(
                            "watch handler failed for %s %s",
                            etype, obj.key,
                        )

    def _mirror_apply(self, etype: str, obj: Resource) -> None:
        with self._mirror_lock:
            if etype == "DELETED":
                self._mirror.pop(*obj.key)
            else:
                self._mirror.put(obj)

    @property
    def current_rv(self) -> int:
        with self._dispatch_lock:
            return self._rv

    def events_since(
        self,
        resource_version: int,
        kind: str | None = None,
        namespace: str | None = None,
    ) -> tuple[list[tuple[int, str, Resource]], int]:
        """FakeApiServer's journal contract — the shared
        select_journal_events, so the 410 horizon math is one
        implementation across backends."""
        from kubeflow_tpu.testing.fake_apiserver import (
            select_journal_events,
        )

        with self._dispatch_lock:
            return select_journal_events(
                self._journal, self._floor, self._rv,
                resource_version, kind, namespace,
            )

    def wait_events(
        self,
        resource_version: int,
        kind: str | None = None,
        namespace: str | None = None,
        timeout: float = 10.0,
    ) -> tuple[list[tuple[int, str, Resource]], int]:
        from kubeflow_tpu.testing.fake_apiserver import wait_journal_events

        return wait_journal_events(
            self._journal_cv, self.events_since,
            resource_version, kind, namespace, timeout,
        )

    def _translate(self, err: core.StoreError) -> Exception:
        msg = str(err)
        if err.code == core.STORE_NOT_FOUND:
            return NotFound(msg)
        if err.code == core.STORE_ALREADY_EXISTS:
            return AlreadyExists(msg)
        if err.code == core.STORE_CONFLICT:
            return Conflict(msg)
        return err

    # -- CRUD -------------------------------------------------------------


    def _check_lease_guard(self, guard, kind: str) -> None:
        """Shared fencing contract (fake_apiserver.check_lease_guard) —
        caller holds _dispatch_lock, which every mutation including
        Lease renewals through this server serializes on, so the check
        is atomic here too."""
        from kubeflow_tpu.testing.fake_apiserver import check_lease_guard

        def lookup(ns: str, name: str):
            try:
                return _to_resource(self._store.get("Lease", ns, name)).spec
            except core.StoreError:
                return None

        check_lease_guard(lookup, guard, kind)

    def create(self, obj: Resource, *, lease_guard=None) -> Resource:
        self._reject_webhook_config(obj)
        obj = self._admit(obj)
        with self._dispatch_lock:
            self._check_lease_guard(lease_guard, obj.kind)
            try:
                stored = self._store.create(obj.to_dict())
            except core.StoreError as e:
                raise self._translate(e) from None
            self._drain_events()
            return self._committed(stored)

    def _committed(self, stored: dict) -> Resource:
        """The frozen snapshot for a just-committed write. The caller
        holds _dispatch_lock through mutate+drain, so the mirror entry
        at this rv IS this write; parse the ABI's JSON only if the
        object is already gone again (finalizing update)."""
        meta = stored["metadata"]
        with self._mirror_lock:
            obj = self._mirror.get(
                stored["kind"], meta.get("namespace", "default"),
                meta["name"],
            )
        if (
            obj is not None
            and obj.metadata.resource_version == meta.get("resourceVersion")
        ):
            return obj
        return _to_resource(stored).freeze()

    def get(self, kind: str, name: str, namespace: str = "default") -> Resource:
        with self._mirror_lock:
            obj = self._mirror.get(kind, namespace, name)
        if obj is None:
            raise NotFound(f"{kind} {namespace}/{name} not found")
        return obj  # frozen shared snapshot; .thaw() to mutate

    def list(
        self,
        kind: str,
        namespace: str | None = None,
        label_selector: dict[str, str] | None = None,
    ) -> list[Resource]:
        """Frozen shared snapshots from the mirror (the shared
        KindIndex walk, so ordering/filtering can't drift from
        FakeApiServer): O(result) per call, no ctypes round trip, no
        JSON parse."""
        with self._mirror_lock:
            return self._mirror.list(kind, namespace, label_selector)

    def _reject_webhook_config(self, obj: Resource) -> None:
        # Webhook callouts are implemented by FakeApiServer only;
        # silently storing the config here would make failurePolicy=Fail
        # fail OPEN on this backend — refuse loudly instead.
        if obj.kind == "WebhookConfiguration":
            from kubeflow_tpu.testing.fake_apiserver import Invalid

            raise Invalid(
                "WebhookConfiguration callouts are not supported on the "
                "native store backend — run the facade over "
                "FakeApiServer for out-of-process admission"
            )

    def update(self, obj: Resource, *, lease_guard=None) -> Resource:
        self._reject_webhook_config(obj)
        obj = self._admit(obj)
        return self._update(
            obj, status_only=False, lease_guard=lease_guard
        )

    def update_status(self, obj: Resource, *, lease_guard=None) -> Resource:
        return self._update(
            obj, status_only=True, lease_guard=lease_guard
        )

    def _update(
        self, obj: Resource, *, status_only: bool, lease_guard=None
    ) -> Resource:
        with self._dispatch_lock:
            self._check_lease_guard(lease_guard, obj.kind)
            try:
                stored = self._store.update(
                    obj.to_dict(), status_only=status_only
                )
            except core.StoreError as e:
                raise self._translate(e) from None
            self._drain_events()
            return self._committed(stored)

    def delete(
        self,
        kind: str,
        name: str,
        namespace: str = "default",
        *,
        lease_guard=None,
    ) -> None:
        with self._dispatch_lock:
            self._check_lease_guard(lease_guard, kind)
            try:
                self._store.delete(kind, namespace, name)
            except core.StoreError as e:
                raise self._translate(e) from None
            self._drain_events()

    # -- conveniences (same contracts as FakeApiServer) -------------------

    def apply(self, obj: Resource, *, lease_guard=None) -> Resource:
        try:
            current = self.get(
                obj.kind, obj.metadata.name, obj.metadata.namespace
            )
        except NotFound:
            return self.create(obj, lease_guard=lease_guard)
        obj = self._admit(obj)
        if (
            current.spec == obj.spec
            and current.metadata.labels == obj.metadata.labels
            and current.metadata.annotations == obj.metadata.annotations
        ):
            return current
        merged = obj.deepcopy()
        merged.metadata.resource_version = current.metadata.resource_version
        merged.metadata.uid = current.metadata.uid
        return self.update(merged, lease_guard=lease_guard)

    def record_event(
        self,
        about: Resource,
        reason: str,
        message: str,
        *,
        type_: str = "Normal",
    ) -> Resource:
        from kubeflow_tpu.testing.fake_apiserver import event_resource

        ev = event_resource(about, reason, message, type_=type_)
        try:
            return self.create(ev)
        except AlreadyExists:
            return self.get(
                "Event", ev.metadata.name, about.metadata.namespace
            )

    # -- facade parity -----------------------------------------------------
    #
    # Drop-in for FakeApiServer means drop-in BEHIND THE FACADE and under
    # the controller runtime too: the HTTP app calls convert_to for
    # `?version=` reads, run_until_idle calls flush() as its dispatch
    # barrier, and the CLI's kind disambiguation asks kinds(). The chaos
    # soak is the first suite to drive this backend as the spine rather
    # than a parity exhibit, and these are the seams it crossed.

    def convert_to(self, obj: Resource, version: str) -> Resource:
        """Read-side conversion at a served version — the same
        versioning registry FakeApiServer consults."""
        from kubeflow_tpu.api import versioning
        from kubeflow_tpu.testing.fake_apiserver import Invalid

        try:
            return versioning.registry.convert(obj, version)
        except versioning.ConversionError as e:
            raise Invalid(str(e)) from e

    def kinds(self) -> list[str]:
        """Distinct kinds with live objects (quota's count/<resource>
        inverse — same contract as FakeApiServer.kinds), served from the
        snapshot mirror (empty kinds are pruned on delete)."""
        with self._mirror_lock:
            return self._mirror.kinds()

    def flush(self, timeout: float = 30.0) -> None:
        """Dispatch barrier. Watch delivery on this backend is
        synchronous with the mutating call (see _drain_events), so by
        the time any mutator returns, its events have been handled —
        the barrier is trivially satisfied."""

    def checkpoint(self) -> None:
        """No durable tier on this backend (the WAL lives in the Python
        store); a no-op keeps shutdown paths backend-agnostic."""

    def close(self) -> None:
        """See checkpoint()."""
