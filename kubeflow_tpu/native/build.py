"""On-demand cmake+ninja build of the native tier (native/).

Shared by all ctypes bindings: one cmake project produces every shared
library (scheduler, control-plane core). No packaging step, no pybind11
(not in the image) — the C ABI plus ctypes is the binding layer.
"""

from __future__ import annotations

import ctypes
import subprocess
import threading
from pathlib import Path

_REPO = Path(__file__).resolve().parent.parent.parent
_NATIVE = _REPO / "native"
_BUILD = _NATIVE / "build"
_build_lock = threading.Lock()


def ensure_built(lib_name: str) -> Path:
    """Build (if stale) and return the path to native/build/<lib_name>."""
    lib = _BUILD / lib_name
    # _build_lock exists to serialize exactly these cmake invocations
    # (two racing builders corrupt the ninja state); the subprocess IS
    # the critical section, and nothing else ever takes this lock.
    with _build_lock:
        sources = list((_NATIVE / "src").glob("*.cc")) + [
            _NATIVE / "CMakeLists.txt"
        ]
        src_newest = max(p.stat().st_mtime for p in sources)
        if not lib.exists() or lib.stat().st_mtime < src_newest:
            subprocess.run(  # kftpu-lint: disable=blocking-under-lock
                ["cmake", "-S", str(_NATIVE), "-B", str(_BUILD), "-G",
                 "Ninja"],
                check=True, capture_output=True,
            )
            subprocess.run(  # kftpu-lint: disable=blocking-under-lock
                ["cmake", "--build", str(_BUILD)],
                check=True, capture_output=True,
            )
    return lib


_libs: dict[str, ctypes.CDLL] = {}
_libs_lock = threading.Lock()


def load(lib_name: str, configure) -> ctypes.CDLL:
    """Load a native library once per process; `configure(lib)` declares
    the C ABI (argtypes/restypes) on first load.

    The cmake build runs OUTSIDE `_libs_lock` (a cold-cache build takes
    seconds; holding the cache lock over it would stall every other
    library's `load`). Two racing first-loaders may both CDLL the same
    library; the insert is double-checked so exactly one wins, and a
    duplicate CDLL handle of the same .so is harmless."""
    with _libs_lock:
        cached = _libs.get(lib_name)
    if cached is not None:
        return cached
    built = ensure_built(lib_name)
    fresh = ctypes.CDLL(str(built))
    configure(fresh)
    with _libs_lock:
        cached = _libs.get(lib_name)
        if cached is None:
            _libs[lib_name] = cached = fresh
        return cached
