"""ctypes bindings for the native control-plane core (libkftpu_core):

- ``WorkQueue`` — rate-limited delaying workqueue (workqueue.cc), the
  compiled equivalent of the client-go workqueue every reference
  controller rides (`notebook_controller.go:82` via controller-runtime).
- ``NativeStore`` — JSON-object store with K8s storage semantics
  (store.cc): resourceVersion concurrency, spec/status surfaces, label
  selectors, finalizers, owner-ref cascade, watch journal.

Blocking calls (``WorkQueue.get``) park in native code — ctypes releases
the GIL for the duration, so Python worker threads cost nothing while
idle.
"""

from __future__ import annotations

import ctypes
import json as _json

from kubeflow_tpu.native.build import load

# store.h status codes
STORE_OK = 0
STORE_NOT_FOUND = -1
STORE_ALREADY_EXISTS = -2
STORE_CONFLICT = -3
STORE_BAD_OBJECT = -4


def _configure(lib: ctypes.CDLL) -> None:
    P, S, I32, I64 = (ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32,
                      ctypes.c_int64)
    lib.kftpu_wq_new.restype = P
    lib.kftpu_wq_new.argtypes = [I64, I64]
    lib.kftpu_wq_free.argtypes = [P]
    lib.kftpu_wq_add.argtypes = [P, S]
    lib.kftpu_wq_add_after.argtypes = [P, S, I64]
    lib.kftpu_wq_get.restype = I32
    lib.kftpu_wq_get.argtypes = [P, ctypes.c_char_p, I32, I64]
    lib.kftpu_wq_done.argtypes = [P, S]
    lib.kftpu_wq_requeue_error.restype = I64
    lib.kftpu_wq_requeue_error.argtypes = [P, S]
    lib.kftpu_wq_forget.argtypes = [P, S]
    lib.kftpu_wq_len.restype = I64
    lib.kftpu_wq_len.argtypes = [P]
    lib.kftpu_wq_next_ready_ms.restype = I64
    lib.kftpu_wq_next_ready_ms.argtypes = [P]
    lib.kftpu_wq_shutdown.argtypes = [P]

    lib.kftpu_store_new.restype = P
    lib.kftpu_store_free.argtypes = [P]
    lib.kftpu_store_create.restype = S
    lib.kftpu_store_create.argtypes = [P, S]
    lib.kftpu_store_get.restype = S
    lib.kftpu_store_get.argtypes = [P, S, S, S]
    lib.kftpu_store_update.restype = S
    lib.kftpu_store_update.argtypes = [P, S, I32]
    lib.kftpu_store_list.restype = S
    lib.kftpu_store_list.argtypes = [P, S, S, S]
    lib.kftpu_store_delete.restype = I32
    lib.kftpu_store_delete.argtypes = [P, S, S, S]
    lib.kftpu_store_events.restype = S
    lib.kftpu_store_events.argtypes = [P, I64, ctypes.POINTER(I64)]
    lib.kftpu_store_trim.argtypes = [P, I64]
    lib.kftpu_store_len.restype = I64
    lib.kftpu_store_len.argtypes = [P]
    lib.kftpu_store_status.restype = I32
    lib.kftpu_store_error.restype = S

    lib.kftpu_wal_open.restype = P
    lib.kftpu_wal_open.argtypes = [S]
    lib.kftpu_wal_free.argtypes = [P]
    lib.kftpu_wal_append.restype = I32
    lib.kftpu_wal_append.argtypes = [P, S]
    lib.kftpu_wal_snapshot.restype = I32
    lib.kftpu_wal_snapshot.argtypes = [P, S]
    lib.kftpu_wal_read_snapshot.restype = S
    lib.kftpu_wal_read_snapshot.argtypes = [P]
    lib.kftpu_wal_read_journal.restype = S
    lib.kftpu_wal_read_journal.argtypes = [P]
    lib.kftpu_wal_error.restype = S


def _lib() -> ctypes.CDLL:
    return load("libkftpu_core.so", _configure)


class WorkQueue:
    """Keyed, deduping, delaying, rate-limited workqueue (native)."""

    _KEY_BUF = 4096

    def __init__(self, base_backoff: float = 0.02, max_backoff: float = 30.0):
        self._lib = _lib()
        self._handle = self._lib.kftpu_wq_new(
            max(1, int(base_backoff * 1000)), max(1, int(max_backoff * 1000))
        )

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.kftpu_wq_free(handle)
            self._handle = None

    def add(self, key: str, *, after: float = 0.0) -> None:
        if after > 0:
            self._lib.kftpu_wq_add_after(
                self._handle, key.encode(), int(after * 1000)
            )
        else:
            self._lib.kftpu_wq_add(self._handle, key.encode())

    def get(self, timeout: float = 0.0) -> str | None:
        """Dequeue a ready key (None on timeout). timeout=0 polls. The
        caller must balance with done()."""
        buf = ctypes.create_string_buffer(self._KEY_BUF)
        rc = self._lib.kftpu_wq_get(
            self._handle, buf, len(buf), int(timeout * 1000)
        )
        if rc == 1:
            return buf.value.decode()
        if rc == -2:
            raise ValueError("key exceeds buffer")
        return None

    def done(self, key: str) -> None:
        self._lib.kftpu_wq_done(self._handle, key.encode())

    def requeue_error(self, key: str) -> float:
        """Schedule an exponential-backoff retry; returns the delay (s)."""
        return self._lib.kftpu_wq_requeue_error(
            self._handle, key.encode()
        ) / 1000.0

    def forget(self, key: str) -> None:
        self._lib.kftpu_wq_forget(self._handle, key.encode())

    def __len__(self) -> int:
        return int(self._lib.kftpu_wq_len(self._handle))

    def next_ready_in(self) -> float | None:
        """Seconds until the earliest pending key matures; None if empty."""
        ms = self._lib.kftpu_wq_next_ready_ms(self._handle)
        return None if ms < 0 else ms / 1000.0

    def shutdown(self) -> None:
        self._lib.kftpu_wq_shutdown(self._handle)


class StoreError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code


class NativeStore:
    """Low-level dict-in/dict-out wrapper over the C++ store."""

    def __init__(self):
        self._lib = _lib()
        self._handle = self._lib.kftpu_store_new()

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.kftpu_store_free(handle)
            self._handle = None

    def _raise(self) -> None:
        code = self._lib.kftpu_store_status()
        msg = (self._lib.kftpu_store_error() or b"").decode()
        raise StoreError(code, msg)

    def _ok(self, out: bytes | None) -> dict | list:
        if out is None:
            self._raise()
        return _json.loads(out.decode())

    def create(self, obj: dict) -> dict:
        return self._ok(
            self._lib.kftpu_store_create(
                self._handle, _json.dumps(obj).encode()
            )
        )

    def get(self, kind: str, namespace: str, name: str) -> dict:
        return self._ok(
            self._lib.kftpu_store_get(
                self._handle, kind.encode(), namespace.encode(), name.encode()
            )
        )

    def update(self, obj: dict, *, status_only: bool = False) -> dict:
        return self._ok(
            self._lib.kftpu_store_update(
                self._handle, _json.dumps(obj).encode(), 1 if status_only else 0
            )
        )

    def list(
        self,
        kind: str,
        namespace: str | None = None,
        label_selector: dict[str, str] | None = None,
    ) -> list[dict]:
        return self._ok(
            self._lib.kftpu_store_list(
                self._handle,
                kind.encode(),
                # None = all namespaces (NULL at the ABI); "" = exactly
                # the cluster scope — the two must stay distinct or
                # list("Lease", namespace="") returns every tenant's
                # leases (FakeApiServer parity).
                None if namespace is None else namespace.encode(),
                _json.dumps(label_selector).encode() if label_selector else None,
            )
        )

    def delete(self, kind: str, namespace: str, name: str) -> None:
        rc = self._lib.kftpu_store_delete(
            self._handle, kind.encode(), namespace.encode(), name.encode()
        )
        if rc != STORE_OK:
            self._raise()

    def events(self, cursor: int) -> tuple[list[dict], int]:
        """Journal entries with seq > cursor, and the new cursor."""
        new_cursor = ctypes.c_int64(cursor)
        out = self._lib.kftpu_store_events(
            self._handle, cursor, ctypes.byref(new_cursor)
        )
        return self._ok(out), new_cursor.value

    def trim(self, cursor: int) -> None:
        self._lib.kftpu_store_trim(self._handle, cursor)

    def __len__(self) -> int:
        return int(self._lib.kftpu_store_len(self._handle))


class WalError(Exception):
    pass


class NativeWal:
    """Durable WAL+snapshot directory (wal.cc): fsync'd appends, atomic
    snapshot replacement. The compiled persistence tier FakeApiServer
    stores through (the reference's equivalent durability comes from
    etcd, `profile-controller/controllers/suite_test.go:29-54`)."""

    def __init__(self, directory: str):
        import os

        self._lib = _lib()
        # wal.cc creates the leaf directory only; deep paths are the
        # caller's concern — make them here so both backends accept them.
        os.makedirs(str(directory), mode=0o700, exist_ok=True)
        self._handle = self._lib.kftpu_wal_open(str(directory).encode())
        if not self._handle:
            raise WalError(
                (self._lib.kftpu_wal_error() or b"").decode()
                or f"cannot open wal dir {directory!r}"
            )

    def close(self) -> None:
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.kftpu_wal_free(handle)
            self._handle = None

    __del__ = close

    def _check(self, rc: int) -> None:
        if rc != 0:
            raise WalError((self._lib.kftpu_wal_error() or b"").decode())

    def append(self, line: str) -> None:
        self._check(self._lib.kftpu_wal_append(self._handle, line.encode()))

    def snapshot(self, text: str) -> None:
        self._check(self._lib.kftpu_wal_snapshot(self._handle, text.encode()))

    def read_snapshot(self) -> str:
        out = self._lib.kftpu_wal_read_snapshot(self._handle)
        if out is None:
            raise WalError((self._lib.kftpu_wal_error() or b"").decode())
        return out.decode()

    def read_journal(self) -> str:
        out = self._lib.kftpu_wal_read_journal(self._handle)
        if out is None:
            raise WalError((self._lib.kftpu_wal_error() or b"").decode())
        return out.decode()
