"""ctypes bindings for the native data plane (libkftpu_data).

Record files (fixed-size records — static shapes, which is exactly what
XLA wants) plus a compiled multithreaded prefetching loader. The blocking
``next`` call parks in native code (ctypes releases the GIL), so host IO
overlaps device compute in the training loop.
"""

from __future__ import annotations

import ctypes

import numpy as np

from kubeflow_tpu.native.build import load


def _configure(lib: ctypes.CDLL) -> None:
    P, S, I32, I64, U64 = (ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32,
                           ctypes.c_int64, ctypes.c_uint64)
    lib.kftpu_recwriter_open.restype = P
    lib.kftpu_recwriter_open.argtypes = [S, U64]
    lib.kftpu_recwriter_append.restype = I32
    lib.kftpu_recwriter_append.argtypes = [P, ctypes.c_void_p]
    lib.kftpu_recwriter_close.restype = I64
    lib.kftpu_recwriter_close.argtypes = [P]
    lib.kftpu_recfile_stat.restype = I32
    lib.kftpu_recfile_stat.argtypes = [S, ctypes.POINTER(U64),
                                       ctypes.POINTER(U64)]
    lib.kftpu_loader_new.restype = P
    lib.kftpu_loader_new.argtypes = [S, I64, I32, I32, I64, U64, I32, I32,
                                     I32, I32]
    lib.kftpu_loader_free.argtypes = [P]
    lib.kftpu_loader_record_bytes.restype = U64
    lib.kftpu_loader_record_bytes.argtypes = [P]
    lib.kftpu_loader_shard_records.restype = I64
    lib.kftpu_loader_shard_records.argtypes = [P]
    lib.kftpu_loader_next.restype = I64
    lib.kftpu_loader_next.argtypes = [P, ctypes.c_void_p]
    lib.kftpu_loader_batches.restype = I64
    lib.kftpu_loader_batches.argtypes = [P]


def _lib() -> ctypes.CDLL:
    return load("libkftpu_data.so", _configure)


class RecordWriter:
    """Writes fixed-size records; finalizes the header on close."""

    def __init__(self, path: str, record_bytes: int):
        self._lib = _lib()
        self._handle = self._lib.kftpu_recwriter_open(
            str(path).encode(), record_bytes
        )
        if not self._handle:
            raise OSError(f"cannot create record file {path!r}")
        self.record_bytes = record_bytes
        self.count = 0

    def append(self, data: bytes | np.ndarray) -> None:
        buf = np.frombuffer(
            data.tobytes() if isinstance(data, np.ndarray) else data,
            dtype=np.uint8,
        )
        if buf.nbytes != self.record_bytes:
            raise ValueError(
                f"record is {buf.nbytes} bytes, expected {self.record_bytes}"
            )
        rc = self._lib.kftpu_recwriter_append(
            self._handle, buf.ctypes.data_as(ctypes.c_void_p)
        )
        if rc != 0:
            raise OSError("record append failed")
        self.count += 1

    def close(self) -> int:
        if self._handle:
            n = self._lib.kftpu_recwriter_close(self._handle)
            self._handle = None
            if n < 0:
                raise OSError("record file finalize failed")
            return int(n)
        return self.count

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def stat_record_file(path: str) -> tuple[int, int]:
    """(record_bytes, record_count) of a record file."""
    rb, rc = ctypes.c_uint64(), ctypes.c_uint64()
    if _lib().kftpu_recfile_stat(
        str(path).encode(), ctypes.byref(rb), ctypes.byref(rc)
    ) != 0:
        raise OSError(f"not a record file: {path!r}")
    return int(rb.value), int(rc.value)


class RecordLoader:
    """Compiled prefetching loader over one or more record files.

    Yields (batch_bytes, n_records) — raw uint8 arrays of shape
    [batch_size, record_bytes]; typed decoding lives a layer up
    (`kubeflow_tpu.train.records`)."""

    def __init__(
        self,
        paths: list[str] | str,
        batch_size: int,
        *,
        shard_id: int = 0,
        shards: int = 1,
        shuffle_buffer: int = 0,
        seed: int = 0,
        num_threads: int = 4,
        prefetch: int = 2,
        drop_remainder: bool = True,
        epochs: int = 0,
    ):
        if isinstance(paths, str):
            paths = [paths]
        self._lib = _lib()
        self._handle = self._lib.kftpu_loader_new(
            ";".join(str(p) for p in paths).encode(),
            batch_size, shard_id, shards, shuffle_buffer, seed,
            num_threads, prefetch, 1 if drop_remainder else 0, epochs,
        )
        if not self._handle:
            raise ValueError(
                f"cannot open loader over {paths!r} (missing file, "
                "mismatched record sizes, or bad sharding args)"
            )
        self.batch_size = batch_size
        self.record_bytes = int(
            self._lib.kftpu_loader_record_bytes(self._handle)
        )

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.kftpu_loader_free(handle)
            self._handle = None

    @property
    def shard_records(self) -> int:
        return int(self._lib.kftpu_loader_shard_records(self._handle))

    @property
    def batches_delivered(self) -> int:
        return int(self._lib.kftpu_loader_batches(self._handle))

    def next(self) -> tuple[np.ndarray, int] | None:
        """One batch, or None at end of data. Blocks without the GIL."""
        out = np.empty((self.batch_size, self.record_bytes), dtype=np.uint8)
        n = self._lib.kftpu_loader_next(
            self._handle, out.ctypes.data_as(ctypes.c_void_p)
        )
        if n < 0:
            raise OSError("native loader IO failure")
        if n == 0:
            return None
        return out, int(n)

    def __iter__(self):
        while True:
            item = self.next()
            if item is None:
                return
            yield item
