"""ctypes bindings for the native gang scheduler (native/src/scheduler.cc).

The shared library is built on demand with cmake+ninja into native/build —
no packaging step, no pybind11 (not in the image); the C ABI plus ctypes is
the binding layer.
"""

from __future__ import annotations

import ctypes

from kubeflow_tpu.native.build import load


class PlacementError(RuntimeError):
    pass


def _configure(lib: ctypes.CDLL) -> None:
    lib.kftpu_sched_new.restype = ctypes.c_void_p
    lib.kftpu_sched_free.argtypes = [ctypes.c_void_p]
    lib.kftpu_sched_add_node.restype = ctypes.c_int32
    lib.kftpu_sched_add_node.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
    ]
    lib.kftpu_sched_remove_node.restype = ctypes.c_int32
    lib.kftpu_sched_remove_node.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
    ]
    lib.kftpu_sched_set_pool_topology.restype = ctypes.c_int32
    lib.kftpu_sched_set_pool_topology.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32, ctypes.c_int32,
    ]
    lib.kftpu_sched_place_gang.restype = ctypes.c_int64
    lib.kftpu_sched_place_gang.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_char_p,
        ctypes.c_int32,
    ]
    lib.kftpu_sched_release_gang.restype = ctypes.c_int32
    lib.kftpu_sched_release_gang.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
    ]
    lib.kftpu_sched_reserve.restype = ctypes.c_int32
    lib.kftpu_sched_reserve.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_int32,
    ]
    lib.kftpu_sched_free_chips.restype = ctypes.c_int64
    lib.kftpu_sched_free_chips.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
    ]


class GangScheduler:
    """Topology-aware, all-or-nothing gang placement (native-backed)."""

    def __init__(self):
        self._lib = load("libkftpu_sched.so", _configure)
        self._handle = self._lib.kftpu_sched_new()

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.kftpu_sched_free(handle)
            self._handle = None

    def add_node(
        self, name: str, pool: str, *, x: int = 0, y: int = 0, chips: int = 4
    ) -> None:
        rc = self._lib.kftpu_sched_add_node(
            self._handle, name.encode(), pool.encode(), x, y, chips
        )
        if rc != 0:
            raise PlacementError(f"node {name!r} already registered")

    def remove_node(self, name: str) -> bool:
        return (
            self._lib.kftpu_sched_remove_node(self._handle, name.encode()) == 0
        )

    def set_pool_topology(self, pool: str, width: int, height: int) -> None:
        """Declare `pool` as a width x height 2D TORUS: ring cost then
        uses per-axis wraparound distance (min(d, size-d)) — real v5e
        pod slices wrap their ICI links, so a ring crossing the seam is
        one hop, not width-1. 0/1 on an axis = no wrap there."""
        rc = self._lib.kftpu_sched_set_pool_topology(
            self._handle, pool.encode(), width, height
        )
        if rc != 0:
            raise PlacementError(
                f"bad topology {width}x{height} for pool {pool!r}"
            )

    def place_gang(
        self, job: str, pool: str, workers: int, chips_per_worker: int
    ) -> tuple[list[str], int]:
        """Returns (node per rank, ring cost). Raises PlacementError if the
        pool cannot hold the whole gang (nothing is reserved)."""
        buf = ctypes.create_string_buffer(64 * max(1, workers) + 64)
        cost = self._lib.kftpu_sched_place_gang(
            self._handle, job.encode(), pool.encode(), workers,
            chips_per_worker, buf, len(buf),
        )
        if cost == -1:
            raise PlacementError(
                f"pool {pool!r} lacks capacity for {workers}x"
                f"{chips_per_worker} chips"
            )
        if cost < 0:
            raise PlacementError(f"placement failed (code {cost}) for {job!r}")
        return buf.value.decode().split(";"), int(cost)

    def reserve(self, job: str, node: str, chips: int) -> bool:
        """Record an observed placement (rebuilding state from pods)."""
        return (
            self._lib.kftpu_sched_reserve(
                self._handle, job.encode(), node.encode(), chips
            )
            == 0
        )

    def release_gang(self, job: str) -> int:
        n = self._lib.kftpu_sched_release_gang(self._handle, job.encode())
        return max(0, n)

    def free_chips(self, pool: str) -> int:
        return int(
            self._lib.kftpu_sched_free_chips(self._handle, pool.encode())
        )
