"""ctypes bindings for the native gang scheduler (native/src/scheduler.cc).

The shared library is built on demand with cmake+ninja into native/build —
no packaging step, no pybind11 (not in the image); the C ABI plus ctypes is
the binding layer.
"""

from __future__ import annotations

import ctypes

from kubeflow_tpu.native.build import load


class PlacementError(RuntimeError):
    pass


def _configure(lib: ctypes.CDLL) -> None:
    lib.kftpu_sched_new.restype = ctypes.c_void_p
    lib.kftpu_sched_free.argtypes = [ctypes.c_void_p]
    lib.kftpu_sched_add_node.restype = ctypes.c_int32
    lib.kftpu_sched_add_node.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
    ]
    lib.kftpu_sched_remove_node.restype = ctypes.c_int32
    lib.kftpu_sched_remove_node.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
    ]
    lib.kftpu_sched_set_pool_topology.restype = ctypes.c_int32
    lib.kftpu_sched_set_pool_topology.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32, ctypes.c_int32,
    ]
    lib.kftpu_sched_place_gang.restype = ctypes.c_int64
    lib.kftpu_sched_place_gang.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_char_p,
        ctypes.c_int32,
    ]
    lib.kftpu_sched_release_gang.restype = ctypes.c_int32
    lib.kftpu_sched_release_gang.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
    ]
    lib.kftpu_sched_reserve.restype = ctypes.c_int32
    lib.kftpu_sched_reserve.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_int32,
    ]
    lib.kftpu_sched_free_chips.restype = ctypes.c_int64
    lib.kftpu_sched_free_chips.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
    ]


class GangScheduler:
    """Topology-aware, all-or-nothing gang placement (native-backed)."""

    def __init__(self):
        self._lib = load("libkftpu_sched.so", _configure)
        self._handle = self._lib.kftpu_sched_new()

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.kftpu_sched_free(handle)
            self._handle = None

    def add_node(
        self, name: str, pool: str, *, x: int = 0, y: int = 0, chips: int = 4
    ) -> None:
        rc = self._lib.kftpu_sched_add_node(
            self._handle, name.encode(), pool.encode(), x, y, chips
        )
        if rc != 0:
            raise PlacementError(f"node {name!r} already registered")

    def remove_node(self, name: str) -> bool:
        return (
            self._lib.kftpu_sched_remove_node(self._handle, name.encode()) == 0
        )

    def set_pool_topology(self, pool: str, width: int, height: int) -> None:
        """Declare `pool` as a width x height 2D TORUS: ring cost then
        uses per-axis wraparound distance (min(d, size-d)) — real v5e
        pod slices wrap their ICI links, so a ring crossing the seam is
        one hop, not width-1. 0/1 on an axis = no wrap there."""
        rc = self._lib.kftpu_sched_set_pool_topology(
            self._handle, pool.encode(), width, height
        )
        if rc != 0:
            raise PlacementError(
                f"bad topology {width}x{height} for pool {pool!r}"
            )

    def place_gang(
        self, job: str, pool: str, workers: int, chips_per_worker: int
    ) -> tuple[list[str], int]:
        """Returns (node per rank, ring cost). Raises PlacementError if the
        pool cannot hold the whole gang (nothing is reserved)."""
        buf = ctypes.create_string_buffer(64 * max(1, workers) + 64)
        cost = self._lib.kftpu_sched_place_gang(
            self._handle, job.encode(), pool.encode(), workers,
            chips_per_worker, buf, len(buf),
        )
        if cost == -1:
            raise PlacementError(
                f"pool {pool!r} lacks capacity for {workers}x"
                f"{chips_per_worker} chips"
            )
        if cost < 0:
            raise PlacementError(f"placement failed (code {cost}) for {job!r}")
        return buf.value.decode().split(";"), int(cost)

    def reserve(self, job: str, node: str, chips: int) -> bool:
        """Record an observed placement (rebuilding state from pods)."""
        return (
            self._lib.kftpu_sched_reserve(
                self._handle, job.encode(), node.encode(), chips
            )
            == 0
        )

    def release_gang(self, job: str) -> int:
        n = self._lib.kftpu_sched_release_gang(self._handle, job.encode())
        return max(0, n)

    def free_chips(self, pool: str) -> int:
        return int(
            self._lib.kftpu_sched_free_chips(self._handle, pool.encode())
        )


class PyGangScheduler:
    """Pure-Python twin of the native scheduler with IDENTICAL semantics
    — same serpentine slot order, same torus ring-cost minimization,
    same tie-breaking — pinned by the golden parity test
    (tests/test_native_scheduler.py). Exists so (a) environments without
    the native toolchain still gang-schedule correctly and (b) the
    compiled path has an executable specification to diff against
    (the _PyWorkQueue pattern, controllers/runtime.py)."""

    def __init__(self):
        # name -> [pool, x, y, chips, reserved]
        self._nodes: dict[str, list] = {}
        self._gangs: dict[str, list[tuple[str, int]]] = {}
        self._pool_topo: dict[str, tuple[int, int]] = {}

    def add_node(self, name, pool, *, x=0, y=0, chips=4) -> None:
        if chips < 0:
            raise PlacementError(f"node {name!r}: negative chips {chips}")
        if name in self._nodes:
            raise PlacementError(f"node {name!r} already registered")
        self._nodes[name] = [pool, x, y, chips, 0]

    def remove_node(self, name) -> bool:
        return self._nodes.pop(name, None) is not None

    def set_pool_topology(self, pool, width, height) -> None:
        if width < 0 or height < 0:
            raise PlacementError(
                f"bad topology {width}x{height} for pool {pool!r}"
            )
        self._pool_topo[pool] = (width, height)

    def _dist(self, a: str, b: str) -> int:
        pool, ax, ay, _, _ = self._nodes[a]
        _, bx, by, _, _ = self._nodes[b]
        w, h = self._pool_topo.get(pool, (0, 0))

        def axis(d, size):
            d = abs(d)
            if size > 1:
                d %= size
                return min(d, size - d)
            return d

        return axis(ax - bx, w) + axis(ay - by, h)

    def place_gang(self, job, pool, workers, chips_per_worker):
        if workers <= 0 or chips_per_worker < 0 or job in self._gangs:
            raise PlacementError(f"placement failed (code -3) for {job!r}")
        pool_nodes = sorted(
            (name for name, n in self._nodes.items() if n[0] == pool),
            key=lambda name: (
                self._nodes[name][2],
                (-self._nodes[name][1] if self._nodes[name][2] & 1
                 else self._nodes[name][1]),
                name,
            ),
        )
        slots: list[str] = []
        for name in pool_nodes:
            _, _, _, chips, reserved = self._nodes[name]
            cap = (
                (workers if chips >= reserved else 0)
                if chips_per_worker == 0
                else (chips - reserved) // chips_per_worker
            )
            for _ in range(cap):
                if len(slots) >= workers * 2 + 1024:
                    break
                slots.append(name)
        if len(slots) < workers:
            raise PlacementError(
                f"pool {pool!r} lacks capacity for {workers}x"
                f"{chips_per_worker} chips"
            )
        best_cost, best_start = -1, 0
        for start in range(len(slots) - workers + 1):
            cost = sum(
                self._dist(slots[start + i - 1], slots[start + i])
                for i in range(1, workers)
            )
            if best_cost < 0 or cost < best_cost:
                best_cost, best_start = cost, start
        assignment = slots[best_start:best_start + workers]
        gang = self._gangs.setdefault(job, [])
        for name in assignment:
            self._nodes[name][4] += chips_per_worker
            gang.append((name, chips_per_worker))
        return assignment, int(best_cost)

    def reserve(self, job, node, chips) -> bool:
        n = self._nodes.get(node)
        if n is None or chips < 0:
            return False
        n[4] += chips
        self._gangs.setdefault(job, []).append((node, chips))
        return True

    def release_gang(self, job) -> int:
        gang = self._gangs.pop(job, None)
        if gang is None:
            return 0
        for node, chips in gang:
            if node in self._nodes:
                self._nodes[node][4] -= chips
        return len(gang)

    def free_chips(self, pool) -> int:
        return sum(
            max(0, n[3] - n[4])
            for n in self._nodes.values()
            if n[0] == pool
        )


def make_gang_scheduler():
    """Native scheduler when the toolchain is available, else the Python
    twin — same contract either way (the make_workqueue pattern)."""
    try:
        return GangScheduler()
    except Exception:
        import logging

        logging.getLogger(__name__).warning(
            "native scheduler unavailable; using Python twin"
        )
        return PyGangScheduler()
