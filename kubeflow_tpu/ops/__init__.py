"""Compute ops: attention implementations and (Pallas) kernels.

Every op here has a portable jnp reference implementation (used on CPU test
meshes and as the correctness oracle) and, where it pays, a TPU-optimized
path — shard_map collectives for cross-chip ops, Pallas kernels for on-chip
hot loops.
"""

from kubeflow_tpu.ops.attention import dense_attention, ring_attention
