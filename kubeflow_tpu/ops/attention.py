"""Attention: dense reference and ring (sequence-parallel) implementation.

Long context is first-class here where the reference had nothing (SURVEY.md
§5 "Long-context / sequence parallelism: Absent"). The design is blockwise
ring attention: the sequence axis is sharded over the mesh's `sp` axis; K/V
chunks rotate around the sp ring via `ppermute` (nearest-neighbor ICI hops)
while each device's Q stays put, and softmax is accumulated online
(flash-attention style running max/sum) so no device ever materializes the
full [S, S] score matrix or the full K/V.

Memory per device: O(S/n · S/n) scores, O(S/n) K/V — sequence length scales
linearly with the sp ring size.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from kubeflow_tpu.parallel.collectives import axis_size
from kubeflow_tpu.parallel.sharding import batch_axes


def dense_attention(q, k, v, *, causal: bool = True):
    """Reference attention. q,k,v: [B, S, H, D] (or [B,S,G,H,D] grouped)."""
    d = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(d)
    if causal:
        s_q, s_k = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((s_q, s_k), bool), k=s_k - s_q)
        scores = jnp.where(mask, scores, -jnp.inf)
    weights = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", weights.astype(q.dtype), v)


def _ring_body(q, k, v, *, axis: str, causal: bool):
    """Per-shard ring attention. q,k,v: local [B, C, H, D] chunks.

    The ring has a static size, so the loop is unrolled at trace time:
    the step index is static (letting the causal mask specialize per hop)
    and the final hop skips its rotation — n-1 ppermutes, not n.
    """
    n = axis_size(axis)
    my = lax.axis_index(axis)
    b, c, h, d = q.shape
    scale = 1.0 / math.sqrt(d)
    q32 = q.astype(jnp.float32)

    q_pos = my * c + lax.broadcasted_iota(jnp.int32, (c, c), 0)

    o = jnp.zeros((b, c, h, d), jnp.float32)
    m = jnp.full((b, h, c), -jnp.inf, jnp.float32)
    l = jnp.zeros((b, h, c), jnp.float32)
    k_cur, v_cur = k, v
    for i in range(n):
        src = (my - i) % n  # ring position this K/V chunk originated from

        def accumulate(o, m, l, k_blk=k_cur, v_blk=v_cur, src_=src):
            s = jnp.einsum(
                "bqhd,bkhd->bhqk", q32, k_blk.astype(jnp.float32)
            ) * scale
            if causal:
                k_pos = src_ * c + lax.broadcasted_iota(
                    jnp.int32, (c, c), 1
                )
                s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
            m_blk = jnp.max(s, axis=-1)
            m_new = jnp.maximum(m, m_blk)
            # Rows with no unmasked key yet keep m=-inf; exp(-inf - -inf)
            # is nan, so guard the correction factor.
            corr = jnp.where(m == -jnp.inf, 0.0, jnp.exp(m - m_new))
            p = jnp.exp(s - m_new[..., None])
            p = jnp.where(jnp.isfinite(s), p, 0.0)
            l_new = l * corr + jnp.sum(p, axis=-1)
            pv = jnp.einsum(
                "bhqk,bkhd->bqhd", p, v_blk.astype(jnp.float32)
            )
            o_new = o * corr.transpose(0, 2, 1)[..., None] + pv
            return o_new, m_new, l_new

        if causal:
            # A K/V chunk from a LATER ring position is entirely masked
            # for this device's queries — skip both einsums (half the
            # ring's attention FLOPs on average). Devices legitimately
            # diverge here: the cond body has no collectives, the
            # rotation below is unconditional.
            o, m, l = lax.cond(
                src <= my, accumulate, lambda o, m, l: (o, m, l), o, m, l
            )
        else:
            o, m, l = accumulate(o, m, l)
        if i + 1 < n:
            k_cur = _rotate(k_cur, axis, n)
            v_cur = _rotate(v_cur, axis, n)
    l = jnp.where(l == 0.0, 1.0, l)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def _rotate(x, axis: str, n: int):
    return lax.ppermute(x, axis, perm=[(i, (i + 1) % n) for i in range(n)])


def ring_attention(
    q,
    k,
    v,
    mesh: Mesh,
    *,
    causal: bool = True,
    sp_axis: str = "sp",
    heads_axis: str | None = "tp",
):
    """Sequence-parallel attention over `mesh`'s sp ring.

    q,k,v: global [B, S, H, D]; S must divide by the sp ring size, H by the
    tp size. Falls back to dense attention when the ring is trivial.
    """
    if mesh.shape.get(sp_axis, 1) == 1:
        return dense_attention(q, k, v, causal=causal)

    ring = mesh.shape[sp_axis]
    if q.shape[1] % ring:
        raise ValueError(
            f"ring attention requires the sequence length ({q.shape[1]}) to "
            f"be divisible by the {sp_axis!r} ring size ({ring})"
        )
    bspec = batch_axes(mesh)
    spec = P(bspec, sp_axis, heads_axis, None)
    body = functools.partial(_ring_body, axis=sp_axis, causal=causal)
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_rep=False,
    )(q, k, v)
