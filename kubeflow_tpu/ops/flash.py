"""Flash attention as a Pallas TPU kernel (forward + backward).

The reference has no kernels at all — its device-level compute lives inside
third-party containers (SURVEY.md §2.1). On TPU the hot op of the flagship
transformer is attention, and the XLA-fused dense path materializes the
[S, S] score matrix in HBM. This kernel is the classic blockwise
(flash-attention) schedule tiled for the MXU instead:

- grid (batch*heads, q_blocks, k_blocks), k innermost: TPU grid steps run
  sequentially, so the running max / normalizer / output accumulator live in
  VMEM scratch and carry across k-steps — HBM traffic is O(S·d), never O(S²).
- Q/K/V blocks stream HBM→VMEM via the BlockSpec pipeline (double-buffered
  by Pallas); the two matmuls per block hit the MXU in float32 accumulation.
- causal blocks strictly above the diagonal are predicated off with
  ``pl.when`` — they cost a grid step but no FLOPs.
- the saved log-sum-exp rides in a lane-replicated [BH, S, 128] buffer —
  Mosaic requires the last two block dims to be (8k, 128)-tileable, so a
  [BH, S] vector output is not lowerable (same layout the upstream TPU
  flash kernel uses).
- backward is two more kernels with the same tiling: one accumulating dQ
  (k innermost), one accumulating dK/dV (q innermost), both recomputing
  P = exp(S - lse) from the lse rather than storing P, and recomputing
  delta = rowsum(dO ∘ O) on-chip.

Everything is wired through ``jax.custom_vjp`` so the op drops into any
``jax.grad`` / ``pjit`` / ``shard_map`` context. On non-TPU backends the
same kernels run under the Pallas interpreter (slow, test-only), which is
how the CPU test suite validates them against the dense reference.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = float("-inf")
_LANES = 128  # lse lane-replication width (Mosaic min tile lane count)
_SUBLANES = 8  # Mosaic's minimum second-minor tile rows


def _auto_interpret(interpret: bool | None) -> bool:
    if interpret is not None:
        return interpret
    return jax.default_backend() != "tpu"


def _causal_mask(s, i, j, bq, bk):
    q_pos = i * bq + lax.broadcasted_iota(jnp.int32, s.shape, 0)
    k_pos = j * bk + lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(q_pos >= k_pos, s, _NEG_INF)


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc,
    *, scale: float, causal: bool, bq: int, bk: int,
):
    i = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc[:] = jnp.zeros_like(acc)

    run = True
    if causal:
        # Skip blocks strictly above the diagonal.
        run = j * bk <= i * bq + bq - 1

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal:
            s = _causal_mask(s, i, j, bq, bk)
        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        # Rows with every key masked so far keep m=-inf; exp(-inf - -inf)
        # is nan, so both the correction and P need the guard.
        safe_m = jnp.where(m_new == _NEG_INF, 0.0, m_new)
        corr = jnp.where(m_prev == _NEG_INF, 0.0, jnp.exp(m_prev - safe_m))
        p = jnp.where(s == _NEG_INF, 0.0, jnp.exp(s - safe_m))
        l_scr[:] = jnp.broadcast_to(
            l_scr[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True),
            l_scr.shape,
        )
        acc[:] = acc[:] * corr + lax.dot_general(
            p,
            v_ref[0].astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(j == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        m = m_scr[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc[:] / safe_l).astype(o_ref.dtype)
        lse = jnp.where(m == _NEG_INF, _NEG_INF, m + jnp.log(safe_l))
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _dq_kernel(
    q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dq_ref, dq_acc, delta_scr,
    *, scale: float, causal: bool, bq: int, bk: int,
):
    i = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)
        delta = jnp.sum(
            do_ref[0].astype(jnp.float32) * o_ref[0].astype(jnp.float32),
            axis=-1,
            keepdims=True,
        )
        delta_scr[:] = jnp.broadcast_to(delta, delta_scr.shape)

    run = True
    if causal:
        run = j * bk <= i * bq + bq - 1

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal:
            s = _causal_mask(s, i, j, bq, bk)
        lse = lse_ref[0][:, :1]
        p = jnp.where(s == _NEG_INF, 0.0, jnp.exp(s - lse))
        do = do_ref[0].astype(jnp.float32)
        dp = lax.dot_general(
            do,
            v_ref[0].astype(jnp.float32),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_scr[:, :1])
        dq_acc[:] = dq_acc[:] + lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(j == nk - 1)
    def _finalize():
        dq_ref[0] = (dq_acc[:] * scale).astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dk_ref, dv_ref,
    dk_acc, dv_acc,
    *, scale: float, causal: bool, bq: int, bk: int,
):
    j = pl.program_id(1)  # k block (outer)
    i = pl.program_id(2)  # q block (inner)
    nq = pl.num_programs(2)

    @pl.when(i == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = True
    if causal:
        run = j * bk <= i * bq + bq - 1

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal:
            s = _causal_mask(s, i, j, bq, bk)
        lse = lse_ref[0][:, :1]
        p = jnp.where(s == _NEG_INF, 0.0, jnp.exp(s - lse))
        do = do_ref[0].astype(jnp.float32)
        dv_acc[:] = dv_acc[:] + lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = lax.dot_general(
            do,
            v_ref[0].astype(jnp.float32),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        delta = jnp.sum(
            do * o_ref[0].astype(jnp.float32), axis=-1, keepdims=True
        )
        ds = p * (dp - delta)
        dk_acc[:] = dk_acc[:] + lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(i == nq - 1)
    def _finalize():
        # dK = Σ dSᵀ·(scale·q); q was loaded pre-scaled, so the accumulator
        # already carries the 1/sqrt(d) factor. dV is scale-free.
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _pick_block(block: int, s: int) -> int:
    """The requested block, clamped and — when it doesn't divide the
    sequence — degraded to the largest aligned divisor of `s` instead of
    erroring (a v5e sweep shows bigger blocks win, so prefer the largest
    block that tiles the sequence exactly). Every returned block is a
    multiple of the 8-row sublane so Mosaic can lower the (bq, ...)
    VMEM tiles; lane-aligned (128) divisors are preferred."""
    block = min(block, s)
    if s % block == 0 and block % _SUBLANES == 0:
        return block
    for step in (_LANES, _SUBLANES):
        for candidate in range(block - block % step, step - 1, -step):
            if s % candidate == 0:
                return candidate
    raise ValueError(
        f"flash attention: no {_SUBLANES}-aligned block <= {block} divides "
        f"the sequence length ({s}); pad the sequence or use "
        "dense_attention"
    )


def _clamp_j(i, j, bq: int, bk: int, causal: bool):
    """K-block index for grid step (i, j). Under causality, blocks
    strictly above the diagonal are compute-skipped (`pl.when(run)`), but
    Pallas would still DMA their K/V tiles; clamping the index to the
    diagonal makes every skipped step re-address the block the previous
    step already holds, so Mosaic elides the copy — the skipped half of
    the grid costs neither FLOPs nor HBM traffic (the long-context win)."""
    if not causal:
        return j
    return jnp.minimum(j, (i * bq + bq - 1) // bk)


def _clamp_i(i, j, bq: int, bk: int, causal: bool):
    """Q-block index for the dk/dv grid (i inner, ascending): steps below
    the first unmasked q block are compute-skipped; clamping them onto
    that first block elides their DMAs the same way."""
    if not causal:
        return i
    return jnp.maximum(i, (j * bk) // bq)


def _qkv_specs(bq: int, bk: int, d: int, causal: bool):
    kv = lambda b, i, j: (b, _clamp_j(i, j, bq, bk, causal), 0)
    return [
        pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, bk, d), kv),
        pl.BlockSpec((1, bk, d), kv),
    ]


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret")
)
def _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret):
    bh, sq, d = q.shape
    sk = k.shape[1]
    bq = _pick_block(block_q, sq)
    bk = _pick_block(block_k, sk)
    scale = 1.0 / math.sqrt(d)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, bq=bq, bk=bk
    )
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, sq // bq, sk // bk),
        in_specs=_qkv_specs(bq, bk, d, causal),
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, _LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=4 * bh * sq * sk * d // (2 if causal else 1),
            bytes_accessed=bh * (sq + 2 * sk) * d * q.dtype.itemsize,
            transcendentals=bh * sq * sk,
        ),
        interpret=interpret,
    )(q, k, v)
    return o, lse


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret")
)
def _flash_bwd_impl(q, k, v, o, lse, do, causal, block_q, block_k, interpret):
    bh, sq, d = q.shape
    sk = k.shape[1]
    bq = _pick_block(block_q, sq)
    bk = _pick_block(block_k, sk)
    scale = 1.0 / math.sqrt(d)

    def _common_specs(qidx, kidx):
        # qidx/kidx map grid positions (x, y) → block indices, with the
        # causal clamp folded in so compute-skipped steps re-address the
        # previous step's block and their DMAs are elided (see _clamp_j).
        return [
            pl.BlockSpec((1, bq, d), lambda b, x, y: (b, qidx(x, y), 0)),
            pl.BlockSpec((1, bk, d), lambda b, x, y: (b, kidx(x, y), 0)),
            pl.BlockSpec((1, bk, d), lambda b, x, y: (b, kidx(x, y), 0)),
            pl.BlockSpec((1, bq, d), lambda b, x, y: (b, qidx(x, y), 0)),
            pl.BlockSpec((1, bq, d), lambda b, x, y: (b, qidx(x, y), 0)),
            pl.BlockSpec(
                (1, bq, _LANES), lambda b, x, y: (b, qidx(x, y), 0)
            ),
        ]

    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, causal=causal, bq=bq, bk=bk
        ),
        grid=(bh, sq // bq, sk // bk),
        in_specs=_common_specs(
            lambda i, j: i,
            lambda i, j: _clamp_j(i, j, bq, bk, causal),
        ),
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, o, do, lse)

    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, causal=causal, bq=bq, bk=bk
        ),
        grid=(bh, sk // bk, sq // bq),
        in_specs=_common_specs(
            lambda j, i: _clamp_i(i, j, bq, bk, causal),
            lambda j, i: j,
        ),
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, o, do, lse)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_bhsd(q, k, v, causal, block_q, block_k, bwd_block_q, bwd_block_k,
                interpret):
    o, _ = _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret)
    return o


def _flash_vjp_fwd(q, k, v, causal, block_q, block_k, bwd_block_q,
                   bwd_block_k, interpret):
    o, lse = _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret)
    # Residual slimming: the kernel writes lse BROADCAST across all 128
    # lanes (Mosaic's f32 tile shape — a narrower kernel output is
    # blocked, see the dead-end log), but the backward kernels read only
    # lane 0. Saving all 128 identical copies as the VJP residual is
    # 128x the bytes that carry information — at S=16k that's ~64 MB of
    # activation memory per layer per (batch*head) group of 8. Keep one
    # lane; the backward re-broadcasts before its pallas_calls. This is
    # what made batch 2 fit at S=16k under the attention-saving remat
    # policy (it previously overflowed HBM by 74 MB).
    return o, (q, k, v, o, lse[:, :, :1])


def _flash_vjp_bwd(causal, block_q, block_k, bwd_block_q, bwd_block_k,
                   interpret, residuals, do):
    q, k, v, o, lse_slim = residuals
    lse = jnp.broadcast_to(
        lse_slim, lse_slim.shape[:2] + (_LANES,)
    )
    return _flash_bwd_impl(
        q, k, v, o, lse, do, causal, bwd_block_q, bwd_block_k, interpret
    )


_flash_bhsd.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = True,
    block_q: int = 1024,
    block_k: int = 1024,
    bwd_block_q: int | None = None,
    bwd_block_k: int | None = None,
    interpret: bool | None = None,
):
    """Blockwise attention on the MXU. q, k, v: [B, S, H, D] → [B, S, H, D].

    Numerically matches ``dense_attention`` (same online-softmax math) while
    never materializing the [S, S] score matrix in HBM — at S=8192 the
    dense path OOMs a 16 GB v5e chip outright; this runs. ``interpret=None``
    autodetects: compiled on TPU, Pallas interpreter elsewhere (tests).

    Default blocks come from a v5e sweep (B=4, H=16, D=128, causal,
    serialized timing): (1024, 1024) beats the small-block configs at
    every length — vs (256, 512): fwd 43.0 vs 26.6 TF/s at S=8k and 67.9
    vs 34.7 TF/s at S=16k (fwd+bwd 85.2 vs 47.4 TF/s); 2048-wide blocks
    fail to compile (VMEM). Blocks clamp to the sequence and degrade to a
    lane-aligned divisor, so short sequences are unaffected.
    """
    b, sq, h, d = q.shape
    interp = _auto_interpret(interpret)
    # [B, S, H, D] → [B*H, S, D]: head-major layout keeps each grid step's
    # blocks contiguous in HBM.
    to_bhsd = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)
    # The backward kernels carry bigger VMEM footprints (two extra f32
    # accumulators), so wide forward tiles can be paired with safer
    # backward tiles; default = same blocks both ways.
    o = _flash_bhsd(
        to_bhsd(q), to_bhsd(k), to_bhsd(v), causal, block_q, block_k,
        bwd_block_q or block_q, bwd_block_k or block_k, interp
    )
    return o.reshape(b, h, sq, d).transpose(0, 2, 1, 3)


def flash_usable(seq_q: int, seq_k: int, block_q: int = 1024,
                 block_k: int = 1024) -> bool:
    """True when the shapes divide into flash blocks (else use dense)."""
    try:
        _pick_block(block_q, seq_q)
        _pick_block(block_k, seq_k)
    except ValueError:
        return False
    return True


# -- ring flash: sequence-parallel flash attention --------------------------
#
# The long-context composition the platform's sp axis exists for: each
# device holds a sequence chunk, K/V chunks rotate around the ring
# (`ops/attention.ring_attention` topology), and every hop runs the
# Pallas kernel instead of materializing the [C, C] score matrix —
# blockwise-parallel ring attention. Per-hop (o_i, lse_i) pairs merge
# with the standard log-sum-exp algebra; the backward re-walks the ring
# passing the GLOBAL (o, lse) into the kernel's bwd (whose
# p = exp(s - lse) and delta = rowsum(do*o) are then the global softmax
# weights — see _dq_kernel), accumulating dk/dv in the rotating frame and
# delivering them home with one final rotation.


def _flat_heads(x):
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _unflat_heads(x, b, h):
    bh, s, d = x.shape
    return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def _hop_branches(qf, kf, vf, bq, bk, interpret):
    """(full, diagonal, skip) branch thunks for one ring hop — the hop
    kind is data-dependent (axis_index), the kernel's causal flag is
    static, so lax.switch picks among three static traces."""
    bh, c, d = qf.shape

    def full_blk():
        return _flash_fwd_impl(qf, kf, vf, False, bq, bk, interpret)

    def diag_blk():
        return _flash_fwd_impl(qf, kf, vf, True, bq, bk, interpret)

    def skip_blk():
        return (
            jnp.zeros((bh, c, d), qf.dtype),
            jnp.full((bh, c, _LANES), _NEG_INF, jnp.float32),
        )

    return (full_blk, diag_blk, skip_blk)


def _hop_index(src, my):
    # 0 = full (earlier chunk), 1 = diagonal (own chunk), 2 = skip
    # (later chunk — fully masked under causality).
    return jnp.where(src == my, 1, jnp.where(src < my, 0, 2))


def _ring_rotate(x, axis: str, n: int):
    # One helper for both attention modules: the dense-hop ring and the
    # flash-hop ring MUST share the same permutation direction.
    from kubeflow_tpu.ops.attention import _rotate

    return _rotate(x, axis, n)


def _ring_flash_fwd_pass(q, k, v, axis, causal, bq, bk, interpret):
    b, c, h, d = q.shape
    n = lax.axis_size(axis)
    my = lax.axis_index(axis)
    qf = _flat_heads(q)
    bh = b * h

    acc = jnp.zeros((bh, c, d), jnp.float32)
    m = jnp.full((bh, c, _LANES), _NEG_INF, jnp.float32)
    l = jnp.zeros((bh, c, _LANES), jnp.float32)
    k_cur, v_cur = k, v
    for i in range(n):
        src = (my - i) % n
        branches = _hop_branches(
            qf, _flat_heads(k_cur), _flat_heads(v_cur), bq, bk, interpret
        )
        if causal:
            o_i, lse_i = lax.switch(_hop_index(src, my), branches)
        else:
            o_i, lse_i = branches[0]()
        # Log-sum-exp merge of the hop's normalized output into the
        # running global softmax (same algebra as the kernel's own
        # online accumulation, one level up).
        m_new = jnp.maximum(m, lse_i)
        corr = jnp.where(m == _NEG_INF, 0.0, jnp.exp(m - m_new))
        w = jnp.where(lse_i == _NEG_INF, 0.0, jnp.exp(lse_i - m_new))
        acc = acc * corr[:, :, :1] + w[:, :, :1] * o_i.astype(jnp.float32)
        l = l * corr + w
        m = m_new
        if i + 1 < n:
            k_cur = _ring_rotate(k_cur, axis, n)
            v_cur = _ring_rotate(v_cur, axis, n)

    safe_l = jnp.where(l == 0.0, 1.0, l)
    o = (acc / safe_l[:, :, :1]).astype(q.dtype)
    lse_tot = m + jnp.log(safe_l)
    return _unflat_heads(o, b, h), lse_tot


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _ring_flash_body(q, k, v, axis, causal, bq, bk, interpret):
    o, _ = _ring_flash_fwd_pass(q, k, v, axis, causal, bq, bk, interpret)
    return o


def _ring_flash_body_fwd(q, k, v, axis, causal, bq, bk, interpret):
    o, lse = _ring_flash_fwd_pass(q, k, v, axis, causal, bq, bk, interpret)
    # Same residual slimming as _flash_vjp_fwd: the global lse is
    # lane-broadcast 128 wide; save one lane, re-broadcast in bwd.
    return o, (q, k, v, o, lse[:, :, :1])


def _ring_flash_body_bwd(axis, causal, bq, bk, interpret, residuals, do):
    q, k, v, o, lse_slim = residuals
    lse = jnp.broadcast_to(lse_slim, lse_slim.shape[:2] + (_LANES,))
    b, c, h, d = q.shape
    n = lax.axis_size(axis)
    my = lax.axis_index(axis)
    qf, of, dof = _flat_heads(q), _flat_heads(o), _flat_heads(do)
    bh = b * h

    dq = jnp.zeros((bh, c, d), jnp.float32)
    # dk/dv accumulate in the ROTATING frame: each hop adds its
    # contribution to the chunk currently held, and the accumulators
    # travel with the chunk.
    k_cur, v_cur = k, v
    dk_cur = jnp.zeros((bh, c, d), jnp.float32)
    dv_cur = jnp.zeros((bh, c, d), jnp.float32)
    for i in range(n):
        src = (my - i) % n
        kf, vf = _flat_heads(k_cur), _flat_heads(v_cur)

        def full_blk():
            return _flash_bwd_impl(
                qf, kf, vf, of, lse, dof, False, bq, bk, interpret
            )

        def diag_blk():
            return _flash_bwd_impl(
                qf, kf, vf, of, lse, dof, True, bq, bk, interpret
            )

        def skip_blk():
            z = jnp.zeros((bh, c, d), q.dtype)
            return z, z, z

        if causal:
            dq_i, dk_i, dv_i = lax.switch(
                _hop_index(src, my), (full_blk, diag_blk, skip_blk)
            )
        else:
            dq_i, dk_i, dv_i = full_blk()
        dq = dq + dq_i.astype(jnp.float32)
        dk_cur = dk_cur + dk_i.astype(jnp.float32)
        dv_cur = dv_cur + dv_i.astype(jnp.float32)
        if i + 1 < n:
            k_cur = _ring_rotate(k_cur, axis, n)
            v_cur = _ring_rotate(v_cur, axis, n)
            dk_cur = _ring_rotate(dk_cur, axis, n)
            dv_cur = _ring_rotate(dv_cur, axis, n)
    # After n-1 rotations the chunk (and its gradient) sits one hop
    # short of home — one final rotation delivers dk/dv to their owners.
    dk_home = _ring_rotate(dk_cur, axis, n)
    dv_home = _ring_rotate(dv_cur, axis, n)
    return (
        _unflat_heads(dq, b, h).astype(q.dtype),
        _unflat_heads(dk_home, b, h).astype(k.dtype),
        _unflat_heads(dv_home, b, h).astype(v.dtype),
    )


_ring_flash_body.defvjp(_ring_flash_body_fwd, _ring_flash_body_bwd)


def ring_flash_attention(
    q,
    k,
    v,
    mesh,
    *,
    causal: bool = True,
    sp_axis: str = "sp",
    heads_axis: str | None = "tp",
    block_q: int = 1024,
    block_k: int = 1024,
    interpret: bool | None = None,
):
    """Sequence-parallel flash attention over `mesh`'s sp ring.

    q, k, v: GLOBAL [B, S, H, D]; S divides by the ring, H by tp. Each
    hop runs the Pallas kernel on the local [C, C] tile (C = S/ring), so
    per-device attention memory is O(C·D) instead of O(C²) — the
    composition that takes the single-chip S=16k flash ceiling to
    ring-size × 16k. Differentiable end-to-end (custom VJP re-walks the
    ring with global statistics). Falls back to single-device flash when
    the ring is trivial."""
    if mesh.shape.get(sp_axis, 1) == 1:
        return flash_attention(
            q, k, v, causal=causal, block_q=block_q, block_k=block_k,
            interpret=interpret,
        )
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from kubeflow_tpu.parallel.sharding import batch_axes

    ring = mesh.shape[sp_axis]
    if q.shape[1] % ring:
        raise ValueError(
            f"ring flash attention: sequence length {q.shape[1]} does "
            f"not divide the {sp_axis!r} ring size {ring}"
        )
    spec = P(batch_axes(mesh), sp_axis, heads_axis, None)
    interp = _auto_interpret(interpret)

    def body(q_, k_, v_):
        # nondiff custom_vjp args must be positional, so no partial().
        return _ring_flash_body(
            q_, k_, v_, sp_axis, causal, block_q, block_k, interp
        )

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_rep=False,
    )(q, k, v)
