"""Flash attention as a Pallas TPU kernel (forward + backward).

The reference has no kernels at all — its device-level compute lives inside
third-party containers (SURVEY.md §2.1). On TPU the hot op of the flagship
transformer is attention, and the XLA-fused dense path materializes the
[S, S] score matrix in HBM. This kernel is the classic blockwise
(flash-attention) schedule tiled for the MXU, with a long-context schedule
on top:

- **Compact causal grid.** For causal self-attention the grid enumerates
  ONLY the lower-triangular (q, k) block pairs: the grid is
  (batch*heads, T) with T = nq·(nq+1)/2, and two scalar-prefetched int32
  tables map the flat step index back to (i, j). Blocks above the
  diagonal cost zero grid steps — at large S that halves the step count
  outright, where the old rectangular grid paid a predicated-off
  DMA+step per masked block. The rectangular grid (with `_clamp_i` /
  `_clamp_j` DMA elision) remains as the fallback for non-causal,
  cross-shaped, or uneven-block configurations.
- **Lane-packed LSE.** The saved log-sum-exp is stored as
  [BH, S/128, 128] tiles — 128 per-row values per lane row — instead of
  the lane-replicated [BH, S, 128] buffer Mosaic's tiling would
  otherwise force (a [BH, S] vector output is not lowerable). That cuts
  the lse's HBM footprint and its fwd→bwd traffic 128×. Packing happens
  in-register via (128, 128) transposes of the lane-replicated scratch
  (a supported Mosaic relayout), not a 1-D reshape. Block sizes that are
  not lane-aligned fall back to the replicated layout with a slim
  [BH, S, 1] residual.
- **Shared-delta backward.** A small precompute kernel emits
  delta = rowsum(dO ∘ O) once per backward; the backward kernels read
  it as an input instead of each recomputing the rowsum on-chip — which
  also removes O entirely from the backward input streams (dO/O were
  previously re-streamed by each kernel).
- **Fused one-pass dq/dkv backward.** On the compact causal grid the
  backward is ONE kernel (`_dqkv_kernel_fused`) walking the triangle
  once in column-major order: dk/dv accumulate in per-column VMEM
  scratch (as the two-pass dkv kernel did), and each step's dq
  contribution lands in a per-row slot of a VMEM dq ring — every q row
  is live from the first kv column and retires in row order (row j's
  last contribution is column j's diagonal step), so slot j flushes to
  the dq output when column j completes. K/V are fetched once per
  COLUMN and only Q/dO (plus the slim lse/delta rows) stream per grid
  step; the two-pass backward streamed K/V per dq step AND Q/dO per
  dkv step, so fusing halves the dominant bwd HBM traffic — and the
  (s, p, ds) recurrence is computed once instead of twice (5 block
  matmuls, not 7). The dq ring costs S·d·4 bytes of VMEM, so fusion is
  gated by `_bwd_fused` (the same predicate `flash_schedule` reports as
  `bwd_fused`); past the budget — or on the rectangular fallback — the
  two-pass kernels run unchanged. `KFTPU_FLASH_FUSED_BWD=0` force-
  disables fusion (operational escape hatch).
- **Internal padding.** Sequence lengths with no 8-aligned divisor pad
  to the next lane multiple inside `flash_attention`; the tail is
  masked in-kernel (`kv_len`) and sliced off the output, so ragged
  lengths run the kernel instead of silently falling back to the dense
  O(S²) path.
- grid steps run sequentially on TPU, so the running max / normalizer /
  output accumulator live in VMEM scratch and carry across k-steps —
  HBM traffic is O(S·d), never O(S²); Q/K/V blocks stream HBM→VMEM via
  the BlockSpec pipeline (double-buffered by Pallas) and the two
  matmuls per block hit the MXU in float32 accumulation.

The forward names its outputs (`flash_attn_out`, `flash_attn_lse`) via
`jax.checkpoint_name`, so `remat_policy="flash"`
(`models/transformer.py`) can pin exactly {attention output, lse} across
a block checkpoint — the backward then never re-runs the forward kernel
(its residuals q/k/v recompute from the cheap projections; o and lse are
saved).

Everything is wired through ``jax.custom_vjp`` so the op drops into any
``jax.grad`` / ``pjit`` / ``shard_map`` context. On non-TPU backends the
same kernels run under the Pallas interpreter (slow, test-only), which is
how the CPU test suite validates them against the dense reference.
"""

from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = float("-inf")
_LANES = 128  # Mosaic min tile lane count (f32 tile is (8, 128))
_SUBLANES = 8  # Mosaic's minimum second-minor tile rows

# Compact causal grids carry two int32 (i, j) lookup tables in SMEM via
# scalar prefetch. Cap their length so a degenerate tiny-block × huge-S
# combination cannot blow the scalar-memory budget; past the cap the
# rectangular fallback (predicated blocks + clamped DMAs) still runs.
_MAX_COMPACT_STEPS = 1 << 16

# jax.checkpoint_name tags on the forward's outputs — the handles
# remat_policy="flash" (models/transformer.py) pins across a block
# checkpoint so the backward never re-runs the forward kernel.
CHECKPOINT_OUT_NAME = "flash_attn_out"
CHECKPOINT_LSE_NAME = "flash_attn_lse"


def _auto_interpret(interpret: bool | None) -> bool:
    if interpret is not None:
        return interpret
    return jax.default_backend() != "tpu"


def _causal_mask(s, i, j, bq, bk):
    q_pos = i * bq + lax.broadcasted_iota(jnp.int32, s.shape, 0)
    k_pos = j * bk + lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(q_pos >= k_pos, s, _NEG_INF)


def _kv_tail_mask(s, j, bk, kv_len: int):
    """Mask key positions past the true (pre-padding) sequence length."""
    k_pos = j * bk + lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(k_pos < kv_len, s, _NEG_INF)


# -- lse layouts -------------------------------------------------------------
#
# Kernel-side the lse rides in one of two layouts:
#   packed     [BH, S/128, 128] — tile (w, l) holds lse[w*128 + l]; the
#              exact information content, 1/128th the replicated bytes.
#   replicated [BH, S, 128]     — every lane carries the row's value (the
#              layout Mosaic's (8, 128) tiling forces when the q block is
#              not lane-aligned).
# The packed layout needs S and every q-block size in play (fwd and bwd)
# to be multiples of 128 so block boundaries land on packed-row
# boundaries. Outside the kernels the canonical form is per-row
# [BH, S, 1] ("rows"), to which both layouts convert with free reshapes.


def _lse_layout_shape(bh: int, sq: int, packed: bool) -> tuple[int, ...]:
    if packed:
        return (bh, sq // _LANES, _LANES)
    return (bh, sq, _LANES)


def _lse_block(bq: int, packed: bool) -> tuple[int, ...]:
    if packed:
        return (1, bq // _LANES, _LANES)
    return (1, bq, _LANES)


def _lse_is_packed(sq: int, *q_blocks: int) -> bool:
    return sq % _LANES == 0 and all(b % _LANES == 0 for b in q_blocks)


def _pack_rows(x_rep):
    """(bq, 128) lane-replicated → (bq/128, 128) packed, in-kernel.

    Cross-lane packing without a Mosaic 1-D reshape: each 128-row chunk
    of the replicated buffer is transposed — a supported (128, 128)
    relayout — after which EVERY row of the transpose holds the chunk's
    128 per-row values; row 0 is the packed tile row."""
    bq = x_rep.shape[0]
    rows = [
        x_rep[w * _LANES:(w + 1) * _LANES, :].T[:1, :]
        for w in range(bq // _LANES)
    ]
    return rows[0] if len(rows) == 1 else jnp.concatenate(rows, axis=0)


def _unpack_rows(x_packed):
    """(m, 128) packed → (m*128, 128) lane-replicated, in-kernel (the
    inverse trick: broadcast each packed row across sublanes, transpose)."""
    m = x_packed.shape[0]
    chunks = [
        jnp.broadcast_to(x_packed[w:w + 1, :], (_LANES, _LANES)).T
        for w in range(m)
    ]
    return chunks[0] if m == 1 else jnp.concatenate(chunks, axis=0)


def _read_rows(ref0, packed: bool):
    """Kernel-side: an lse/delta block in either layout → (bq, 1) rows."""
    if packed:
        return _unpack_rows(ref0)[:, :1]
    return ref0[:, :1]


def _lse_rows(lse, sq: int):
    """Host-side: any lse form (packed / replicated / slim) → [BH, S, 1]."""
    if lse.shape[1] == sq:
        return lse[:, :, :1]
    return lse.reshape(lse.shape[0], sq, 1)


def _rows_to_layout(rows, packed: bool):
    """Host-side: [BH, S, 1] rows → the kernel layout."""
    bh, sq, _ = rows.shape
    if packed:
        return rows.reshape(bh, sq // _LANES, _LANES)
    return jnp.broadcast_to(rows, (bh, sq, _LANES))


# -- schedule ----------------------------------------------------------------


def _pick_block(block: int, s: int) -> int:
    """The requested block, clamped and — when it doesn't divide the
    sequence — degraded to the largest aligned divisor of `s` instead of
    erroring (a v5e sweep shows bigger blocks win, so prefer the largest
    block that tiles the sequence exactly). Every returned block is a
    multiple of the 8-row sublane so Mosaic can lower the (bq, ...)
    VMEM tiles; lane-aligned (128) divisors are preferred. Raising is
    internal-only now: ``flash_attention`` pads untileable sequences to
    the next lane multiple before the kernels ever see them."""
    block = min(block, s)
    if s % block == 0 and block % _SUBLANES == 0:
        return block
    for step in (_LANES, _SUBLANES):
        for candidate in range(block - block % step, step - 1, -step):
            if s % candidate == 0:
                return candidate
    raise ValueError(
        f"flash attention: no {_SUBLANES}-aligned block <= {block} divides "
        f"the sequence length ({s}); pad the sequence (flash_attention "
        "does this automatically) or use dense_attention"
    )


def _tileable(block: int, s: int) -> bool:
    try:
        _pick_block(block, s)
    except ValueError:
        return False
    return True


def _pad_to_tileable(block: int, s: int) -> int:
    """`s` when it already tiles, else the next lane multiple (which
    always tiles: 128 itself divides any 128-multiple)."""
    if _tileable(block, s):
        return s
    return -(-s // _LANES) * _LANES


def _compactable(causal: bool, sq: int, sk: int, bq: int, bk: int) -> bool:
    """Whether the triangular grid applies: causal self-attention with
    square blocks, so block row i runs exactly blocks j <= i."""
    if not (causal and sq == sk and bq == bk):
        return False
    nq = sq // bq
    return nq * (nq + 1) // 2 <= _MAX_COMPACT_STEPS


def _grid_steps(causal: bool, sq: int, sk: int, bq: int, bk: int):
    """(steps, rectangular_steps, compact) per (batch*head) grid row."""
    nq, nk = sq // bq, sk // bk
    rect = nq * nk
    if _compactable(causal, sq, sk, bq, bk):
        return nq * (nq + 1) // 2, rect, True
    return rect, rect, False


def _tri_tables(nq: int, order: str):
    """Scalar-prefetch lookup tables for the compact causal grid: the
    flat step index t → (i, j) over the lower triangle. "row" order
    (fwd / dq: j contiguous per i) or "col" order (dkv: i contiguous
    per j)."""
    i, j = np.tril_indices(nq)
    if order == "col":
        o = np.lexsort((i, j))
        i, j = i[o], j[o]
    return jnp.asarray(i, jnp.int32), jnp.asarray(j, jnp.int32)


# -- fused backward gating + HBM byte model ----------------------------------
#
# The fused one-pass backward holds a full dq accumulator ring in VMEM
# (one f32 row-block slot per q block: every row is live from the first
# kv column), so it engages only while that scratch — plus the dk/dv
# accumulators and the double-buffered streamed blocks — fits a VMEM
# budget. ~16 MiB/core on v5e; 12 MiB leaves margin for Mosaic's own
# buffers. At the flagship shape (S=16384, d=128, bf16, 1024² blocks)
# the fused footprint is ~11.1 MiB, so the 16k target regime fuses; a
# 32k/d=128 dq ring alone is 16 MiB and falls back to two-pass.
_FUSED_VMEM_BUDGET = 12 * 1024 * 1024
# Operational escape hatch: KFTPU_FLASH_FUSED_BWD=0 pins the two-pass
# backward everywhere (e.g. if a toolchain rejects the fused kernel).
# Read at TRACE time — jit caches a traced backward by shapes/static
# args, so this is a set-before-first-use process knob (a rollback
# lever for launch scripts), not a runtime toggle: flipping it after a
# shape has been traced does not retrace that shape.
_FUSED_ENV = "KFTPU_FLASH_FUSED_BWD"


def _fused_enabled() -> bool:
    return os.environ.get(_FUSED_ENV, "1") != "0"


def _lse_bytes_of(sq: int, packed: bool) -> int:
    return int(np.prod(_lse_layout_shape(1, sq, packed)[1:])) * 4


def _lse_block_bytes(bq: int, packed: bool) -> int:
    return int(np.prod(_lse_block(bq, packed))) * 4


def _fused_vmem_bytes(
    sq: int, bq: int, bk: int, d: int, itemsize: int, packed: bool
) -> int:
    """VMEM the fused kernel needs: the dq ring (f32, one slot per q
    block — i.e. the whole padded sequence), per-column dk/dv f32
    accumulators, and the Pallas-double-buffered streamed blocks."""
    return (
        sq * d * 4  # dq ring scratch
        + 2 * bk * d * 4  # dk/dv accumulators
        + 2 * 2 * bq * d * itemsize  # q, do blocks (double-buffered)
        + 2 * 2 * bk * d * itemsize  # k, v blocks (double-buffered)
        + 2 * 2 * _lse_block_bytes(bq, packed)  # lse, delta blocks
    )


def _bwd_fused(
    causal: bool, sq: int, sk: int, bq: int, bk: int, d: int,
    itemsize: int, packed: bool,
) -> bool:
    """Whether the backward runs the fused one-pass kernel: compact
    causal grid (square blocks, self-attention) AND the dq ring fits
    the VMEM budget. Shared verbatim by `flash_schedule` (reported as
    `bwd_fused`) and the `_flash_bwd_kernels` dispatch, so the
    accounting benches/tests gate on is the schedule that actually
    runs."""
    if not _fused_enabled():
        return False
    if not _compactable(causal, sq, sk, bq, bk):
        return False
    return (
        _fused_vmem_bytes(sq, bq, bk, d, itemsize, packed)
        <= _FUSED_VMEM_BUDGET
    )


def _bwd_hbm_bytes(
    causal: bool, sq: int, sk: int, bq: int, bk: int, d: int,
    itemsize: int, packed: bool, fused: bool,
) -> int:
    """Modeled backward HBM bytes per (batch·head) grid row, including
    the shared-delta precompute. Counts what each kernel's BlockSpec
    pipeline actually moves: blocks whose index map is constant across
    consecutive grid steps are fetched once per row/column (Mosaic
    elides the re-fetch); blocks whose index changes stream once per
    step. DMA elision on the predicated rectangular fallback is not
    modeled (it is not the path this model exists to tune)."""
    steps, _, _ = _grid_steps(causal, sq, sk, bq, bk)
    lse_bytes = _lse_bytes_of(sq, packed)
    lse_blk = _lse_block_bytes(bq, packed)
    # delta = rowsum(dO ∘ O): one pass over (o, do), one lse-layout write.
    delta = 2 * sq * d * itemsize + lse_bytes
    if fused:
        # One walk, column-major: k/v resident per column; q/do/lse/delta
        # stream per step; dq+dk+dv written once each.
        return delta + (
            2 * sk * d * itemsize  # k, v (once per column)
            + steps * 2 * bq * d * itemsize  # q, do per step
            + steps * 2 * lse_blk  # lse, delta rows per step
            + 3 * sq * d * itemsize  # dq, dk, dv writes
        )
    # Two passes over the same grid: the dq kernel (row-major) streams
    # k/v per step with q/do/lse/delta resident per row; the dkv kernel
    # (column-major) streams q/do/lse/delta per step with k/v resident.
    dq_pass = (
        2 * sq * d * itemsize  # q, do (once per row)
        + 2 * lse_bytes  # lse, delta (once per row)
        + steps * 2 * bk * d * itemsize  # k, v per step
        + sq * d * itemsize  # dq write
    )
    dkv_pass = (
        2 * sk * d * itemsize  # k, v (once per column)
        + steps * 2 * bq * d * itemsize  # q, do per step
        + steps * 2 * lse_blk  # lse, delta rows per step
        + 2 * sk * d * itemsize  # dk, dv writes
    )
    return delta + dq_pass + dkv_pass


def flash_schedule(
    seq_q: int,
    seq_k: int,
    *,
    block_q: int = 1024,
    block_k: int = 1024,
    bwd_block_q: int | None = None,
    bwd_block_k: int | None = None,
    causal: bool = True,
    head_dim: int = 128,
    dtype_bytes: int = 2,
) -> dict:
    """Static accounting for the schedule `flash_attention` would run.

    This is the single source of truth the kernel impls themselves use
    (`_grid_steps`, `_lse_is_packed`, `_pad_to_tileable`, `_bwd_fused`),
    exposed so benches and regression tests can assert grid-step counts
    and lse/backward HBM bytes without launching a kernel. All
    byte/step figures are per (batch*head) grid row; `head_dim` and
    `dtype_bytes` (2 = bf16, the training dtype) parameterize the
    backward byte/VMEM models only."""
    sp_q = _pad_to_tileable(block_q, seq_q)
    sp_k = _pad_to_tileable(block_k, seq_k)
    bq = _pick_block(block_q, sp_q)
    bk = _pick_block(block_k, sp_k)
    bq_bwd = _pick_block(bwd_block_q or block_q, sp_q)
    bk_bwd = _pick_block(bwd_block_k or block_k, sp_k)
    steps, rect, compact = _grid_steps(causal, sp_q, sp_k, bq, bk)
    # The backward kernels run their own grids with the (possibly
    # narrower) bwd blocks — dq and dkv each walk this many steps.
    bwd_steps, bwd_rect, bwd_compact = _grid_steps(
        causal, sp_q, sp_k, bq_bwd, bk_bwd
    )
    packed = _lse_is_packed(sp_q, bq, bq_bwd)
    lse_shape = _lse_layout_shape(1, sp_q, packed)[1:]
    fused = _bwd_fused(
        causal, sp_q, sp_k, bq_bwd, bk_bwd, head_dim, dtype_bytes, packed
    )
    bwd_bytes = lambda f: _bwd_hbm_bytes(
        causal, sp_q, sp_k, bq_bwd, bk_bwd, head_dim, dtype_bytes, packed, f
    )
    return {
        "padded_seq_q": sp_q,
        "padded_seq_k": sp_k,
        "block_q": bq,
        "block_k": bk,
        "bwd_block_q": bq_bwd,
        "bwd_block_k": bk_bwd,
        "compact": compact,
        "grid_steps": steps,
        "rect_grid_steps": rect,
        "bwd_compact": bwd_compact,
        "bwd_grid_steps": bwd_steps,
        "bwd_rect_grid_steps": bwd_rect,
        # Fused one-pass backward: whether it engages at these
        # shapes/dtype, the total bwd grid steps actually walked (one
        # triangle pass fused, two passes otherwise — the single-KV-pass
        # gate), and the modeled HBM bytes per bh row for BOTH paths so
        # benches can assert the fused path's ~halving.
        "bwd_fused": fused,
        "bwd_total_grid_steps": bwd_steps if fused else 2 * bwd_steps,
        "bwd_fused_vmem_bytes": _fused_vmem_bytes(
            sp_q, bq_bwd, bk_bwd, head_dim, dtype_bytes, packed
        ),
        "bwd_hbm_bytes": bwd_bytes(fused),
        "bwd_hbm_bytes_fused": bwd_bytes(True),
        "bwd_hbm_bytes_two_pass": bwd_bytes(False),
        "lse_packed": packed,
        "lse_shape": lse_shape,
        "lse_bytes": int(np.prod(lse_shape)) * 4,
        "lse_replicated_bytes": sp_q * _LANES * 4,
    }


# -- kernels -----------------------------------------------------------------


def _fwd_body(
    i, j, first, last, run, q_ref, k_ref, v_ref, o_ref, lse_ref,
    m_scr, l_scr, acc,
    *, scale: float, causal: bool, bq: int, bk: int,
    kv_len: int | None, packed: bool,
):
    @pl.when(first)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc[:] = jnp.zeros_like(acc)

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal:
            s = _causal_mask(s, i, j, bq, bk)
        if kv_len is not None:
            s = _kv_tail_mask(s, j, bk, kv_len)
        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        # Rows with every key masked so far keep m=-inf; exp(-inf - -inf)
        # is nan, so both the correction and P need the guard.
        safe_m = jnp.where(m_new == _NEG_INF, 0.0, m_new)
        corr = jnp.where(m_prev == _NEG_INF, 0.0, jnp.exp(m_prev - safe_m))
        p = jnp.where(s == _NEG_INF, 0.0, jnp.exp(s - safe_m))
        l_scr[:] = jnp.broadcast_to(
            l_scr[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True),
            l_scr.shape,
        )
        acc[:] = acc[:] * corr + lax.dot_general(
            p,
            v_ref[0].astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(last)
    def _finalize():
        l = l_scr[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc[:] / safe_l).astype(o_ref.dtype)
        lse_rep = jnp.where(
            m_scr[:] == _NEG_INF,
            _NEG_INF,
            m_scr[:] + jnp.log(jnp.where(l_scr[:] == 0.0, 1.0, l_scr[:])),
        )
        lse_ref[0] = _pack_rows(lse_rep) if packed else lse_rep


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc, **kw
):
    """Rectangular grid: (bh, nq, nk), k innermost; causal blocks above
    the diagonal are predicated off (they still cost a grid step — the
    compact kernel below is the one that doesn't pay them)."""
    i = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)
    run = True
    if kw["causal"]:
        run = j * kw["bk"] <= i * kw["bq"] + kw["bq"] - 1
    _fwd_body(
        i, j, j == 0, j == nk - 1, run,
        q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc, **kw
    )


def _fwd_kernel_compact(
    rows_ref, cols_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
    m_scr, l_scr, acc, **kw
):
    """Compact causal grid: (bh, T) over lower-triangular block pairs;
    the scalar-prefetched tables recover (i, j). Every enumerated block
    runs — skipped blocks simply don't exist in the grid."""
    t = pl.program_id(1)
    i = rows_ref[t]
    j = cols_ref[t]
    _fwd_body(
        i, j, j == 0, j == i, True,
        q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc, **kw
    )


def _delta_kernel(o_ref, do_ref, delta_ref, *, packed: bool):
    """delta = rowsum(dO ∘ O), computed ONCE per backward and shared by
    the dq and dkv kernels (each previously recomputed it per grid row,
    re-streaming dO and O from HBM to do so)."""
    delta = jnp.sum(
        do_ref[0].astype(jnp.float32) * o_ref[0].astype(jnp.float32),
        axis=-1,
        keepdims=True,
    )
    rep = jnp.broadcast_to(delta, (delta.shape[0], _LANES))
    delta_ref[0] = _pack_rows(rep) if packed else rep


def _dq_body(
    i, j, first, last, run, q_ref, k_ref, v_ref, do_ref, lse_ref,
    delta_ref, dq_ref, dq_acc,
    *, scale: float, causal: bool, bq: int, bk: int,
    kv_len: int | None, packed: bool,
):
    @pl.when(first)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal:
            s = _causal_mask(s, i, j, bq, bk)
        if kv_len is not None:
            s = _kv_tail_mask(s, j, bk, kv_len)
        lse = _read_rows(lse_ref[0], packed)
        p = jnp.where(s == _NEG_INF, 0.0, jnp.exp(s - lse))
        do = do_ref[0].astype(jnp.float32)
        dp = lax.dot_general(
            do,
            v_ref[0].astype(jnp.float32),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - _read_rows(delta_ref[0], packed))
        dq_acc[:] = dq_acc[:] + lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(last)
    def _finalize():
        dq_ref[0] = (dq_acc[:] * scale).astype(dq_ref.dtype)


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc, **kw
):
    i = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)
    run = True
    if kw["causal"]:
        run = j * kw["bk"] <= i * kw["bq"] + kw["bq"] - 1
    _dq_body(
        i, j, j == 0, j == nk - 1, run,
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc,
        **kw,
    )


def _dq_kernel_compact(
    rows_ref, cols_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dq_ref, dq_acc, **kw
):
    t = pl.program_id(1)
    i = rows_ref[t]
    j = cols_ref[t]
    _dq_body(
        i, j, j == 0, j == i, True,
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc,
        **kw,
    )


def _dkv_body(
    i, j, first, last, run, q_ref, k_ref, v_ref, do_ref, lse_ref,
    delta_ref, dk_ref, dv_ref, dk_acc, dv_acc,
    *, scale: float, causal: bool, bq: int, bk: int,
    kv_len: int | None, packed: bool,
):
    @pl.when(first)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal:
            s = _causal_mask(s, i, j, bq, bk)
        if kv_len is not None:
            s = _kv_tail_mask(s, j, bk, kv_len)
        lse = _read_rows(lse_ref[0], packed)
        p = jnp.where(s == _NEG_INF, 0.0, jnp.exp(s - lse))
        do = do_ref[0].astype(jnp.float32)
        dv_acc[:] = dv_acc[:] + lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = lax.dot_general(
            do,
            v_ref[0].astype(jnp.float32),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - _read_rows(delta_ref[0], packed))
        dk_acc[:] = dk_acc[:] + lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(last)
    def _finalize():
        # dK = Σ dSᵀ·(scale·q); q was loaded pre-scaled, so the accumulator
        # already carries the 1/sqrt(d) factor. dV is scale-free.
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc, dv_acc, **kw
):
    j = pl.program_id(1)  # k block (outer)
    i = pl.program_id(2)  # q block (inner)
    nq = pl.num_programs(2)
    run = True
    if kw["causal"]:
        run = j * kw["bk"] <= i * kw["bq"] + kw["bq"] - 1
    _dkv_body(
        i, j, i == 0, i == nq - 1, run,
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
        dk_acc, dv_acc, **kw,
    )


def _dkv_kernel_compact(
    rows_ref, cols_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dk_ref, dv_ref, dk_acc, dv_acc, **kw
):
    """Column-major compact traversal: for each k block j, q blocks
    i = j..nq-1 are contiguous, so dk/dv accumulate across exactly the
    blocks that exist below the diagonal."""
    t = pl.program_id(1)
    i = rows_ref[t]
    j = cols_ref[t]
    nq = kw.pop("nq")
    _dkv_body(
        i, j, i == j, i == nq - 1, True,
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
        dk_acc, dv_acc, **kw,
    )


def _dqkv_kernel_fused(
    rows_ref, cols_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dq_ref, dk_ref, dv_ref, dq_ring, dk_acc, dv_acc,
    *, scale: float, causal: bool, bq: int, bk: int,
    kv_len: int | None, packed: bool, nq: int,
):
    """Fused one-pass backward over the compact causal grid, column-major
    (for each kv block j, q blocks i = j..nq-1 are contiguous).

    Each step computes the (s, p, ds) recurrence ONCE and feeds all
    three gradients: dk/dv accumulate in per-column scratch exactly like
    `_dkv_kernel_compact`, and the step's dq contribution ds·K lands in
    slot i of the dq ring. Every q row is live from column 0 and retires
    in row order — row j's last contribution is column j's diagonal
    step (the column's FIRST step, since i ascends from j) — so slot j
    flushes to the dq output block when column j completes. The three
    output BlockSpecs all ride the column index, which is constant
    within a column: one HBM write per output block.

    Input streams are q/do/lse/delta (per step) and k/v (once per
    column). O is NOT an input — delta carries the rowsum(dO ∘ O)
    precompute (shared-delta contract, see `_delta_kernel`)."""
    t = pl.program_id(1)
    i = rows_ref[t]
    j = cols_ref[t]
    first = i == j  # column j's first step (the diagonal block)
    last = i == nq - 1  # column j's last step

    @pl.when(first)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q = q_ref[0].astype(jnp.float32) * scale
    k = k_ref[0].astype(jnp.float32)
    s = lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    if causal:
        s = _causal_mask(s, i, j, bq, bk)
    if kv_len is not None:
        s = _kv_tail_mask(s, j, bk, kv_len)
    lse = _read_rows(lse_ref[0], packed)
    p = jnp.where(s == _NEG_INF, 0.0, jnp.exp(s - lse))
    do = do_ref[0].astype(jnp.float32)
    dv_acc[:] = dv_acc[:] + lax.dot_general(
        p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    dp = lax.dot_general(
        do,
        v_ref[0].astype(jnp.float32),
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - _read_rows(delta_ref[0], packed))
    dk_acc[:] = dk_acc[:] + lax.dot_general(
        ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    # dq contribution for row i from column j; q was loaded pre-scaled,
    # so the ring carries the 1/sqrt(d) factor once more at flush (same
    # algebra as `_dq_body`'s finalize).
    dq_i = lax.dot_general(
        ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    slot = pl.ds(i * bq, bq)

    @pl.when(j == 0)
    def _seed():
        # Column 0 is every row's first contribution — a store, not an
        # accumulate, so the ring never needs a zeroing pass.
        dq_ring[slot, :] = dq_i

    @pl.when(j > 0)
    def _accum():
        dq_ring[slot, :] = dq_ring[slot, :] + dq_i

    @pl.when(last)
    def _flush():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)
        # Row j retired at this column's diagonal step; its completed
        # slot flushes into the column-indexed dq output block.
        dq_ref[0] = (dq_ring[pl.ds(j * bq, bq), :] * scale).astype(
            dq_ref.dtype
        )


# -- clamped index maps (rectangular fallback only) --------------------------


def _clamp_j(i, j, bq: int, bk: int, causal: bool):
    """K-block index for rectangular grid step (i, j). Under causality,
    blocks strictly above the diagonal are compute-skipped (`pl.when`),
    but Pallas would still DMA their K/V tiles; clamping the index to
    the diagonal makes every skipped step re-address the block the
    previous step already holds, so Mosaic elides the copy. The compact
    grid doesn't enumerate those steps at all — this clamp only matters
    for the non-compacted fallback."""
    if not causal:
        return j
    return jnp.minimum(j, (i * bq + bq - 1) // bk)


def _clamp_i(i, j, bq: int, bk: int, causal: bool):
    """Q-block index for the rectangular dk/dv grid (i inner, ascending):
    steps below the first unmasked q block are compute-skipped; clamping
    them onto that first block elides their DMAs the same way."""
    if not causal:
        return i
    return jnp.maximum(i, (j * bk) // bq)


def _qkv_specs(bq: int, bk: int, d: int, causal: bool):
    kv = lambda b, i, j: (b, _clamp_j(i, j, bq, bk, causal), 0)
    return [
        pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, bk, d), kv),
        pl.BlockSpec((1, bk, d), kv),
    ]


# -- pallas_call wrappers ----------------------------------------------------


@functools.partial(
    jax.jit,
    static_argnames=(
        "causal", "block_q", "block_k", "interpret", "kv_len", "packed"
    ),
)
def _flash_fwd_impl(
    q, k, v, causal, block_q, block_k, interpret, kv_len=None, packed=False
):
    bh, sq, d = q.shape
    sk = k.shape[1]
    bq = _pick_block(block_q, sq)
    bk = _pick_block(block_k, sk)
    scale = 1.0 / math.sqrt(d)
    steps, _, compact = _grid_steps(causal, sq, sk, bq, bk)
    nq = sq // bq
    kernel_kw = dict(
        scale=scale, causal=causal, bq=bq, bk=bk, kv_len=kv_len,
        packed=packed,
    )
    out_shape = [
        jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        jax.ShapeDtypeStruct(_lse_layout_shape(bh, sq, packed), jnp.float32),
    ]
    scratch = [
        pltpu.VMEM((bq, _LANES), jnp.float32),
        pltpu.VMEM((bq, _LANES), jnp.float32),
        pltpu.VMEM((bq, d), jnp.float32),
    ]
    cost = pl.CostEstimate(
        flops=4 * bh * steps * bq * bk * d,
        bytes_accessed=bh * (sq + 2 * sk) * d * q.dtype.itemsize,
        transcendentals=bh * steps * bq * bk,
    )
    if compact:
        rows, cols = _tri_tables(nq, "row")
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh, steps),
            in_specs=[
                pl.BlockSpec((1, bq, d), lambda b, t, rs, cs: (b, rs[t], 0)),
                pl.BlockSpec((1, bk, d), lambda b, t, rs, cs: (b, cs[t], 0)),
                pl.BlockSpec((1, bk, d), lambda b, t, rs, cs: (b, cs[t], 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, bq, d), lambda b, t, rs, cs: (b, rs[t], 0)),
                pl.BlockSpec(
                    _lse_block(bq, packed),
                    lambda b, t, rs, cs: (b, rs[t], 0),
                ),
            ],
            scratch_shapes=scratch,
        )
        return pl.pallas_call(
            functools.partial(_fwd_kernel_compact, **kernel_kw),
            grid_spec=grid_spec,
            out_shape=out_shape,
            cost_estimate=cost,
            interpret=interpret,
        )(rows, cols, q, k, v)
    return pl.pallas_call(
        functools.partial(_fwd_kernel, **kernel_kw),
        grid=(bh, nq, sk // bk),
        in_specs=_qkv_specs(bq, bk, d, causal),
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec(_lse_block(bq, packed), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=out_shape,
        scratch_shapes=scratch,
        cost_estimate=cost,
        interpret=interpret,
    )(q, k, v)


@functools.partial(
    jax.jit, static_argnames=("block_q", "interpret", "packed")
)
def _flash_delta_impl(o, do, block_q, interpret, packed):
    """The shared-delta precompute: one O(S·d) pass over (o, do)."""
    bh, sq, d = o.shape
    bq = _pick_block(block_q, sq)
    spec = pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0))
    return pl.pallas_call(
        functools.partial(_delta_kernel, packed=packed),
        grid=(bh, sq // bq),
        in_specs=[spec, spec],
        out_specs=pl.BlockSpec(
            _lse_block(bq, packed), lambda b, i: (b, i, 0)
        ),
        out_shape=jax.ShapeDtypeStruct(
            _lse_layout_shape(bh, sq, packed), jnp.float32
        ),
        interpret=interpret,
    )(o, do)


@functools.partial(
    jax.jit,
    static_argnames=(
        "causal", "block_q", "block_k", "interpret", "kv_len", "packed",
        "fused",
    ),
)
def _flash_bwd_kernels(
    q, k, v, do, lse, delta, causal, block_q, block_k, interpret,
    kv_len=None, packed=False, fused=None,
):
    """Backward kernels over a precomputed (lse, delta) pair (both in
    the kernel lse layout): the fused one-pass dq/dkv kernel when
    `_bwd_fused` allows (compact causal grid + dq ring fits VMEM), else
    the two-pass dq + dkv kernels. `fused=None` auto-selects via the
    same predicate `flash_schedule` reports; tests pass True/False to
    pin a path (True on a non-compactable or over-budget shape is an
    error — the fused kernel only exists on the compact grid)."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    bq = _pick_block(block_q, sq)
    bk = _pick_block(block_k, sk)
    scale = 1.0 / math.sqrt(d)
    steps, _, compact = _grid_steps(causal, sq, sk, bq, bk)
    nq, nk = sq // bq, sk // bk
    if fused is None:
        fused = _bwd_fused(
            causal, sq, sk, bq, bk, d, q.dtype.itemsize, packed
        )
    elif fused:
        if not _compactable(causal, sq, sk, bq, bk):
            raise ValueError(
                "fused flash backward requires the compact causal grid "
                f"(causal self-attention, square blocks); got "
                f"causal={causal} sq={sq} sk={sk} bq={bq} bk={bk}"
            )
        vmem = _fused_vmem_bytes(sq, bq, bk, d, q.dtype.itemsize, packed)
        if vmem > _FUSED_VMEM_BUDGET:
            raise ValueError(
                "fused flash backward forced on an over-budget shape: "
                f"the dq ring + accumulators need {vmem / 2**20:.1f} MiB "
                f"of VMEM (budget {_FUSED_VMEM_BUDGET / 2**20:.0f} MiB) "
                "— use the two-pass path"
            )
    kw = dict(
        scale=scale, causal=causal, bq=bq, bk=bk, kv_len=kv_len,
        packed=packed,
    )

    def _row_specs(qidx, kidx):
        # q/do/lse/delta ride the q-block index, k/v the k-block index.
        return [
            pl.BlockSpec((1, bq, d), lambda *a: (a[0], qidx(*a[1:]), 0)),
            pl.BlockSpec((1, bk, d), lambda *a: (a[0], kidx(*a[1:]), 0)),
            pl.BlockSpec((1, bk, d), lambda *a: (a[0], kidx(*a[1:]), 0)),
            pl.BlockSpec((1, bq, d), lambda *a: (a[0], qidx(*a[1:]), 0)),
            pl.BlockSpec(
                _lse_block(bq, packed), lambda *a: (a[0], qidx(*a[1:]), 0)
            ),
            pl.BlockSpec(
                _lse_block(bq, packed), lambda *a: (a[0], qidx(*a[1:]), 0)
            ),
        ]

    if fused:
        # One pass over the triangle, column-major: dk/dv per-column
        # accumulators + the dq ring (see `_dqkv_kernel_fused`). All
        # three outputs ride the column index. The cost estimate counts
        # the 5 block matmuls (the two-pass path re-derives s/dp and
        # pays 7) and the modeled one-pass HBM bytes.
        rows_c, cols_c = _tri_tables(nq, "col")
        cost = pl.CostEstimate(
            flops=10 * bh * steps * bq * bk * d,
            bytes_accessed=bh * (
                _bwd_hbm_bytes(
                    causal, sq, sk, bq, bk, d, q.dtype.itemsize, packed,
                    True,
                )
                - 2 * sq * d * q.dtype.itemsize  # delta precompute's share
                - _lse_bytes_of(sq, packed)
            ),
            transcendentals=bh * steps * bq * bk,
        )
        col_idx = lambda b, t, rs, cs: (b, cs[t], 0)
        dq, dk, dv = pl.pallas_call(
            functools.partial(_dqkv_kernel_fused, nq=nq, **kw),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(bh, steps),
                in_specs=_row_specs(
                    lambda t, rs, cs: rs[t], lambda t, rs, cs: cs[t]
                ),
                out_specs=[
                    pl.BlockSpec((1, bq, d), col_idx),
                    pl.BlockSpec((1, bk, d), col_idx),
                    pl.BlockSpec((1, bk, d), col_idx),
                ],
                scratch_shapes=[
                    pltpu.VMEM((nq * bq, d), jnp.float32),  # dq ring
                    pltpu.VMEM((bk, d), jnp.float32),
                    pltpu.VMEM((bk, d), jnp.float32),
                ],
            ),
            out_shape=[
                jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
                jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
                jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
            ],
            cost_estimate=cost,
            interpret=interpret,
        )(rows_c, cols_c, q, k, v, do, lse, delta)
        return dq, dk, dv

    if compact:
        rows, cols = _tri_tables(nq, "row")
        dq = pl.pallas_call(
            functools.partial(_dq_kernel_compact, **kw),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(bh, steps),
                in_specs=_row_specs(
                    lambda t, rs, cs: rs[t], lambda t, rs, cs: cs[t]
                ),
                out_specs=pl.BlockSpec(
                    (1, bq, d), lambda b, t, rs, cs: (b, rs[t], 0)
                ),
                scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
            ),
            out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            interpret=interpret,
        )(rows, cols, q, k, v, do, lse, delta)
        rows_c, cols_c = _tri_tables(nq, "col")
        dk, dv = pl.pallas_call(
            functools.partial(_dkv_kernel_compact, nq=nq, **kw),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(bh, steps),
                in_specs=_row_specs(
                    lambda t, rs, cs: rs[t], lambda t, rs, cs: cs[t]
                ),
                out_specs=[
                    pl.BlockSpec(
                        (1, bk, d), lambda b, t, rs, cs: (b, cs[t], 0)
                    ),
                    pl.BlockSpec(
                        (1, bk, d), lambda b, t, rs, cs: (b, cs[t], 0)
                    ),
                ],
                scratch_shapes=[
                    pltpu.VMEM((bk, d), jnp.float32),
                    pltpu.VMEM((bk, d), jnp.float32),
                ],
            ),
            out_shape=[
                jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
                jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
            ],
            interpret=interpret,
        )(rows_c, cols_c, q, k, v, do, lse, delta)
        return dq, dk, dv

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, **kw),
        grid=(bh, nq, nk),
        in_specs=_row_specs(
            lambda i, j: i,
            lambda i, j: _clamp_j(i, j, bq, bk, causal),
        ),
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, **kw),
        grid=(bh, nk, nq),
        in_specs=_row_specs(
            lambda j, i: _clamp_i(i, j, bq, bk, causal),
            lambda j, i: j,
        ),
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


def _flash_bwd_impl(
    q, k, v, o, lse, do, causal, block_q, block_k, interpret,
    kv_len=None, packed=False,
):
    delta = _flash_delta_impl(o, do, block_q, interpret, packed)
    return _flash_bwd_kernels(
        q, k, v, do, lse, delta, causal, block_q, block_k, interpret,
        kv_len, packed,
    )


# -- custom VJP --------------------------------------------------------------


def _residual_packed(sq: int, block_q: int, bwd_block_q: int) -> bool:
    return _lse_is_packed(
        sq, _pick_block(block_q, sq), _pick_block(bwd_block_q, sq)
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash_bhsd(q, k, v, causal, block_q, block_k, bwd_block_q, bwd_block_k,
                interpret, kv_len):
    """Returns (o, lse). The lse output carries NO cotangent path (its
    incoming gradient is discarded in the VJP) — it exists so callers
    and `remat_policy="flash"` can hold the softmax statistics."""
    packed = _residual_packed(q.shape[1], block_q, bwd_block_q)
    o, lse = _flash_fwd_impl(
        q, k, v, causal, block_q, block_k, interpret, kv_len, packed
    )
    if not packed:
        lse = lse[:, :, :1]
    o = checkpoint_name(o, CHECKPOINT_OUT_NAME)
    lse = checkpoint_name(lse, CHECKPOINT_LSE_NAME)
    return o, lse


def _flash_vjp_fwd(q, k, v, causal, block_q, block_k, bwd_block_q,
                   bwd_block_k, interpret, kv_len):
    packed = _residual_packed(q.shape[1], block_q, bwd_block_q)
    o, lse = _flash_fwd_impl(
        q, k, v, causal, block_q, block_k, interpret, kv_len, packed
    )
    # Residual slimming: in the packed layout the lse residual is already
    # exactly the information (1/128th the old lane-replicated buffer);
    # the replicated fallback keeps one lane and re-broadcasts in bwd.
    # checkpoint_name AFTER slimming, so remat_policy="flash" saves the
    # slim form — these named values are both the primal outputs and the
    # VJP residuals, which is what lets a checkpoint policy that saves
    # them dead-code-eliminate the forward kernel from the backward.
    if not packed:
        lse = lse[:, :, :1]
    o = checkpoint_name(o, CHECKPOINT_OUT_NAME)
    lse = checkpoint_name(lse, CHECKPOINT_LSE_NAME)
    return (o, lse), (q, k, v, o, lse)


def _flash_vjp_bwd(causal, block_q, block_k, bwd_block_q, bwd_block_k,
                   interpret, kv_len, residuals, cts):
    q, k, v, o, lse = residuals
    do, _ = cts  # the lse output is statistics-only; its cotangent drops
    packed = _residual_packed(q.shape[1], block_q, bwd_block_q)
    lse_layout = _rows_to_layout(_lse_rows(lse, q.shape[1]), packed)
    return _flash_bwd_impl(
        q, k, v, o, lse_layout, do, causal, bwd_block_q, bwd_block_k,
        interpret, kv_len, packed,
    )


_flash_bhsd.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = True,
    block_q: int = 1024,
    block_k: int = 1024,
    bwd_block_q: int | None = None,
    bwd_block_k: int | None = None,
    interpret: bool | None = None,
    return_lse: bool = False,
):
    """Blockwise attention on the MXU. q, k, v: [B, S, H, D] → [B, S, H, D].

    Numerically matches ``dense_attention`` (same online-softmax math) while
    never materializing the [S, S] score matrix in HBM — at S=8192 the
    dense path OOMs a 16 GB v5e chip outright; this runs. ``interpret=None``
    autodetects: compiled on TPU, Pallas interpreter elsewhere (tests).

    Sequence lengths that don't divide into 8-aligned blocks are padded
    internally to the next lane multiple; the tail is masked in-kernel
    and sliced off the output, so ragged lengths run this kernel instead
    of falling back to the dense O(S²) path. Causal self-attention runs
    the compact triangular grid (see module docstring): ~half the grid
    steps of the rectangular schedule at large S.

    ``return_lse=True`` additionally returns the log-sum-exp as
    [B, H, S] (float32). The lse return is statistics-only: no gradient
    flows through it.

    Default blocks come from a v5e sweep (B=4, H=16, D=128, causal,
    serialized timing): (1024, 1024) beats the small-block configs at
    every length — vs (256, 512): fwd 43.0 vs 26.6 TF/s at S=8k and 67.9
    vs 34.7 TF/s at S=16k (fwd+bwd 85.2 vs 47.4 TF/s); 2048-wide blocks
    fail to compile (VMEM). Blocks clamp to the sequence and degrade to a
    lane-aligned divisor, so short sequences are unaffected.
    """
    b, sq, h, d = q.shape
    sk = k.shape[1]
    interp = _auto_interpret(interpret)
    sp_q = _pad_to_tileable(block_q, sq)
    sp_k = _pad_to_tileable(block_k, sk)
    kv_len = sk if sp_k != sk else None
    if sp_q != sq or sp_k != sk:
        pad = lambda x, s: jnp.pad(
            x, ((0, 0), (0, s - x.shape[1]), (0, 0), (0, 0))
        )
        q, k, v = pad(q, sp_q), pad(k, sp_k), pad(v, sp_k)
    # [B, S, H, D] → [B*H, S, D]: head-major layout keeps each grid step's
    # blocks contiguous in HBM.
    to_bhsd = lambda x: x.transpose(0, 2, 1, 3).reshape(
        b * h, x.shape[1], d
    )
    # The backward kernels carry bigger VMEM footprints (extra f32
    # accumulators, and the fused one-pass kernel's dq ring), so wide
    # forward tiles can be paired with safer backward tiles; default =
    # same blocks both ways. Note the fused backward needs SQUARE bwd
    # blocks (compact grid) — asymmetric pairs fall back to two-pass.
    o, lse = _flash_bhsd(
        to_bhsd(q), to_bhsd(k), to_bhsd(v), causal, block_q, block_k,
        bwd_block_q or block_q, bwd_block_k or block_k, interp, kv_len,
    )
    o = o.reshape(b, h, sp_q, d).transpose(0, 2, 1, 3)
    if sp_q != sq:
        o = o[:, :sq]
    if not return_lse:
        return o
    lse_rows = _lse_rows(lse, sp_q).reshape(b, h, sp_q)[:, :, :sq]
    return o, lse_rows


def flash_usable(seq_q: int, seq_k: int, block_q: int = 1024,
                 block_k: int = 1024) -> bool:
    """True when `flash_attention` can run these shapes — which, since
    ragged lengths pad internally, is any positive pair. Kept as the
    dispatch predicate (`models/transformer._attend`) so call sites
    don't hard-code the padding contract."""
    del block_q, block_k
    return seq_q >= 1 and seq_k >= 1


def flash_kernel_tileable(seq: int, block: int = 1024) -> bool:
    """True when `seq` divides into 8-aligned flash blocks WITHOUT
    padding. The ring path needs this (chunks must stay congruent across
    hops, so it cannot pad); everything else should use `flash_usable`."""
    return _tileable(block, seq)


# -- ring flash: sequence-parallel flash attention --------------------------
#
# The long-context composition the platform's sp axis exists for: each
# device holds a sequence chunk, K/V chunks rotate around the ring
# (`ops/attention.ring_attention` topology), and every hop runs the
# Pallas kernel instead of materializing the [C, C] score matrix —
# blockwise-parallel ring attention. Per-hop (o_i, lse_i) pairs merge
# with the standard log-sum-exp algebra; the backward re-walks the ring
# passing the GLOBAL (o, lse) into the kernel's bwd (whose
# p = exp(s - lse) and delta = rowsum(do*o) are then the global softmax
# weights), accumulating dk/dv in the rotating frame and delivering them
# home with one final rotation. delta is the SAME for every hop (it
# depends only on the global o/do), so the shared-delta precompute runs
# once per backward, not once per hop.


def _flat_heads(x):
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _unflat_heads(x, b, h):
    bh, s, d = x.shape
    return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def _ring_packed(chunk: int, bq: int) -> bool:
    return _lse_is_packed(chunk, _pick_block(bq, chunk))


def _hop_branches(qf, kf, vf, bq, bk, interpret):
    """(full, diagonal, skip) branch thunks for one ring hop — the hop
    kind is data-dependent (axis_index), the kernel's causal flag is
    static, so lax.switch picks among three static traces. Each branch
    returns (o, lse) with lse in per-row [BH, C, 1] form."""
    bh, c, d = qf.shape
    packed = _ring_packed(c, bq)

    def full_blk():
        o, lse = _flash_fwd_impl(qf, kf, vf, False, bq, bk, interpret,
                                 None, packed)
        return o, _lse_rows(lse, c)

    def diag_blk():
        o, lse = _flash_fwd_impl(qf, kf, vf, True, bq, bk, interpret,
                                 None, packed)
        return o, _lse_rows(lse, c)

    def skip_blk():
        return (
            jnp.zeros((bh, c, d), qf.dtype),
            jnp.full((bh, c, 1), _NEG_INF, jnp.float32),
        )

    return (full_blk, diag_blk, skip_blk)


def _hop_index(src, my):
    # 0 = full (earlier chunk), 1 = diagonal (own chunk), 2 = skip
    # (later chunk — fully masked under causality).
    return jnp.where(src == my, 1, jnp.where(src < my, 0, 2))


def _ring_rotate(x, axis: str, n: int):
    # One helper for both attention modules: the dense-hop ring and the
    # flash-hop ring MUST share the same permutation direction.
    from kubeflow_tpu.ops.attention import _rotate

    return _rotate(x, axis, n)


def _ring_flash_fwd_pass(q, k, v, axis, causal, bq, bk, interpret):
    from kubeflow_tpu.parallel.collectives import axis_size

    b, c, h, d = q.shape
    n = axis_size(axis)
    my = lax.axis_index(axis)
    qf = _flat_heads(q)
    bh = b * h

    acc = jnp.zeros((bh, c, d), jnp.float32)
    m = jnp.full((bh, c, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((bh, c, 1), jnp.float32)
    k_cur, v_cur = k, v
    for i in range(n):
        src = (my - i) % n
        branches = _hop_branches(
            qf, _flat_heads(k_cur), _flat_heads(v_cur), bq, bk, interpret
        )
        if causal:
            o_i, lse_i = lax.switch(_hop_index(src, my), branches)
        else:
            o_i, lse_i = branches[0]()
        # Log-sum-exp merge of the hop's normalized output into the
        # running global softmax (same algebra as the kernel's own
        # online accumulation, one level up), in per-row [BH, C, 1]
        # space — the lane-replicated merge buffers are gone with the
        # packed lse layout.
        m_new = jnp.maximum(m, lse_i)
        corr = jnp.where(m == _NEG_INF, 0.0, jnp.exp(m - m_new))
        w = jnp.where(lse_i == _NEG_INF, 0.0, jnp.exp(lse_i - m_new))
        acc = acc * corr + w * o_i.astype(jnp.float32)
        l = l * corr + w
        m = m_new
        if i + 1 < n:
            k_cur = _ring_rotate(k_cur, axis, n)
            v_cur = _ring_rotate(v_cur, axis, n)

    safe_l = jnp.where(l == 0.0, 1.0, l)
    o = (acc / safe_l).astype(q.dtype)
    lse_tot = m + jnp.log(safe_l)  # [BH, C, 1] rows form
    return _unflat_heads(o, b, h), lse_tot


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _ring_flash_body(q, k, v, axis, causal, bq, bk, interpret):
    o, lse = _ring_flash_fwd_pass(q, k, v, axis, causal, bq, bk, interpret)
    o = checkpoint_name(o, CHECKPOINT_OUT_NAME)
    del lse
    return o

def _ring_flash_body_fwd(q, k, v, axis, causal, bq, bk, interpret):
    o, lse = _ring_flash_fwd_pass(q, k, v, axis, causal, bq, bk, interpret)
    # The global lse rides in per-row [BH, C, 1] form — already slim.
    # Named so remat_policy="flash" can pin (o, lse) and skip re-walking
    # the forward ring inside the backward.
    o = checkpoint_name(o, CHECKPOINT_OUT_NAME)
    lse = checkpoint_name(lse, CHECKPOINT_LSE_NAME)
    return o, (q, k, v, o, lse)


def _ring_flash_body_bwd(axis, causal, bq, bk, interpret, residuals, do):
    from kubeflow_tpu.parallel.collectives import axis_size

    q, k, v, o, lse_rows = residuals
    b, c, h, d = q.shape
    n = axis_size(axis)
    my = lax.axis_index(axis)
    qf, of, dof = _flat_heads(q), _flat_heads(o), _flat_heads(do)
    bh = b * h
    packed = _ring_packed(c, bq)
    lse_layout = _rows_to_layout(lse_rows, packed)
    # Shared delta across the whole ring: delta = rowsum(do ∘ o) depends
    # only on the GLOBAL output and its cotangent, which every hop
    # shares — one precompute pass feeds all n hops' dq/dkv kernels.
    delta = _flash_delta_impl(of, dof, bq, interpret, packed)

    dq = jnp.zeros((bh, c, d), jnp.float32)
    # dk/dv accumulate in the ROTATING frame: each hop adds its
    # contribution to the chunk currently held, and the accumulators
    # travel with the chunk.
    k_cur, v_cur = k, v
    dk_cur = jnp.zeros((bh, c, d), jnp.float32)
    dv_cur = jnp.zeros((bh, c, d), jnp.float32)
    for i in range(n):
        src = (my - i) % n
        kf, vf = _flat_heads(k_cur), _flat_heads(v_cur)

        def full_blk():
            return _flash_bwd_kernels(
                qf, kf, vf, dof, lse_layout, delta, False, bq, bk,
                interpret, None, packed,
            )

        def diag_blk():
            return _flash_bwd_kernels(
                qf, kf, vf, dof, lse_layout, delta, True, bq, bk,
                interpret, None, packed,
            )

        def skip_blk():
            z = jnp.zeros((bh, c, d), q.dtype)
            return z, z, z

        if causal:
            dq_i, dk_i, dv_i = lax.switch(
                _hop_index(src, my), (full_blk, diag_blk, skip_blk)
            )
        else:
            dq_i, dk_i, dv_i = full_blk()
        dq = dq + dq_i.astype(jnp.float32)
        dk_cur = dk_cur + dk_i.astype(jnp.float32)
        dv_cur = dv_cur + dv_i.astype(jnp.float32)
        if i + 1 < n:
            k_cur = _ring_rotate(k_cur, axis, n)
            v_cur = _ring_rotate(v_cur, axis, n)
            dk_cur = _ring_rotate(dk_cur, axis, n)
            dv_cur = _ring_rotate(dv_cur, axis, n)
    # After n-1 rotations the chunk (and its gradient) sits one hop
    # short of home — one final rotation delivers dk/dv to their owners.
    dk_home = _ring_rotate(dk_cur, axis, n)
    dv_home = _ring_rotate(dv_cur, axis, n)
    return (
        _unflat_heads(dq, b, h).astype(q.dtype),
        _unflat_heads(dk_home, b, h).astype(k.dtype),
        _unflat_heads(dv_home, b, h).astype(v.dtype),
    )


_ring_flash_body.defvjp(_ring_flash_body_fwd, _ring_flash_body_bwd)


def ring_flash_attention(
    q,
    k,
    v,
    mesh,
    *,
    causal: bool = True,
    sp_axis: str = "sp",
    heads_axis: str | None = "tp",
    block_q: int = 1024,
    block_k: int = 1024,
    interpret: bool | None = None,
):
    """Sequence-parallel flash attention over `mesh`'s sp ring.

    q, k, v: GLOBAL [B, S, H, D]; S divides by the ring, H by tp. Each
    hop runs the Pallas kernel on the local [C, C] tile (C = S/ring), so
    per-device attention memory is O(C·D) instead of O(C²) — the
    composition that takes the single-chip S=16k flash ceiling to
    ring-size × 16k. Differentiable end-to-end (custom VJP re-walks the
    ring with global statistics). Falls back to single-device flash when
    the ring is trivial. Ring chunks must tile WITHOUT padding
    (`flash_kernel_tileable`): padded chunks would de-synchronize the
    hop algebra."""
    if mesh.shape.get(sp_axis, 1) == 1:
        return flash_attention(
            q, k, v, causal=causal, block_q=block_q, block_k=block_k,
            interpret=interpret,
        )
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from kubeflow_tpu.parallel.sharding import batch_axes

    ring = mesh.shape[sp_axis]
    if q.shape[1] % ring:
        raise ValueError(
            f"ring flash attention: sequence length {q.shape[1]} does "
            f"not divide the {sp_axis!r} ring size {ring}"
        )
    chunk = q.shape[1] // ring
    if not flash_kernel_tileable(chunk, block_q) or not (
        flash_kernel_tileable(chunk, block_k)
    ):
        raise ValueError(
            f"ring flash attention: per-device chunk {chunk} does not "
            "divide into 8-aligned flash blocks (the ring cannot pad); "
            "use ring_attention or resize the sp axis"
        )
    spec = P(batch_axes(mesh), sp_axis, heads_axis, None)
    interp = _auto_interpret(interpret)

    def body(q_, k_, v_):
        # nondiff custom_vjp args must be positional, so no partial().
        return _ring_flash_body(
            q_, k_, v_, sp_axis, causal, block_q, block_k, interp
        )

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_rep=False,
    )(q, k, v)
