"""Parallelism core: meshes, sharding rules, collectives, process bootstrap.

This package is the TPU-native replacement for everything the reference
outsourced to TensorFlow's gRPC parameter-server runtime and OpenMPI/Horovod
(SURVEY.md §2.2): parallelism is expressed as axes of a
``jax.sharding.Mesh`` and XLA collectives over ICI (in-slice) and DCN
(cross-slice), not as replica processes pushing gradients over Ethernet.
"""

from kubeflow_tpu.parallel.mesh import (
    AXES,
    MeshSpec,
    build_hybrid_mesh,
    build_mesh,
    local_mesh_spec,
    mesh_spec_of,
    resize_spec,
)
from kubeflow_tpu.parallel.sharding import (
    LogicalRules,
    batch_shard_count,
    batch_sharding,
    default_rules,
    logical_sharding,
    named_sharding,
    replicated,
    shard_pytree,
)
from kubeflow_tpu.parallel.distributed import (
    ProcessEnv,
    initialize_from_env,
)
from kubeflow_tpu.parallel.pipeline import (
    bubble_fraction,
    pipeline_schedule,
    spmd_pipeline,
)
