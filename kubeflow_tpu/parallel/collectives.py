"""Collective helpers for `shard_map` code.

The reference's collectives lived outside the repo entirely (TF's gRPC
parameter server and Horovod's NCCL ring — SURVEY.md §2.2 "Communication
backends"). Here they are XLA collectives over ICI/DCN, wrapped only thinly:
the wrappers add ring-neighbor index math (the part that is easy to get wrong)
and keep call sites readable. Everything is usable only inside
`jax.shard_map` / `pjit`-traced code.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax


def axis_size(axis: str) -> int:
    """Static size of a named mesh axis, from inside traced code.

    `lax.axis_size` comes and goes across jax versions (absent in the
    pinned 0.4.x); `core.axis_frame(name)` resolves the same static int
    from the axis environment, which is what the ring loops need — the
    hop count must be a Python int so the ring unrolls at trace time.
    """
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis)
    from jax import core

    return core.axis_frame(axis)


def axis_index(axis: str) -> jax.Array:
    return lax.axis_index(axis)


def psum(x: Any, axis: str | tuple[str, ...]) -> Any:
    return lax.psum(x, axis)


def pmean(x: Any, axis: str | tuple[str, ...]) -> Any:
    return lax.pmean(x, axis)


def all_gather(x: Any, axis: str, *, tiled: bool = True, gather_axis: int = 0) -> Any:
    """Gather shards along `axis`; tiled=True concatenates on `gather_axis`."""
    return lax.all_gather(x, axis, axis=gather_axis, tiled=tiled)


def reduce_scatter(x: Any, axis: str, *, scatter_axis: int = 0) -> Any:
    """Sum over `axis` then keep this device's 1/n slice of `scatter_axis`."""
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_axis, tiled=True)


def all_to_all(x: Any, axis: str, *, split_axis: int, concat_axis: int) -> Any:
    """The EP/MoE dispatch primitive (and Ulysses-style sequence exchange)."""
    return lax.all_to_all(
        x, axis, split_axis=split_axis, concat_axis=concat_axis, tiled=True
    )


def ppermute_ring(x: Any, axis: str, *, shift: int = 1) -> Any:
    """Rotate shards around the `axis` ring by `shift` (ring attention's hop).

    perm[i] = (i + shift) % n, i.e. every device sends its shard `shift`
    neighbors "up" the ring; on TPU this lowers to nearest-neighbor ICI
    transfers when `axis` is an innermost mesh axis.
    """
    n = axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm=perm)


def psum_ring_bidirectional(x: Any, axis: str) -> Any:
    """psum over `axis`; name documents intent at call sites where the ring
    (not tree) algorithm is what XLA will pick on a torus axis."""
    return lax.psum(x, axis)


def unreplicate(tree: Any) -> Any:
    """Host-side: fetch fully-replicated arrays as single host values."""
    return jax.tree_util.tree_map(lambda x: jax.device_get(x), tree)
