"""Multi-process bootstrap: the TpuJob env contract.

The reference's tf-operator injected a ``TF_CONFIG`` JSON blob that each pod
parsed into parameter-server CLI flags
(`tf-controller-examples/tf-cnn/launcher.py:68-88`). The TPU-native
equivalent is a flat env contract that the TpuJob operator injects into every
pod of a gang and that maps 1:1 onto ``jax.distributed.initialize``:

    TPUJOB_COORDINATOR    host:port of process 0 (the JAX coordinator)
    TPUJOB_NUM_PROCESSES  total processes in the gang
    TPUJOB_PROCESS_ID     this process's rank
    TPUJOB_NUM_SLICES     number of TPU slices (multi-slice over DCN); def 1
    TPUJOB_SLICE_ID       which slice this process belongs to; default 0

Within a slice collectives ride ICI; across slices XLA routes the outer mesh
axes over DCN (`jax.sharding` handles both through the same Mesh).
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Mapping

log = logging.getLogger(__name__)

ENV_COORDINATOR = "TPUJOB_COORDINATOR"
ENV_NUM_PROCESSES = "TPUJOB_NUM_PROCESSES"
ENV_PROCESS_ID = "TPUJOB_PROCESS_ID"
ENV_NUM_SLICES = "TPUJOB_NUM_SLICES"
ENV_SLICE_ID = "TPUJOB_SLICE_ID"


@dataclasses.dataclass(frozen=True)
class ProcessEnv:
    """Parsed gang membership for one process."""

    coordinator: str | None = None
    num_processes: int = 1
    process_id: int = 0
    num_slices: int = 1
    slice_id: int = 0

    @classmethod
    def from_env(cls, env: Mapping[str, str] | None = None) -> "ProcessEnv":
        env = os.environ if env is None else env
        pe = cls(
            coordinator=env.get(ENV_COORDINATOR),
            num_processes=int(env.get(ENV_NUM_PROCESSES, "1")),
            process_id=int(env.get(ENV_PROCESS_ID, "0")),
            num_slices=int(env.get(ENV_NUM_SLICES, "1")),
            slice_id=int(env.get(ENV_SLICE_ID, "0")),
        )
        pe.validate()
        return pe

    def validate(self) -> None:
        if self.num_processes < 1:
            raise ValueError(f"num_processes must be >= 1, got {self.num_processes}")
        if not 0 <= self.process_id < self.num_processes:
            raise ValueError(
                f"process_id {self.process_id} out of range [0, {self.num_processes})"
            )
        if self.num_processes > 1 and not self.coordinator:
            raise ValueError(
                f"{ENV_COORDINATOR} is required when {ENV_NUM_PROCESSES} > 1"
            )
        if self.num_slices < 1 or not 0 <= self.slice_id < self.num_slices:
            raise ValueError(
                f"slice_id {self.slice_id} out of range [0, {self.num_slices})"
            )
        if self.num_processes % self.num_slices:
            raise ValueError(
                f"num_processes {self.num_processes} not divisible by "
                f"num_slices {self.num_slices}"
            )

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0

    def to_env(self) -> dict[str, str]:
        """The operator-side inverse of from_env: env to inject into a pod."""
        out = {
            ENV_NUM_PROCESSES: str(self.num_processes),
            ENV_PROCESS_ID: str(self.process_id),
            ENV_NUM_SLICES: str(self.num_slices),
            ENV_SLICE_ID: str(self.slice_id),
        }
        if self.coordinator:
            out[ENV_COORDINATOR] = self.coordinator
        return out


def initialize_from_env(env: Mapping[str, str] | None = None) -> ProcessEnv:
    """Initialize `jax.distributed` from the TpuJob env contract.

    Single-process gangs (the default, and every test) skip initialization
    entirely, so this is safe to call unconditionally at trainer startup —
    the same way the reference's launcher ran identically with and without
    TF_CONFIG present.

    Multi-slice gangs additionally export the MEGASCALE_* variables that
    libtpu's DCN transport reads, so cross-slice collectives are configured
    before the backend initializes. (jax.distributed itself only sees the
    flat process gang; slice structure is a runtime concern.)
    """
    pe = ProcessEnv.from_env(env)
    if pe.num_slices > 1:
        os.environ.setdefault("MEGASCALE_NUM_SLICES", str(pe.num_slices))
        os.environ.setdefault("MEGASCALE_SLICE_ID", str(pe.slice_id))
        if pe.coordinator:
            os.environ.setdefault(
                "MEGASCALE_COORDINATOR_ADDRESS", pe.coordinator
            )
    if pe.num_processes > 1:
        import jax

        log.info(
            "jax.distributed.initialize coordinator=%s rank=%d/%d slice=%d/%d",
            pe.coordinator, pe.process_id, pe.num_processes, pe.slice_id,
            pe.num_slices,
        )
        jax.distributed.initialize(
            coordinator_address=pe.coordinator,
            num_processes=pe.num_processes,
            process_id=pe.process_id,
        )
    return pe
