"""Device-mesh construction for TPU slices.

The reference scaled training only by adding replica processes (PS/worker
TFJobs, `tf-controller-examples/tf-cnn/launcher.py:68-88`; Horovod rings,
`components/openmpi-controller/controller/controller.py`). Here every
parallelism strategy — including the ones the reference lacked entirely
(tensor, pipeline, sequence/context, expert; SURVEY.md §2.2) — is an axis of
one `jax.sharding.Mesh`:

    pp    pipeline-parallel stages (slowest-varying; stage boundaries cross
          the fewest ICI links and tolerate DCN in multi-slice layouts)
    dp    pure data parallel (gradient psum only)
    fsdp  data parallel with fully-sharded parameters (ZeRO-3 style:
          all-gather params, reduce-scatter grads)
    sp    sequence/context parallel (ring attention shifts ride this axis)
    ep    expert parallel (MoE all-to-all rides this axis)
    tp    tensor parallel (fastest-varying so its all-reduces ride
          nearest-neighbor ICI links)

Axis order is part of the performance contract: `mesh_utils.create_device_mesh`
maps the last mesh axis onto physically adjacent chips, so the axis with the
chattiest collectives (tp) must come last and the one that can tolerate DCN
(pp, then dp) first.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

# Mesh axis names, slowest-varying (outermost, DCN-tolerant) first.
AXES: tuple[str, ...] = ("pp", "dp", "fsdp", "sp", "ep", "tp")

# Axes over which a *global data batch* is split. `sp` and `ep` shard
# activations (tokens within an example / experts), `tp` shards features,
# `pp` shards layers — none of those divide the batch.
BATCH_AXES: tuple[str, ...] = ("dp", "fsdp")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """A named parallelism layout.

    Each field is the size of one mesh axis. At most one axis may be -1,
    meaning "fill with all remaining devices" — the usual idiom is
    ``MeshSpec(fsdp=-1)`` for pure FSDP or ``MeshSpec(dp=-1)`` for pure DP.
    """

    pp: int = 1
    dp: int = 1
    fsdp: int = 1
    sp: int = 1
    ep: int = 1
    tp: int = 1

    def sizes(self) -> tuple[int, ...]:
        return tuple(getattr(self, a) for a in AXES)

    def resolve(self, n_devices: int) -> "MeshSpec":
        """Resolve a single -1 axis against the device count and validate."""
        sizes = list(self.sizes())
        if any(s < 1 and s != -1 for s in sizes):
            raise ValueError(f"mesh axis sizes must be >= 1 (or -1 to infer): {self}")
        wild = [i for i, s in enumerate(sizes) if s == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one mesh axis may be -1, got {self}")
        if wild:
            fixed = math.prod(s for s in sizes if s != -1)
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes of {self}"
                )
            sizes[wild[0]] = n_devices // fixed
        if math.prod(sizes) != n_devices:
            raise ValueError(
                f"mesh {dict(zip(AXES, sizes))} needs {math.prod(sizes)} devices, "
                f"have {n_devices}"
            )
        return MeshSpec(**dict(zip(AXES, sizes)))

    @property
    def data_parallelism(self) -> int:
        return self.dp * self.fsdp


def build_mesh(
    spec: MeshSpec | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a `jax.sharding.Mesh` for `spec` over `devices`.

    Uses `mesh_utils.create_device_mesh` so the logical axes are laid out
    along the physical ICI topology (it understands TPU 2D/3D torus wraps);
    falls back to a plain reshape for CPU/virtual device sets where there is
    no topology to exploit.
    """
    devices = list(devices if devices is not None else jax.devices())
    spec = (spec or MeshSpec(dp=-1)).resolve(len(devices))
    shape = spec.sizes()
    try:
        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except (ValueError, NotImplementedError):
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXES)


def build_hybrid_mesh(
    ici: MeshSpec,
    dcn: MeshSpec,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Multi-slice mesh: `ici` axes laid out inside each TPU slice, `dcn`
    axes spanning slices over the data-center network.

    This is the megascale layout (SURVEY.md §2.2 "DCN multi-slice"): the
    DCN axes must carry only bandwidth-tolerant collectives — put dp or
    pp there (gradient psum once per step, or pipeline bubbles), never
    tp/sp whose per-layer collectives would serialize on DCN latency.
    The per-axis mesh size is ici_axis * dcn_axis; shardings address the
    combined axis by its usual name, so models are layout-agnostic.

    Uses `mesh_utils.create_hybrid_device_mesh` on real TPU slices (it
    reads each device's slice_index); virtual/CPU device sets fall back
    to grouping consecutive devices into equal "slices".
    """
    devices = list(devices if devices is not None else jax.devices())
    if any(s == -1 for s in dcn.sizes()):
        raise ValueError("dcn axes must be explicit (no -1): slice count "
                         "is physical, not inferred")
    n_slices = math.prod(dcn.sizes())
    if n_slices < 1 or len(devices) % n_slices:
        raise ValueError(
            f"{len(devices)} devices not divisible into {n_slices} slices"
        )
    per_slice = len(devices) // n_slices
    ici = ici.resolve(per_slice)
    sizes = tuple(
        i * d for i, d in zip(ici.sizes(), dcn.sizes())
    )
    try:
        dev_array = mesh_utils.create_hybrid_device_mesh(
            ici.sizes(), dcn.sizes(), devices=devices
        )
    except (ValueError, NotImplementedError, AttributeError, KeyError):
        # Virtual devices carry no slice topology: emulate slices as
        # consecutive device groups. Build a [dcn..., ici...] array then
        # interleave to [ici*dcn combined axes].
        slices = [
            np.asarray(devices[s * per_slice:(s + 1) * per_slice]).reshape(
                ici.sizes()
            )
            for s in range(n_slices)
        ]
        outer = np.empty(tuple(dcn.sizes()) + tuple(ici.sizes()), dtype=object)
        outer.reshape(n_slices, *ici.sizes())[...] = np.stack(slices)
        # Move each dcn axis to sit just outside its ici partner, then
        # collapse the pair into one combined axis.
        k = len(AXES)
        order: list[int] = []
        for axis in range(k):
            order += [axis, k + axis]
        dev_array = outer.transpose(order).reshape(sizes)
    return Mesh(dev_array, AXES)


def mesh_spec_of(mesh: Mesh) -> MeshSpec:
    """The `MeshSpec` a mesh realizes (axis name -> axis size)."""
    shape = dict(zip(mesh.axis_names, mesh.devices.shape))
    return MeshSpec(**{a: int(shape.get(a, 1)) for a in AXES})


def resize_spec(
    spec: MeshSpec,
    new_dp: int,
    *,
    n_devices: int | None = None,
    global_batch: int | None = None,
) -> MeshSpec:
    """The elastic-resize target layout: `spec` with its dp axis set to
    `new_dp`, every other axis unchanged — validated with the divisor
    math SPELLED OUT.

    A degenerate resize target used to surface as an opaque reshape
    error deep inside sharding (``cannot reshape array of size N``);
    the elastic path validates here instead, so the preemption handler
    can refuse (and fall back to a different target, or to a restart)
    with an error that names the actual arithmetic:

    - the resized mesh needs ``new_dp * (pp*fsdp*sp*ep*tp)`` devices,
      which must not exceed what survives the preemption;
    - the GLOBAL batch is sharded over ``new_dp * fsdp`` batch shards
      (`BATCH_AXES`), so it must divide evenly — elastic resize keeps
      the global batch (and therefore the training trajectory) fixed
      and reshapes only its layout.
    """
    if new_dp < 1:
        raise ValueError(f"resize target dp must be >= 1, got {new_dp}")
    others = {a: s for a, s in zip(AXES, spec.sizes()) if a != "dp"}
    if any(s < 1 for s in others.values()):
        raise ValueError(
            f"resize requires a fully-resolved spec (no -1 axes): {spec}"
        )
    model_axes = math.prod(others.values())
    need = new_dp * model_axes
    if n_devices is not None and need > n_devices:
        factors = " * ".join(f"{a}={s}" for a, s in others.items() if s > 1)
        raise ValueError(
            f"resize to dp={new_dp} needs dp={new_dp}"
            + (f" * {factors}" if factors else "")
            + f" = {need} devices, but only {n_devices} "
            f"survive — shrink dp to at most {n_devices // max(1, model_axes)}"
        )
    batch_shards = new_dp * spec.fsdp
    if global_batch is not None and global_batch % batch_shards:
        divisors = sorted(
            d for d in range(1, global_batch + 1)
            if global_batch % (d * spec.fsdp) == 0
        )
        raise ValueError(
            f"resize to dp={new_dp} cannot shard the global batch: "
            f"{global_batch} examples over dp={new_dp} * fsdp={spec.fsdp} "
            f"= {batch_shards} batch shards leaves "
            f"{global_batch % batch_shards} examples over — elastic "
            f"resize keeps the global batch fixed, so dp must satisfy "
            f"dp * {spec.fsdp} | {global_batch} (valid dp: {divisors})"
        )
    return dataclasses.replace(spec, dp=new_dp)


def local_mesh_spec(n_devices: int | None = None, tp: int = 1, sp: int = 1) -> MeshSpec:
    """Convenience: FSDP over everything not claimed by tp/sp."""
    n = n_devices if n_devices is not None else jax.device_count()
    if n % (tp * sp):
        raise ValueError(f"{n} devices not divisible by tp={tp} * sp={sp}")
    return MeshSpec(fsdp=n // (tp * sp), sp=sp, tp=tp)
