"""Device-mesh construction for TPU slices.

The reference scaled training only by adding replica processes (PS/worker
TFJobs, `tf-controller-examples/tf-cnn/launcher.py:68-88`; Horovod rings,
`components/openmpi-controller/controller/controller.py`). Here every
parallelism strategy — including the ones the reference lacked entirely
(tensor, pipeline, sequence/context, expert; SURVEY.md §2.2) — is an axis of
one `jax.sharding.Mesh`:

    pp    pipeline-parallel stages (slowest-varying; stage boundaries cross
          the fewest ICI links and tolerate DCN in multi-slice layouts)
    dp    pure data parallel (gradient psum only)
    fsdp  data parallel with fully-sharded parameters (ZeRO-3 style:
          all-gather params, reduce-scatter grads)
    sp    sequence/context parallel (ring attention shifts ride this axis)
    ep    expert parallel (MoE all-to-all rides this axis)
    tp    tensor parallel (fastest-varying so its all-reduces ride
          nearest-neighbor ICI links)

Axis order is part of the performance contract: `mesh_utils.create_device_mesh`
maps the last mesh axis onto physically adjacent chips, so the axis with the
chattiest collectives (tp) must come last and the one that can tolerate DCN
(pp, then dp) first.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

# Mesh axis names, slowest-varying (outermost, DCN-tolerant) first.
AXES: tuple[str, ...] = ("pp", "dp", "fsdp", "sp", "ep", "tp")

# Axes over which a *global data batch* is split. `sp` and `ep` shard
# activations (tokens within an example / experts), `tp` shards features,
# `pp` shards layers — none of those divide the batch.
BATCH_AXES: tuple[str, ...] = ("dp", "fsdp")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """A named parallelism layout.

    Each field is the size of one mesh axis. At most one axis may be -1,
    meaning "fill with all remaining devices" — the usual idiom is
    ``MeshSpec(fsdp=-1)`` for pure FSDP or ``MeshSpec(dp=-1)`` for pure DP.
    """

    pp: int = 1
    dp: int = 1
    fsdp: int = 1
    sp: int = 1
    ep: int = 1
    tp: int = 1

    def sizes(self) -> tuple[int, ...]:
        return tuple(getattr(self, a) for a in AXES)

    def resolve(self, n_devices: int) -> "MeshSpec":
        """Resolve a single -1 axis against the device count and validate."""
        sizes = list(self.sizes())
        if any(s < 1 and s != -1 for s in sizes):
            raise ValueError(f"mesh axis sizes must be >= 1 (or -1 to infer): {self}")
        wild = [i for i, s in enumerate(sizes) if s == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one mesh axis may be -1, got {self}")
        if wild:
            fixed = math.prod(s for s in sizes if s != -1)
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes of {self}"
                )
            sizes[wild[0]] = n_devices // fixed
        if math.prod(sizes) != n_devices:
            raise ValueError(
                f"mesh {dict(zip(AXES, sizes))} needs {math.prod(sizes)} devices, "
                f"have {n_devices}"
            )
        return MeshSpec(**dict(zip(AXES, sizes)))

    @property
    def data_parallelism(self) -> int:
        return self.dp * self.fsdp


def build_mesh(
    spec: MeshSpec | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a `jax.sharding.Mesh` for `spec` over `devices`.

    Uses `mesh_utils.create_device_mesh` so the logical axes are laid out
    along the physical ICI topology (it understands TPU 2D/3D torus wraps);
    falls back to a plain reshape for CPU/virtual device sets where there is
    no topology to exploit.
    """
    devices = list(devices if devices is not None else jax.devices())
    spec = (spec or MeshSpec(dp=-1)).resolve(len(devices))
    shape = spec.sizes()
    try:
        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except (ValueError, NotImplementedError):
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXES)


def build_hybrid_mesh(
    ici: MeshSpec,
    dcn: MeshSpec,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Multi-slice mesh: `ici` axes laid out inside each TPU slice, `dcn`
    axes spanning slices over the data-center network.

    This is the megascale layout (SURVEY.md §2.2 "DCN multi-slice"): the
    DCN axes must carry only bandwidth-tolerant collectives — put dp or
    pp there (gradient psum once per step, or pipeline bubbles), never
    tp/sp whose per-layer collectives would serialize on DCN latency.
    The per-axis mesh size is ici_axis * dcn_axis; shardings address the
    combined axis by its usual name, so models are layout-agnostic.

    Uses `mesh_utils.create_hybrid_device_mesh` on real TPU slices (it
    reads each device's slice_index); virtual/CPU device sets fall back
    to grouping consecutive devices into equal "slices".
    """
    devices = list(devices if devices is not None else jax.devices())
    if any(s == -1 for s in dcn.sizes()):
        raise ValueError("dcn axes must be explicit (no -1): slice count "
                         "is physical, not inferred")
    n_slices = math.prod(dcn.sizes())
    if n_slices < 1 or len(devices) % n_slices:
        raise ValueError(
            f"{len(devices)} devices not divisible into {n_slices} slices"
        )
    per_slice = len(devices) // n_slices
    ici = ici.resolve(per_slice)
    sizes = tuple(
        i * d for i, d in zip(ici.sizes(), dcn.sizes())
    )
    try:
        dev_array = mesh_utils.create_hybrid_device_mesh(
            ici.sizes(), dcn.sizes(), devices=devices
        )
    except (ValueError, NotImplementedError, AttributeError, KeyError):
        # Virtual devices carry no slice topology: emulate slices as
        # consecutive device groups. Build a [dcn..., ici...] array then
        # interleave to [ici*dcn combined axes].
        slices = [
            np.asarray(devices[s * per_slice:(s + 1) * per_slice]).reshape(
                ici.sizes()
            )
            for s in range(n_slices)
        ]
        outer = np.empty(tuple(dcn.sizes()) + tuple(ici.sizes()), dtype=object)
        outer.reshape(n_slices, *ici.sizes())[...] = np.stack(slices)
        # Move each dcn axis to sit just outside its ici partner, then
        # collapse the pair into one combined axis.
        k = len(AXES)
        order: list[int] = []
        for axis in range(k):
            order += [axis, k + axis]
        dev_array = outer.transpose(order).reshape(sizes)
    return Mesh(dev_array, AXES)


def local_mesh_spec(n_devices: int | None = None, tp: int = 1, sp: int = 1) -> MeshSpec:
    """Convenience: FSDP over everything not claimed by tp/sp."""
    n = n_devices if n_devices is not None else jax.device_count()
    if n % (tp * sp):
        raise ValueError(f"{n} devices not divisible by tp={tp} * sp={sp}")
    return MeshSpec(fsdp=n // (tp * sp), sp=sp, tp=tp)
