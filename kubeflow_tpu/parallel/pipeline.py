"""SPMD pipeline parallelism: microbatched GPipe schedule over the `pp`
mesh axis.

The reference has no pipeline parallelism at all (SURVEY.md §2.2 — absent);
here it is a first-class mesh axis with an actual schedule, built the TPU
way: every pp rank runs the SAME traced program (`shard_map`), stages hand
activations to their successor with `lax.ppermute` over ICI, and the
steady-state keeps all stages busy while the `S - 1` warmup/drain ticks
are the classic pipeline bubble.

Shape contract:

- `stage_params`: a pytree whose leaves are stacked per stage on the
  leading axis (`[S, ...]`, sharded `P("pp", ...)` — logical axis name
  "stage"). Each rank slices out its own stage's parameters.
- `x`: the global batch `[B, ...]`, sharded over the batch axes (dp/fsdp)
  and replicated over pp. It is split into `num_microbatches` equal
  microbatches along axis 0.
- `stage_fn(params_slice, microbatch) -> microbatch` — pure, same output
  shape (the usual residual-block contract).

Total ticks = num_microbatches + S - 1; bubble fraction = (S-1)/ticks, so
more microbatches amortize the bubble (How-to-Scale-Your-Model's pipeline
recipe). Gradients flow through `ppermute` (it has a transpose rule), so
the same function trains under `jax.grad`.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from kubeflow_tpu.parallel.sharding import batch_axes


def spmd_pipeline(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x: jax.Array,
    *,
    mesh: Mesh,
    num_microbatches: int,
    axis: str = "pp",
) -> jax.Array:
    """Run `x` through S pipeline stages; returns the final activations
    with the same sharding as `x`."""
    n_stages = mesh.shape[axis]
    for leaf in jax.tree_util.tree_leaves(stage_params):
        if leaf.shape[0] != n_stages:
            raise ValueError(
                f"stage_params leaves must be stacked [S={n_stages}, ...]; "
                f"got leading dim {leaf.shape[0]}"
            )
    batch = tuple(batch_axes(mesh))
    batch_shards = 1
    for a in batch:
        batch_shards *= mesh.shape[a]
    local_batch, rem = divmod(x.shape[0], batch_shards)
    if rem:
        raise ValueError(
            f"batch {x.shape[0]} does not shard evenly over "
            f"{batch_shards} batch-axis devices"
        )
    if local_batch % num_microbatches:
        raise ValueError(
            f"per-shard batch {local_batch} must divide into "
            f"{num_microbatches} microbatches"
        )
    if n_stages == 1:
        # Degenerate pipeline: just apply the single stage.
        params0 = jax.tree_util.tree_map(lambda p: p[0], stage_params)
        return stage_fn(params0, x)
    param_spec = jax.tree_util.tree_map(
        lambda _: P(axis), stage_params
    )
    x_spec = P(batch)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(param_spec, x_spec),
        out_specs=x_spec,
        check_rep=False,
    )
    def run(params, local_x):
        # params leaves: [S/pp_size, ...] = [1, ...] per rank -> squeeze.
        my_params = jax.tree_util.tree_map(lambda p: p[0], params)
        stage = lax.axis_index(axis)
        mb = jnp.reshape(
            local_x,
            (num_microbatches, local_x.shape[0] // num_microbatches)
            + local_x.shape[1:],
        )
        state = jnp.zeros_like(mb[0])
        outputs = jnp.zeros_like(mb)
        ticks = num_microbatches + n_stages - 1

        def tick(t, carry):
            state, outputs = carry
            # Stage 0 injects microbatch t (clamped; masked past the end).
            inject = mb[jnp.minimum(t, num_microbatches - 1)]
            state = jnp.where(stage == 0, inject, state)
            state = stage_fn(my_params, state)
            # The last stage emits microbatch t - (S-1) once warm.
            out_idx = jnp.clip(t - (n_stages - 1), 0, num_microbatches - 1)
            emit = jnp.logical_and(
                stage == n_stages - 1, t >= n_stages - 1
            )
            outputs = outputs.at[out_idx].set(
                jnp.where(emit, state, outputs[out_idx])
            )
            # Hand off to the successor stage (ring: last -> 0, ignored
            # because stage 0 overwrites with its next injection).
            state = lax.ppermute(
                state,
                axis,
                [(i, (i + 1) % n_stages) for i in range(n_stages)],
            )
            return state, outputs

        _, outputs = lax.fori_loop(
            0, ticks, tick, (state, outputs)
        )
        # Only the last stage holds real outputs; psum over pp replicates
        # them to every rank (all other ranks contribute zeros).
        outputs = lax.psum(outputs, axis)
        return jnp.reshape(outputs, local_x.shape)

    return run(stage_params, x)


def bubble_fraction(n_stages: int, num_microbatches: int) -> float:
    """The fraction of ticks each stage idles — (S-1)/(M+S-1)."""
    return (n_stages - 1) / (num_microbatches + n_stages - 1)
