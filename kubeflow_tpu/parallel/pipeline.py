"""SPMD pipeline parallelism: microbatched GPipe and interleaved
(circular) schedules over the `pp` mesh axis.

The reference has no pipeline parallelism at all (SURVEY.md §2.2 — absent);
here it is a first-class mesh axis with an actual schedule, built the TPU
way: every pp rank runs the SAME traced program (`shard_map`), stages hand
activations to their successor with `lax.ppermute` over ICI, and the
steady-state keeps all stages busy while the warmup/drain ticks are the
classic pipeline bubble.

Two schedules, one loop:

- **GPipe** (`interleave=1`): each rank holds ONE stage slice; total loop
  ticks = `M + pp - 1`, bubble fraction `(pp-1)/(M+pp-1)`.
- **Interleaved / circular** (`interleave=v > 1`): each rank holds `v`
  NON-ADJACENT stage slices (`n_stages = v * pp`; rank r owns stages
  r, pp+r, 2pp+r, ...). A microbatch circulates the pp ring v times, so
  each loop tick applies 1/v of a rank's layers and the warmup/drain
  shrinks to `(pp-1)/v` GPipe-equivalent ticks — the bubble drops ~v×
  for the same hardware and model ("Exploring the limits of Concurrency
  in ML Training on Google TPUs", PAPERS.md). Wrapped activations wait
  their turn in a per-rank circular buffer (`M - pp` ticks at most),
  which is why `num_microbatches >= pp` is required.

Shape contract:

- `stage_params`: a pytree whose leaves are stacked per stage on the
  leading axis (`[n_stages, ...]` in pipeline order — stage `s` at index
  `s`; sharded `P("pp", ...)`, logical axis name "stage"). The
  interleaved slice-to-rank permutation is internal.
- `x`: the global batch `[B, ...]`, sharded over the batch axes (dp/fsdp)
  and replicated over pp. It is split into `num_microbatches` equal
  microbatches along axis 0.
- `stage_fn(params_slice, microbatch) -> microbatch` — pure, same output
  shape (the usual residual-block contract).

Cross-pp wire contract (the perf_opt this module is shaped around):

- **Training (`loss_fn` given) moves scalars only across pp.** The final
  microbatch activations stay local to the last stage; each microbatch's
  loss is computed there (sequentially, `lax.map`, so logits-sized
  intermediates exist one microbatch at a time) and ONE scalar is
  psum-ed. The old design all-reduced the entire `[M, mb, ...]` output
  buffer over pp — gigabytes per step for data only one rank produced.
  Gradients ride the `ppermute` transposes (scalar loss → per-hop
  activation cotangents), exactly the forward wire pattern reversed.
- The activations-returning path (no `loss_fn` — eval/inference) never
  all-reduces either: the last stage's buffer is rotated around the ring
  with `pp-1` neighbor hops (`_broadcast_from_last`). A lint in
  `tests/test_ci_tools.py` pins that no non-scalar `lax.psum` ever
  reappears in this module.

Gradients flow through `ppermute` (it has a transpose rule), so the same
function trains under `jax.grad`.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from kubeflow_tpu.parallel.sharding import batch_axes, batch_shard_count


def pipeline_schedule(
    n_stages: int, num_microbatches: int, interleave: int = 1
) -> dict:
    """The static schedule accounting for a pipeline configuration — the
    same numbers `spmd_pipeline` builds its loop from, so what the bench
    reports is the schedule that actually ran (the `flash_schedule`
    trick from ops/flash.py, applied to the pipeline layer).

    Returns:
      - ``loop_ticks``: `lax.fori_loop` iterations; each applies ONE of a
        rank's `interleave` stage slices (`M*v + pp - 1`).
      - ``stage_ticks``: loop ticks normalized to GPipe-equivalent stage
        ticks (`loop_ticks / v` — `v` loop ticks do the work one GPipe
        tick does, since each slice is `1/v` of a rank's layers).
      - ``model_stage_ticks``: the `M + S/v - 1` roofline the interleaved
        schedule is measured against (equals `stage_ticks` at v=1).
      - ``bubble``: idle fraction, `(pp-1) / loop_ticks`.
    """
    if interleave < 1:
        raise ValueError(f"interleave must be >= 1, got {interleave}")
    if n_stages % interleave:
        raise ValueError(
            f"n_stages ({n_stages}) must be a multiple of interleave "
            f"({interleave})"
        )
    if num_microbatches < 1:
        raise ValueError(
            f"num_microbatches must be >= 1, got {num_microbatches}"
        )
    pp = n_stages // interleave
    loop_ticks = num_microbatches * interleave + pp - 1
    return {
        "n_stages": n_stages,
        "pp": pp,
        "interleave": interleave,
        "num_microbatches": num_microbatches,
        "loop_ticks": loop_ticks,
        "stage_ticks": loop_ticks / interleave,
        "model_stage_ticks": num_microbatches + n_stages / interleave - 1,
        "bubble": (pp - 1) / loop_ticks,
    }


def bubble_fraction(
    n_stages: int, num_microbatches: int, interleave: int = 1
) -> float:
    """The fraction of ticks each rank idles.

    GPipe (`interleave=1`): `(S-1)/(M+S-1)` — unchanged from the original
    single-slice schedule. Interleaved: each of the `pp = S/v` ranks does
    `M*v` slice-ticks of real work inside `M*v + pp - 1` loop ticks, so
    the bubble is `(pp-1)/(M*v + pp - 1)` — ~v× smaller.
    """
    return pipeline_schedule(n_stages, num_microbatches, interleave)["bubble"]


def _interleave_order(pp: int, v: int) -> list[int]:
    """Stacked-order permutation placing rank r's k-th local slice at
    global stage `k*pp + r` (the non-adjacent, circular assignment)."""
    return [k * pp + r for r in range(pp) for k in range(v)]


def _broadcast_from_last(outputs: jax.Array, axis: str, pp: int) -> jax.Array:
    """Replicate the last rank's buffer to every pp rank with `pp-1`
    neighbor `ppermute` hops — a ring broadcast, never an all-reduce of
    the activation buffer (the hot-path wire contract this module keeps;
    see the module docstring and the test_ci_tools lint)."""
    rank = lax.axis_index(axis)
    ring = [(i, (i + 1) % pp) for i in range(pp)]
    buf = outputs
    for hop in range(1, pp):
        buf = lax.ppermute(buf, axis, ring)
        outputs = jnp.where((pp - 1 + hop) % pp == rank, buf, outputs)
    return outputs


def spmd_pipeline(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x: jax.Array,
    *,
    mesh: Mesh,
    num_microbatches: int,
    axis: str = "pp",
    interleave: int = 1,
    loss_fn: Callable[..., jax.Array] | None = None,
    targets: Any = None,
    loss_params: Any = None,
    inject_fn: Callable[..., jax.Array] | None = None,
) -> jax.Array:
    """Run `x` through `n_stages = interleave * mesh.shape[axis]` pipeline
    stages.

    Without `loss_fn`, returns the final activations with the same
    sharding as `x`. With `loss_fn(out_mb, target_mb, loss_params)` — a
    per-microbatch MEAN objective computed where the last stage's outputs
    live — returns the scalar mean loss over all microbatches, and the
    only cross-pp collective in the whole fwd+bwd program is that
    scalar's psum plus the (weight-sized, unavoidable) gradient psum of
    any replicated `loss_params` (activation gradients ride the ppermute
    transposes).

    `targets` is a pytree of `[B, ...]` arrays microbatched like `x`;
    `loss_params` is a pytree of extra values `loss_fn` needs (e.g. the
    tied embedding for an LM head), passed in replicated.

    `inject_fn(mb, loss_params) -> activation` maps a raw microbatch of
    `x` to the first stage's input (e.g. an embedding lookup). Keep
    differentiable input prep HERE rather than upstream of the call: `x`
    enters replicated over pp, so a float `x` that is already the output
    of traced compute drags a full `[B, ...]`-sized cotangent all-reduce
    across pp through the shard_map boundary — an int token batch has no
    cotangent at all, and `inject_fn`'s own gradients flow into
    `loss_params`' scalar-masked psum instead.
    """
    pp = mesh.shape[axis]
    n_stages = pp * interleave
    sched = pipeline_schedule(n_stages, num_microbatches, interleave)
    for leaf in jax.tree_util.tree_leaves(stage_params):
        if leaf.shape[0] != n_stages:
            raise ValueError(
                f"stage_params leaves must be stacked [S={n_stages}, ...] "
                f"(interleave={interleave} x {axis}={pp}); got leading dim "
                f"{leaf.shape[0]}"
            )
    batch = tuple(batch_axes(mesh))
    batch_shards = batch_shard_count(mesh)
    local_batch, rem = divmod(x.shape[0], batch_shards)
    if rem:
        raise ValueError(
            f"batch {x.shape[0]} does not shard evenly over "
            f"{batch_shards} batch-axis devices"
        )
    # Validated for EVERY n_stages, including the degenerate single-stage
    # pipeline below — a config that errors on pp>1 must not silently
    # pass on pp=1.
    if local_batch % num_microbatches:
        raise ValueError(
            f"per-shard batch {local_batch} must divide into "
            f"{num_microbatches} microbatches"
        )
    if interleave > 1 and num_microbatches < pp:
        raise ValueError(
            f"interleaved schedule needs num_microbatches "
            f"({num_microbatches}) >= {axis} ranks ({pp}): a wrapped "
            f"microbatch re-enters rank 0 {num_microbatches} ticks after "
            f"injection but only becomes available after {pp}"
        )
    if loss_fn is not None and targets is None:
        raise ValueError("loss_fn requires targets")

    if n_stages == 1:
        # Degenerate pipeline: just apply the single stage (and the
        # objective on the full batch — the mean over equal microbatches
        # equals the full-batch mean, so the contract is unchanged).
        params0 = jax.tree_util.tree_map(lambda p: p[0], stage_params)
        x0 = inject_fn(x, loss_params) if inject_fn is not None else x
        out = stage_fn(params0, x0)
        if loss_fn is None:
            return out
        return loss_fn(out, targets, loss_params)

    if interleave > 1:
        # Re-stack from pipeline order to rank-contiguous order so the
        # P(axis) sharding below hands rank r exactly its v non-adjacent
        # slices (stages r, pp+r, ...). One gather of the weights per
        # step; its transpose scatters the gradients straight back.
        order = jnp.asarray(_interleave_order(pp, interleave))
        stage_params = jax.tree_util.tree_map(
            lambda p: jnp.take(p, order, axis=0), stage_params
        )

    param_spec = jax.tree_util.tree_map(lambda _: P(axis), stage_params)
    x_spec = P(batch)
    tgt_spec = jax.tree_util.tree_map(lambda _: P(batch), targets)
    lp_spec = jax.tree_util.tree_map(lambda _: P(), loss_params)
    M, v = num_microbatches, interleave
    total = M * v
    ring = [(i, (i + 1) % pp) for i in range(pp)]

    def split_mb(a):
        return jnp.reshape(
            a, (M, a.shape[0] // M) + a.shape[1:]
        )

    def run_schedule(params, local_x, lp):
        """The pipeline loop. Returns the per-rank `[M, mb, ...]` output
        buffer — real data on the last rank, zeros elsewhere."""
        rank = lax.axis_index(axis)
        mb = split_mb(local_x)

        def feed_fn(m):
            raw = mb[m]
            return inject_fn(raw, lp) if inject_fn is not None else raw

        # First-stage input shape, which the in-flight state buffers
        # share (inject_fn may change trailing dims/dtype, e.g. an
        # embedding lookup's tokens -> activations).
        probe = jax.eval_shape(
            feed_fn, jax.ShapeDtypeStruct((), jnp.int32)
        )
        state = jnp.zeros(probe.shape, probe.dtype)
        outputs = jnp.zeros((M,) + probe.shape, probe.dtype)
        # Circular buffer for wrapped activations (interleave only):
        # rank 0 re-injects microbatch m for repeat w+1 exactly
        # (w+1)*M + m ticks in, M - pp ticks after its wrap arrives.
        circ = jnp.zeros((M,) + probe.shape, probe.dtype) if v > 1 else None

        def tick(t, carry):
            state, outputs, circ = carry
            # Rank r's work item this tick: microbatch `m`, repeat `w`
            # (= local slice index). The staircase `t - rank` is the
            # pipeline's defining skew.
            idx = t - rank
            valid = jnp.logical_and(idx >= 0, idx < total)
            idxc = jnp.clip(idx, 0, total - 1)
            m = idxc % M
            w = idxc // M
            # Rank 0 sources fresh microbatches on repeat 0, wrapped
            # ones from the circular buffer after; everyone else
            # consumes the neighbor handoff.
            inj = feed_fn(m)
            if v > 1:
                feed = jnp.where(w == 0, inj, circ[m])
            else:
                feed = inj
            x_in = jnp.where(rank == 0, feed, state)
            if v > 1:
                my = jax.tree_util.tree_map(
                    lambda p: lax.dynamic_index_in_dim(
                        p, w, 0, keepdims=False
                    ),
                    params,
                )
            else:
                my = jax.tree_util.tree_map(lambda p: p[0], params)
            y = stage_fn(my, x_in)
            # The last rank's last repeat emits microbatch m.
            emit = jnp.logical_and(
                valid,
                jnp.logical_and(rank == pp - 1, w == v - 1),
            )
            outputs = outputs.at[m].set(jnp.where(emit, y, outputs[m]))
            # Neighbor handoff (ring: last -> 0 carries the wrap; for
            # v=1 rank 0 overwrites it with its next injection).
            y = lax.ppermute(y, axis, ring)
            if v > 1:
                # File the arriving wrap under its microbatch id. Only
                # rank 0's buffer is ever read; other ranks file their
                # (differently-sourced) arrivals into slots they never
                # consume.
                src = t - (pp - 1)
                srcc = jnp.clip(src, 0, total - 1)
                wrap = jnp.logical_and(
                    jnp.logical_and(src >= 0, src < total),
                    srcc // M < v - 1,
                )
                sm = srcc % M
                circ = circ.at[sm].set(jnp.where(wrap, y, circ[sm]))
            return y, outputs, circ

        _, outputs, _ = lax.fori_loop(
            0, sched["loop_ticks"], tick, (state, outputs, circ)
        )
        return outputs

    if loss_fn is None:

        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(param_spec, x_spec, lp_spec),
            out_specs=x_spec,
            check_rep=False,
        )
        def run(params, local_x, lp):
            outputs = run_schedule(params, local_x, lp)
            outputs = _broadcast_from_last(outputs, axis, pp)
            return jnp.reshape(
                outputs, (outputs.shape[0] * outputs.shape[1],)
                + outputs.shape[2:]
            )

        return run(stage_params, x, loss_params)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(param_spec, x_spec, tgt_spec, lp_spec),
        out_specs=P(),
        check_rep=False,
    )
    def run_loss(params, local_x, local_targets, lp):
        outputs = run_schedule(params, local_x, lp)
        tgt = jax.tree_util.tree_map(split_mb, local_targets)
        # Per-microbatch objective, sequentially (lax.map): logits-sized
        # intermediates exist for ONE microbatch at a time, which is the
        # whole activation-memory point of microbatching the loss.
        def one(m):
            return loss_fn(
                outputs[m],
                jax.tree_util.tree_map(lambda a: a[m], tgt),
                lp,
            )

        losses = lax.map(one, jnp.arange(M))
        # Every rank ran the (masked) objective on its local buffer, but
        # only the last stage's is real; the ONLY cross-pp collective in
        # the program is this scalar's psum (summed over the batch
        # shards in the same reduction).
        local_loss = jnp.where(
            lax.axis_index(axis) == pp - 1, jnp.sum(losses), 0.0
        )
        return lax.psum(local_loss, (axis,) + batch) / (M * batch_shards)

    return run_loss(stage_params, x, targets, loss_params)
