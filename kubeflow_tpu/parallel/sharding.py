"""Logical-axis sharding rules.

Models annotate parameters/activations with *logical* axis names ("embed",
"mlp", "heads", ...); a rules table maps logical names to mesh axes from
`kubeflow_tpu.parallel.mesh.AXES`. Changing the parallelism layout is a
rules-table change, never a model change — this is the scaling-book recipe
(pick a mesh, annotate shardings, let XLA insert the collectives), and it is
what makes TP/SP/EP "a config, not a fork" (SURVEY.md §5, long-context row).
"""

from __future__ import annotations

from typing import Any, Mapping, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_tpu.parallel.mesh import BATCH_AXES

# logical name -> mesh axis (or tuple of mesh axes, or None for replicated)
LogicalRules = Mapping[str, Any]


def default_rules(*, fsdp_params: bool = True) -> dict[str, Any]:
    """Rules for the standard DP/FSDP × TP × SP transformer layout.

    With ``fsdp_params=True`` the embed dimension of every weight is sharded
    over the fsdp axis (ZeRO-3: XLA all-gathers weights forward, reduce-
    scatters gradients backward). Attention heads and MLP hidden ride tp;
    activation sequence rides sp (ring attention), batch rides dp×fsdp.
    """
    return {
        # activations
        "batch": BATCH_AXES,
        "seq": "sp",
        "act_embed": None,          # activation features replicated across tp
        "act_heads": "tp",
        # parameters
        "embed": "fsdp" if fsdp_params else None,
        "mlp": "tp",
        "heads": "tp",
        "kv": None,
        "qkv_embed": "fsdp" if fsdp_params else None,
        "vocab": "tp",
        "expert": "ep",
        # conv / vision parameters: shard the output-channel dim over fsdp
        "conv_out": "fsdp" if fsdp_params else None,
        "conv_in": None,
        "spatial": None,
        # scalars / norms
        "norm": None,
        "stage": "pp",
    }


def spec_for(names: Sequence[str | None], rules: LogicalRules) -> P:
    """Map a tuple of logical axis names to a PartitionSpec."""
    parts = []
    for name in names:
        if name is None:
            parts.append(None)
        else:
            if name not in rules:
                raise KeyError(f"no sharding rule for logical axis {name!r}")
            parts.append(rules[name])
    # Trim trailing Nones so specs print compactly and match ranks loosely.
    while parts and parts[-1] is None:
        parts.pop()
    return P(*parts)


def named_sharding(mesh: Mesh, *parts: Any) -> NamedSharding:
    return NamedSharding(mesh, P(*parts))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_axes(mesh: Mesh) -> tuple[str, ...]:
    """The batch axes present in `mesh`, in BATCH_AXES order."""
    return tuple(a for a in BATCH_AXES if a in mesh.axis_names)


def batch_shard_count(mesh: Mesh) -> int:
    """How many shards the leading (example) axis of a global batch is
    split into on `mesh` — the product of the batch-axis sizes. The
    divisibility contract every batch consumer validates against
    (pipeline microbatching, trainer init shapes, data synthesis)."""
    n = 1
    for a in batch_axes(mesh):
        n *= mesh.shape[a]
    return n


def batch_sharding(mesh: Mesh, ndim: int = 2) -> NamedSharding:
    """Shard dim 0 over the batch axes, replicate the rest."""
    return NamedSharding(mesh, P(batch_axes(mesh), *([None] * (ndim - 1))))


def logical_sharding(
    mesh: Mesh, names: Sequence[str | None], rules: LogicalRules
) -> NamedSharding:
    return NamedSharding(mesh, spec_for(names, rules))


def shard_pytree(tree: Any, mesh: Mesh, sharding_tree: Any | None = None) -> Any:
    """`jax.device_put` a pytree onto `mesh`, replicating by default.

    `sharding_tree` may be a pytree-prefix of NamedShardings (as accepted by
    device_put); None replicates everything — the right default for small
    states and for tests.
    """
    if sharding_tree is None:
        sharding_tree = replicated(mesh)
    return jax.device_put(tree, sharding_tree)


def apply_logical_annotations(tree: Any, mesh: Mesh, rules: LogicalRules) -> Any:
    """Turn a pytree of flax logically-annotated params into NamedShardings.

    Works with `flax.linen.with_partitioning` metadata: leaves that are
    `nn.Partitioned` (or anything exposing `.names`) get their logical names
    mapped through `rules`; plain arrays are replicated.
    """
    def one(leaf: Any) -> NamedSharding:
        names = getattr(leaf, "names", None)
        if names is None:
            return replicated(mesh)
        return logical_sharding(mesh, names, rules)

    return jax.tree_util.tree_map(
        one, tree, is_leaf=lambda x: hasattr(x, "names")
    )
