"""Podracer-style RL workload (docs/rl.md): an actor–learner loop built
ON the platform's serving, training, and control-plane primitives —
actors do policy inference through the ServingDeployment data plane,
the learner is a stock guarded `fit()`, and weight publication rides
the CR modelVersion drain-roll."""

from kubeflow_tpu.rl.env import (
    EnvConfig,
    Trajectory,
    VectorEnv,
    rollout,
    sample_actions,
)
from kubeflow_tpu.rl.loop import (
    PublishRecord,
    RLConfig,
    RLResult,
    build_learner,
    bump_model_version,
    run_actor_learner,
)
from kubeflow_tpu.rl.policy import (
    PolicyCheckpointPublisher,
    PolicyMLP,
    PolicyWithLoss,
    extract_policy_variables,
    init_policy_variables,
    make_policy_servable,
    split_predictions,
)
from kubeflow_tpu.rl.replay import ReplayQueue, ReplayStalled

__all__ = [
    "EnvConfig",
    "Trajectory",
    "VectorEnv",
    "rollout",
    "sample_actions",
    "PublishRecord",
    "RLConfig",
    "RLResult",
    "build_learner",
    "bump_model_version",
    "run_actor_learner",
    "PolicyCheckpointPublisher",
    "PolicyMLP",
    "PolicyWithLoss",
    "extract_policy_variables",
    "init_policy_variables",
    "make_policy_servable",
    "split_predictions",
    "ReplayQueue",
    "ReplayStalled",
]
