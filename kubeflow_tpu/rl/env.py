"""Synthetic vectorized environment for the Podracer RL workload.

A contextual-bandit-style task sized so the PLATFORM, not the task, is
what a run measures: each step the environment emits a batch of
observation vectors, a hidden linear map (drawn once from the env seed)
defines the best action per observation, and the reward is 1.0 for
choosing it (0.0 otherwise). A random policy earns ~horizon/n_actions
per episode; a converged one earns ~horizon — enough signal for the
study layer's early stopping to rank learning rates on real runs.

Determinism is the load-bearing property: every observation is a pure
function of ``(env seed, salt, trajectory index, step)`` and action
sampling is a pure function of the same tuple plus the policy's logits.
That is what lets the replay queue make the train/data resumability
promise (checkpoint-resume neither repeats nor drops trajectory
indices) and lets the chaos soak assert exact continuity across a
SIGKILLed learner.

The acting path is numpy-only by design — no jax, no device sync. The
`rl-actor-learner` lint contract AST-scans `rollout` (and the actor
loop in `rl/loop.py`) to keep it that way: actors must spend their time
in the serving stack's batcher, not in host-side device chatter.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class EnvConfig:
    """Shape of the synthetic task (and of one actor rollout)."""

    obs_dim: int = 8
    n_actions: int = 4
    # Environments stepped in lockstep per rollout — one predict() call
    # per step carries n_envs observations through the batcher.
    n_envs: int = 8
    horizon: int = 8
    seed: int = 0

    @property
    def transitions_per_trajectory(self) -> int:
        return self.horizon * self.n_envs


@dataclasses.dataclass
class Trajectory:
    """One completed vectorized rollout (the replay queue's unit)."""

    index: int
    # The serving-side model version the actions were sampled from —
    # read in-band from the policy servable's version column, so it
    # reflects what the FLEET actually served, not what the learner
    # believes it published.
    policy_version: int
    obs: np.ndarray      # [horizon, n_envs, obs_dim]
    actions: np.ndarray  # [horizon, n_envs] int32
    rewards: np.ndarray  # [horizon, n_envs] float32

    @property
    def mean_return(self) -> float:
        """Mean per-env episode return."""
        return float(self.rewards.sum(axis=0).mean())

    def transitions(self) -> dict[str, np.ndarray]:
        """Flatten to one learner batch (the trainer's loss_in_model
        contract: obs under input_key, packed [action, return] labels
        under label_key)."""
        t, e, d = self.obs.shape
        obs = self.obs.reshape(t * e, d).astype(np.float32)
        target = np.stack(
            [
                self.actions.reshape(t * e).astype(np.float32),
                self.rewards.reshape(t * e).astype(np.float32),
            ],
            axis=1,
        )
        return {"obs": obs, "target": target}


class VectorEnv:
    """The seeded task. Stateless between calls: observations derive
    from (seed, salt, index, step), so two processes with the same
    config regenerate identical trajectories — the property the
    resumable replay protocol stands on."""

    def __init__(self, config: EnvConfig):
        self.config = config
        rng = np.random.default_rng(config.seed)
        # Hidden scoring map: argmax(obs @ w) is the optimal action.
        self._w = rng.standard_normal(
            (config.obs_dim, config.n_actions)
        ).astype(np.float32)

    def observe(self, index: int, step: int, salt: int = 0) -> np.ndarray:
        c = self.config
        rng = np.random.default_rng((c.seed, salt, index, step))
        return rng.standard_normal((c.n_envs, c.obs_dim)).astype(np.float32)

    def rewards(self, obs: np.ndarray, actions: np.ndarray) -> np.ndarray:
        best = np.argmax(obs @ self._w, axis=1)
        return (actions == best).astype(np.float32)

    def optimal_actions(self, obs: np.ndarray) -> np.ndarray:
        return np.argmax(obs @ self._w, axis=1)


def sample_actions(
    logits: np.ndarray, config: EnvConfig, index: int, step: int, salt: int
) -> np.ndarray:
    """Sample from the softmax policy via the Gumbel trick with noise
    that is a pure function of the rollout coordinates — given the same
    logits, the same actions, on any host."""
    rng = np.random.default_rng((config.seed, salt, index, step, 1))
    gumbel = rng.gumbel(size=logits.shape).astype(np.float32)
    return np.argmax(logits + gumbel, axis=1).astype(np.int32)


def rollout(env: VectorEnv, predict_fn, index: int, *, salt: int = 0):
    """Run one vectorized episode through ``predict_fn`` (the serving
    router, in the real loop): obs -> (logits, served model version).

    Returns a `Trajectory`. Pure numpy on this side of predict_fn.
    """
    c = env.config
    obs_steps = []
    act_steps = []
    rew_steps = []
    version = 0
    for t in range(c.horizon):
        obs = env.observe(index, t, salt)
        logits, version = predict_fn(obs)
        actions = sample_actions(logits, c, index, t, salt)
        obs_steps.append(obs)
        act_steps.append(actions)
        rew_steps.append(env.rewards(obs, actions))
    return Trajectory(
        index=index,
        policy_version=int(version),
        obs=np.stack(obs_steps),
        actions=np.stack(act_steps),
        rewards=np.stack(rew_steps),
    )
