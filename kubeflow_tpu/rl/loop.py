"""Actor–learner orchestrator: the Sebulba split on platform primitives.

One `run_actor_learner` call couples three existing subsystems into one
RL run — nothing here reimplements them:

- **Actors** (threads) pull claim tickets from the `ReplayQueue`, roll
  episodes out through the SERVING stack — `Router.predict` into the
  continuous batcher, retrying 429s/replica deaths the way any client
  does — and push the trajectories back. The policy version each
  trajectory was acted with is read in-band from the servable's version
  column.
- **Learner** is a stock guarded `fit()` over the queue (loss_in_model
  REINFORCE, dp mesh, AnomalyGuard, checkpoint-resume; the queue speaks
  the train/data resumability protocol so all of that applies
  unchanged).
- **Publication** rides the CONTROL PLANE: at each publish boundary the
  learner waits for its checkpoint to commit, then bumps the
  ServingDeployment's ``spec.modelVersion``; the serving controller's
  drain-roll walks the fleet one replica at a time and actors observe
  the new version in their responses. publish→actor latency is the
  time from the CR bump to the first tagged response.

Actor-side code paths (`_actor_loop` here, `rollout` in rl/env.py) are
numpy-only — no jax, no device sync; the `rl-actor-learner` lint
contract enforces it by AST.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time

import numpy as np

from kubeflow_tpu.rl.env import EnvConfig, VectorEnv, rollout
from kubeflow_tpu.rl.replay import ReplayQueue

log = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class RLConfig:
    """One actor–learner run (one study trial, or one bench phase)."""

    env: EnvConfig = dataclasses.field(default_factory=EnvConfig)
    hidden: int = 32
    learning_rate: float = 0.05
    total_steps: int = 60
    # Learner steps between weight publications (also the checkpoint
    # save interval — a publish IS a committed checkpoint).
    publish_every: int = 20
    # Off-policy bound, in learner steps (versions are checkpoint
    # steps). The learner blocks rather than exceed it: two publish
    # intervals means a wedged roll stops the learner before it is two
    # publications ahead of what the fleet is serving.
    staleness_bound: int = 40
    n_actors: int = 2
    replay_capacity: int = 8
    dp: int = 2

    @property
    def batch_size(self) -> int:
        return self.env.transitions_per_trajectory


@dataclasses.dataclass
class PublishRecord:
    version: int
    bumped_at: float
    observed_at: float | None = None

    @property
    def latency_s(self) -> float | None:
        if self.observed_at is None:
            return None
        return self.observed_at - self.bumped_at


@dataclasses.dataclass
class RLResult:
    fit_result: object
    actor_steps: int
    actor_steps_per_sec: float
    learner_steps_per_sec: float
    publishes: list[PublishRecord]
    mean_return: float
    final_loss: float
    predict_retries: int
    rejected_pushes: int
    stale_dropped: int
    trajectories: int

    @property
    def publish_latencies(self) -> list[float]:
        return [
            p.latency_s for p in self.publishes if p.latency_s is not None
        ]


def build_learner(cfg: RLConfig, mesh, *, guard=None):
    """The stock Trainer, configured for the in-model REINFORCE loss."""
    from kubeflow_tpu.rl.policy import PolicyWithLoss
    from kubeflow_tpu.train import TrainConfig, Trainer

    config = TrainConfig(
        batch_size=cfg.batch_size,
        learning_rate=cfg.learning_rate,
        warmup_steps=2,
        total_steps=cfg.total_steps,
        optimizer="adamw",
        fsdp_params=False,
        train_metrics="loss",
        label_smoothing=0.0,
        loss_in_model=True,
    )
    return Trainer(
        PolicyWithLoss(n_actions=cfg.env.n_actions, hidden=cfg.hidden),
        config,
        mesh,
        example_input_shape=(cfg.batch_size, cfg.env.obs_dim),
        input_key="obs",
        label_key="target",
        guard=guard,
    )


def bump_model_version(api, name: str, namespace: str, version: int):
    """Publish: point the ServingDeployment at the new checkpoint step.
    The controller's drain-roll takes it from here."""
    from kubeflow_tpu.api import serving as serving_api
    from kubeflow_tpu.controllers.runtime import retry_on_conflict

    def write():
        dep = api.get(serving_api.KIND, name, namespace).thaw()
        if int(dep.spec.get("modelVersion") or 0) >= version:
            return
        spec = dict(dep.spec)
        spec["modelVersion"] = int(version)
        dep.spec = spec
        api.update(dep)

    retry_on_conflict(write)


class _RouterPolicy:
    """predict_fn for `rollout`: obs -> (logits, served version), with
    client-side retry on shed/unready — the router already retries
    replica death internally for idempotent requests."""

    def __init__(self, router, *, timeout_s: float = 60.0, on_version=None):
        self._router = router
        self._timeout_s = timeout_s
        self._on_version = on_version
        self.retries = 0

    def __call__(self, obs: np.ndarray):
        from kubeflow_tpu.rl.policy import split_predictions
        from kubeflow_tpu.serving.router import NoReadyReplicas, Overloaded

        deadline = time.monotonic() + self._timeout_s
        while True:
            try:
                out = self._router.predict(obs, idempotent=True)
                logits, version = split_predictions(np.asarray(out))
                if self._on_version is not None:
                    # Per-response, not per-trajectory: publish→actor
                    # latency is "first tagged response", and it must
                    # keep ticking even when the replay queue is full.
                    self._on_version(version)
                return logits, version
            except Overloaded as e:
                wait = getattr(e, "retry_after", 0.05)
            except NoReadyReplicas:
                wait = 0.05
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"policy fleet unavailable for {self._timeout_s:.0f}s"
                )
            self.retries += 1
            time.sleep(wait)


def _actor_loop(
    env,
    queue,
    predict_fn,
    stop,
    learner_done,
    publish_lock,
    returns,
    counters,
):
    """One actor thread: claim → rollout through serving → push.
    numpy + queue + predict_fn only (lint-enforced: no jax in here)."""
    while not stop.is_set():
        index, salt = queue.claim()
        try:
            traj = rollout(env, predict_fn, index, salt=salt)
        except Exception:
            queue.abandon(index, salt)
            if stop.is_set():
                return
            time.sleep(0.05)
            continue
        with publish_lock:
            counters["actor_steps"] += traj.obs.shape[0] * traj.obs.shape[1]
            counters["trajectories"] += 1
            returns.append(traj.mean_return)
            del returns[:-50]
        if learner_done.is_set():
            # Nobody will consume it, and a blocking push here would
            # freeze the actor before it can observe the final roll.
            continue
        queue.push(index, salt, traj.policy_version, traj.transitions())


def run_actor_learner(
    *,
    api,
    deployment: str,
    router,
    trainer,
    checkpointer,
    queue: ReplayQueue,
    cfg: RLConfig,
    namespace: str = "default",
    reconcile=None,
    rng=None,
    fault_hook=None,
    on_step=None,
) -> RLResult:
    """Run one coupled actor–learner session to completion.

    ``reconcile`` (optional) is polled on a background thread — pass the
    serving controller's ``run_until_idle`` so CR bumps actually
    materialize into rolls; in a full controller-manager deployment the
    controller is already running and this stays None. ``fault_hook``
    (chaos) and ``on_step`` are called at every learner log boundary.
    May return a `Preempted` fit result; the caller resumes exactly like
    any other trainer (same checkpointer, same queue protocol).
    """
    from kubeflow_tpu.train import Preempted, fit

    env = VectorEnv(cfg.env)
    stop = threading.Event()
    learner_done = threading.Event()
    publish_lock = threading.Lock()
    publishes: list[PublishRecord] = []
    returns: list[float] = []
    counters = {"actor_steps": 0, "trajectories": 0}

    def observe_version(version: int) -> None:
        now = time.monotonic()
        with publish_lock:
            for rec in publishes:
                if rec.observed_at is None and version >= rec.version:
                    rec.observed_at = now

    predict_fn = _RouterPolicy(router, on_version=observe_version)

    threads = [
        threading.Thread(
            target=_actor_loop,
            args=(env, queue, predict_fn, stop, learner_done,
                  publish_lock, returns, counters),
            name=f"rl-actor-{i}",
            daemon=True,
        )
        for i in range(cfg.n_actors)
    ]

    if reconcile is not None:
        def _reconcile_loop():
            while not stop.is_set():
                try:
                    reconcile()
                except Exception:
                    log.exception("serving reconcile failed; retrying")
                time.sleep(0.02)

        threads.append(
            threading.Thread(
                target=_reconcile_loop, name="rl-reconcile", daemon=True
            )
        )

    step_times: list[tuple[int, float]] = []
    last_loss = [float("nan")]

    def on_metrics(step: int, rec: dict) -> None:
        step_times.append((step, time.monotonic()))
        last_loss[0] = rec["loss"]
        queue.note_learner_step(step)
        if (
            step % cfg.publish_every == 0
            and checkpointer is not None
        ):
            # The save for this boundary is already enqueued (fit saves
            # before it logs); make it durable, then publish.
            checkpointer.wait()
            version = checkpointer.latest_step()
            if version:
                bump_model_version(
                    api, deployment, namespace, int(version)
                )
                with publish_lock:
                    publishes.append(
                        PublishRecord(int(version), time.monotonic())
                    )
        if on_step is not None:
            on_step(step, rec)
        if fault_hook is not None:
            fault_hook(step)

    t0 = time.monotonic()
    for t in threads:
        t.start()
    try:
        result = fit(
            trainer,
            queue,
            cfg.total_steps,
            rng=rng,
            checkpointer=checkpointer,
            log_every=1,
            on_metrics=on_metrics,
        )
        learner_done.set()
        queue.drain_pushers()
        # Give the final publish a chance to be observed end-to-end (it
        # needs the controller roll plus one actor round trip).
        if not isinstance(result, Preempted):
            deadline = time.monotonic() + 15.0
            while time.monotonic() < deadline:
                with publish_lock:
                    if all(
                        p.observed_at is not None for p in publishes
                    ):
                        break
                time.sleep(0.05)
    finally:
        stop.set()
        queue.close()
        for t in threads:
            t.join(timeout=10.0)
    elapsed = max(time.monotonic() - t0, 1e-9)

    done_steps = step_times[-1][0] - step_times[0][0] if len(
        step_times
    ) > 1 else 0
    learner_sps = (
        done_steps / (step_times[-1][1] - step_times[0][1])
        if done_steps > 0
        else 0.0
    )
    with publish_lock:
        mean_return = (
            float(np.mean(returns[-20:])) if returns else 0.0
        )
        actor_steps = counters["actor_steps"]
        trajectories = counters["trajectories"]
    return RLResult(
        fit_result=result,
        actor_steps=actor_steps,
        actor_steps_per_sec=actor_steps / elapsed,
        learner_steps_per_sec=learner_sps,
        publishes=list(publishes),
        mean_return=mean_return,
        final_loss=last_loss[0],
        predict_retries=predict_fn.retries,
        rejected_pushes=queue.rejected_pushes,
        stale_dropped=queue.stale_dropped,
        trajectories=trajectories,
    )
