"""The RL policy: one set of weights, two faces.

- **Serving face** (`make_policy_servable`): a tiny MLP `Servable` the
  actors query through the router/batcher. Its output carries one extra
  column — the model VERSION, broadcast per row — so actors observe
  which weights actually served each request *in-band*. That makes
  `rl_policy_publish_to_actor_seconds` an honest end-to-end number
  (CR bump → controller drain-roll → batcher swap → first tagged
  response), not a controller-side timestamp diff.

- **Learner face** (`PolicyWithLoss`): the same MLP wrapped in a
  loss_in_model module so the REINFORCE objective rides the unmodified
  `Trainer`/`fit()` path (dp mesh, AnomalyGuard, elastic resize —
  nothing RL-specific in the trainer). Labels are packed
  ``[action, return]`` columns, matching `Trajectory.transitions()`.

- **Publication channel** (`PolicyCheckpointPublisher`): the serving
  controller's servable factory. It materializes replicas FROM THE
  LEARNER'S CHECKPOINT DIRECTORY — version = checkpoint step — so a
  modelVersion bump on the ServingDeployment really does push freshly
  trained weights through the drain-roll, the same way a production
  roll would (docs/rl.md).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


class PolicyMLP(nn.Module):
    """Actor-side policy network: obs -> action logits."""

    n_actions: int = 4
    hidden: int = 32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.relu(nn.Dense(self.hidden)(x.astype(jnp.float32)))
        return nn.Dense(self.n_actions)(x)


class PolicyWithLoss(nn.Module):
    """Learner-side wrapper: computes the REINFORCE loss in-model so the
    stock trainer drives it (`loss_in_model=True`: the scalar return IS
    the loss; requires train_metrics="loss", label_smoothing=0.0)."""

    n_actions: int = 4
    hidden: int = 32
    entropy_bonus: float = 0.01

    @nn.compact
    def __call__(self, obs, train: bool = False, labels=None):
        logits = PolicyMLP(self.n_actions, self.hidden, name="policy")(obs)
        if labels is None:
            # Shape-inference / init call (the trainer initializes with
            # the example input only).
            labels = jnp.zeros((obs.shape[0], 2), jnp.float32)
        action = labels[:, 0].astype(jnp.int32)
        ret = labels[:, 1]
        logp = jax.nn.log_softmax(logits)
        chosen = jnp.take_along_axis(logp, action[:, None], axis=1)[:, 0]
        # Batch-mean baseline: enough variance reduction for a bandit
        # horizon; anything fancier would make the task the story.
        advantage = ret - jnp.mean(ret)
        pg_loss = -jnp.mean(chosen * advantage)
        entropy = -jnp.mean(jnp.sum(jnp.exp(logp) * logp, axis=1))
        return pg_loss - self.entropy_bonus * entropy


def init_policy_variables(
    obs_dim: int, n_actions: int, hidden: int, seed: int = 0
):
    """Fresh actor-face variables (the pre-first-publish fleet)."""
    module = PolicyMLP(n_actions=n_actions, hidden=hidden)
    return jax.jit(module.init)(
        jax.random.PRNGKey(seed), np.zeros((1, obs_dim), np.float32)
    )


def extract_policy_variables(learner_params) -> dict:
    """Project the learner's PolicyWithLoss params down to the serving
    face (the wrapper adds one submodule level, no extra weights)."""
    params = learner_params
    if "params" in params:
        params = params["params"]
    return {"params": params["policy"]}


def make_policy_servable(
    name: str,
    variables,
    *,
    version: int,
    n_actions: int,
    hidden: int,
    max_batch: int = 64,
    device=None,
    obs_dim: int | None = None,
):
    """Build the version-tagged policy Servable.

    Output shape is ``[B, n_actions + 1]``: logits, then the version
    broadcast down a trailing column. `split_predictions` undoes it.
    """
    from kubeflow_tpu.serving.servable import Servable

    module = PolicyMLP(n_actions=n_actions, hidden=hidden)
    tag = float(int(version))

    def apply_fn(vs, batch):
        logits = module.apply(vs, batch, train=False)
        col = jnp.full((logits.shape[0], 1), tag, logits.dtype)
        return jnp.concatenate([logits, col], axis=1)

    servable = Servable(
        name,
        apply_fn,
        variables,
        version=int(version),
        max_batch=max_batch,
        device=device,
    )
    if obs_dim is not None:
        servable.warmup_with(np.zeros((obs_dim,), np.float32))
    return servable


def split_predictions(out: np.ndarray) -> tuple[np.ndarray, int]:
    """(logits, served version) from a version-tagged response."""
    return out[:, :-1], int(round(float(out[0, -1])))


class PolicyCheckpointPublisher:
    """Servable factory for `LocalReplicaRuntime`, reading weights back
    out of the learner's checkpoint directory.

    Before the first publish (rspec modelVersion == 0, or no committed
    checkpoint yet) replicas serve a seeded fresh init at version 1 —
    the fleet must be up and admitting before the learner has saved
    anything. After a publish, the factory restores the latest committed
    step and serves it at version == step; the controller's
    `_roll_outdated` keeps rolling until the served version matches the
    spec, so a restore racing the writer's in-flight save self-heals on
    the next reconcile.
    """

    def __init__(
        self,
        ckpt_dir: str,
        abstract_state_fn,
        *,
        obs_dim: int,
        n_actions: int,
        hidden: int,
        init_seed: int = 0,
        device=None,
    ):
        self._ckpt_dir = ckpt_dir
        # Callable, not a state: the trainer may not exist yet when the
        # fleet first materializes (and elastic resize may replace it).
        self._abstract_state_fn = abstract_state_fn
        self._obs_dim = obs_dim
        self._n_actions = n_actions
        self._hidden = hidden
        self._init_seed = init_seed
        self._device = device

    def _restore(self):
        from kubeflow_tpu.train.checkpoint import Checkpointer

        try:
            ckpt = Checkpointer(self._ckpt_dir, read_only=True)
        except FileNotFoundError:
            return None
        try:
            restored = ckpt.restore_latest(self._abstract_state_fn())
        finally:
            ckpt.close()
        return restored

    def __call__(self, rspec: dict):
        want = int(rspec.get("modelVersion") or 0)
        restored = self._restore() if want > 0 else None
        if restored is None:
            variables = init_policy_variables(
                self._obs_dim, self._n_actions, self._hidden,
                self._init_seed,
            )
            version = 1
        else:
            variables = extract_policy_variables(
                {"params": restored.state.params}
            )
            version = max(int(restored.step), 1)
        return make_policy_servable(
            rspec.get("model", "policy"),
            variables,
            version=version,
            n_actions=self._n_actions,
            hidden=self._hidden,
            max_batch=int(rspec.get("maxBatch", 64)),
            device=self._device,
            obs_dim=self._obs_dim,
        )
