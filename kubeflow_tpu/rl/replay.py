"""Bounded, resumable trajectory queue between actors and the learner.

One trajectory == one learner batch (``horizon * n_envs`` transitions):
that equality is what keeps the resumability contract exact — the
queue's ``position`` is simultaneously "batches the learner consumed"
and "trajectory indices retired", so the train/data protocol
(`state_dict`/`load_state_dict`/`perturb`/`rebind`, the same duck type
`fit()` already persists for synthetic streams) rides the checkpoint
manifest unchanged and a killed-and-resumed learner neither repeats nor
drops a trajectory index.

Actors `claim()` the next index (with the current salt), roll it out
through the serving stack, and `push()` the result; a push whose claim
ticket no longer matches the queue's state (a restore or an anomaly
rollback happened in between) is REJECTED and the actor just claims
again — in-flight stale work dies at the boundary instead of leaking
into the learner. Backpressure is applied at claim time (a bounded
window of outstanding indices past the learner's position), never at
push time: a blocked push would deadlock the in-order learner behind
the very gap the blocked actor holds.

Off-policy staleness bound (the IMPALA/Sebulba discipline): a
trajectory whose behavior-policy version lags the learner's step by
more than ``staleness_bound`` is DISCARDED at consumption time (counted
in ``stale_dropped``; versions are checkpoint steps, so the bound is in
learner steps). Dropping — not blocking — is deliberate: the
alternative deadlocks when a full buffer of stale work blocks the very
actors that could produce fresh work. With the stale backlog cleared
the learner blocks on an EMPTY buffer, which running actors always
relieve; if publication is wedged so badly that everything arriving is
stale, the stall timeout turns that into a loud `ReplayStalled` instead
of silent off-policy drift. (Resume-exactness is orthogonal: a staleness
drop is a counted policy decision, never a bookkeeping loss — restore
still repeats or skips no index.)
"""

from __future__ import annotations

import threading
import time

import jax


class ReplayStalled(RuntimeError):
    """The learner waited past the stall timeout for admissible data —
    actors dead, a roll wedged, or the staleness gate starved."""


class ReplayQueue:
    def __init__(
        self,
        *,
        capacity: int = 8,
        staleness_bound: int = 10_000,
        mesh=None,
        shardings=None,
        stall_timeout_s: float = 120.0,
    ):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.staleness_bound = staleness_bound
        self.stall_timeout_s = stall_timeout_s
        self._mesh = mesh
        self._shardings = shardings
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._buf: list[tuple[int, int, dict]] = []  # (index, version, batch)
        self._position = 0      # trajectories consumed == batches yielded
        self._next_claim = 0    # next index handed to an actor
        self._returned: list[int] = []  # abandoned claims, re-issued first
        self._salt = 0
        self._max_seen_version = 0
        self._learner_step = 0
        self._closed = False
        self._draining = False
        # Observability for the bench/soak.
        self.rejected_pushes = 0
        self.stale_dropped = 0
        self.stale_wait_seconds = 0.0

    # -- actor side --------------------------------------------------------

    def claim(self) -> tuple[int, int]:
        """Reserve the next trajectory index; returns ``(index, salt)``.
        The ticket must be handed back verbatim to `push`.

        Backpressure lives HERE, not in `push`: a claim blocks while the
        index would fall outside the ``[position, position + capacity)``
        window. Blocking the push instead would deadlock — the buffer
        can fill with out-of-order successors while the actor holding
        the head index waits for space the learner (stuck on that very
        gap) can never free. An issued ticket always has buffer room by
        construction, so completed rollouts are never parked."""
        with self._cond:
            while True:
                if self._closed or self._draining:
                    # Don't wedge a shutting-down actor: hand out a
                    # ticket that will bounce at push.
                    break
                if self._returned:
                    return self._returned.pop(0), self._salt
                if self._next_claim < self._position + self.capacity:
                    break
                self._cond.wait(0.05)
            index = self._next_claim
            self._next_claim += 1
            return index, self._salt

    def abandon(self, index: int, salt: int) -> None:
        """Hand an unfinished claim back (the actor died mid-rollout or
        its predict path failed hard). Unfilled indices would otherwise
        leave a permanent gap the in-order learner stalls behind."""
        with self._cond:
            if salt == self._salt and index >= self._position:
                self._returned.append(index)
                self._returned.sort()
                self._cond.notify_all()

    def push(
        self, index: int, salt: int, version: int, batch: dict
    ) -> bool:
        """Deliver a completed trajectory. Never blocks: the claim
        window already bounded how far actors can outrun the learner,
        and a valid ticket's slot is guaranteed. Returns False — drop
        and re-claim — when the ticket went stale under a
        restore/rollback or the queue closed."""
        with self._cond:
            if self._closed or self._draining:
                return False
            if salt != self._salt or index < self._position:
                self.rejected_pushes += 1
                return False
            self._buf.append((index, int(version), batch))
            self._buf.sort(key=lambda item: item[0])
            self._max_seen_version = max(
                self._max_seen_version, int(version)
            )
            self._cond.notify_all()
            return True

    def max_seen_version(self) -> int:
        with self._lock:
            return self._max_seen_version

    def note_learner_step(self, step: int) -> None:
        """The learner's clock for the staleness comparison (fed from
        the fit loop's metrics callback; versions are checkpoint steps,
        so the two sides share units)."""
        with self._lock:
            self._learner_step = max(self._learner_step, int(step))

    def drain_pushers(self) -> None:
        """The learner is done: release any actor blocked in `claim` on
        a closed window (and bounce subsequent pushes) so it can keep
        acting — observing the final publication — instead of
        freezing."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    # -- learner side (the fit() data iterable) ----------------------------

    def __iter__(self):
        return self

    def _head_ready_locked(self) -> bool:
        # The head must be the next index in order — a later index
        # parked ahead of a gap means its predecessor is still in
        # flight (or was abandoned and will be re-claimed).
        return bool(self._buf) and self._buf[0][0] == self._position

    def __next__(self):
        deadline = time.monotonic() + self.stall_timeout_s
        with self._cond:
            while True:
                if self._head_ready_locked():
                    _, version, batch = self._buf[0]
                    # Consuming this batch puts the learner at step
                    # _learner_step + 1; enforce the off-policy bound
                    # against the version its actions came from.
                    if (
                        self._learner_step + 1 - version
                        > self.staleness_bound
                    ):
                        self._buf.pop(0)
                        self._position += 1
                        self.stale_dropped += 1
                        self._cond.notify_all()
                        continue
                    self._buf.pop(0)
                    self._position += 1
                    self._cond.notify_all()
                    break
                if self._closed:
                    raise StopIteration
                t0 = time.monotonic()
                if t0 >= deadline:
                    raise ReplayStalled(
                        f"no admissible trajectory for "
                        f"{self.stall_timeout_s:.0f}s (position="
                        f"{self._position} buffered={len(self._buf)} "
                        f"learner_step={self._learner_step} "
                        f"max_seen_version={self._max_seen_version} "
                        f"stale_dropped={self.stale_dropped} "
                        f"staleness_bound={self.staleness_bound})"
                    )
                self._cond.wait(min(0.05, deadline - t0))
                self.stale_wait_seconds += time.monotonic() - t0
        if self._mesh is not None:
            from kubeflow_tpu.parallel import sharding as shlib

            batch = {
                k: jax.device_put(
                    v, shlib.batch_sharding(self._mesh, v.ndim)
                )
                for k, v in batch.items()
            }
        return batch

    # -- train/data resumability protocol ----------------------------------

    def state_dict(self) -> dict:
        with self._lock:
            return {"position": self._position, "salt": self._salt}

    def load_state_dict(self, state: dict) -> None:
        with self._cond:
            self._position = int(state["position"])
            self._salt = int(state["salt"])
            # Anything buffered or claimed was produced before the
            # restore point — invalidate it all; actors re-claim from
            # the restored position and in-flight pushes bounce off the
            # ticket check.
            self._buf.clear()
            self._returned.clear()
            self._next_claim = self._position
            self._cond.notify_all()

    def perturb(self, salt: int) -> None:
        """Anomaly-rollback re-seed (the guard's escape from a poisoned
        region): future trajectories draw different observations."""
        with self._cond:
            self._salt = int(salt)
            self._buf.clear()
            self._returned.clear()
            self._next_claim = self._position
            self._cond.notify_all()

    def rebind(self, mesh) -> "ReplayQueue":
        """Elastic resize: re-target batch placement at the new mesh.
        In place (actors hold references to this queue); position/salt
        carry over untouched — the identity step→index mapping is the
        point."""
        with self._lock:
            self._mesh = mesh
        return self
