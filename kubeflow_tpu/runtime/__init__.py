from kubeflow_tpu.runtime.local import LocalPodRunner
