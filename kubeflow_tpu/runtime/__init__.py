from kubeflow_tpu.runtime.local import LocalPodRunner
from kubeflow_tpu.runtime.workloads import WorkloadMaterializer
