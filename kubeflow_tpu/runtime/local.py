"""Local pod runner: executes Pod resources as subprocesses.

The missing piece between the fake API server (storage semantics, no
kubelet — same gap as envtest, SURVEY.md §4.1) and a real E2E slice: it
watches Pods, launches each as a local subprocess with the container's env
injected, mirrors process lifecycle back onto pod status (Running →
Succeeded/Failed), and kills processes whose pods are deleted.

With the TpuJob operator this closes the loop of SURVEY.md §7.2's minimum
slice entirely in-process: TpuJob CR → operator creates a gang → runner
execs N local JAX processes → gloo/ICI collectives run → phases flow back
→ operator marks the job Succeeded.

Coordinator DNS names (``<pod>.<svc>.<ns>.svc``) don't resolve locally, so
the runner rewrites TPUJOB_COORDINATOR to ``localhost:<port>``, one port
per job.
"""

from __future__ import annotations

import logging
import os
import socket
import subprocess
import threading
import time

from kubeflow_tpu.api.objects import Resource
from kubeflow_tpu.testing.fake_apiserver import FakeApiServer, NotFound

log = logging.getLogger(__name__)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


class LocalPodRunner:
    def __init__(
        self,
        api: FakeApiServer,
        *,
        cwd: str | None = None,
        extra_env: dict[str, str] | None = None,
        capture_dir: str | None = None,
    ):
        self.api = api
        self.cwd = cwd
        self.extra_env = dict(extra_env or {})
        self.capture_dir = capture_dir
        self._procs: dict[tuple[str, str], subprocess.Popen] = {}
        self._job_ports: dict[str, int] = {}
        self._lock = threading.Lock()
        api.watch(self._on_pod, "Pod")

    def _on_pod(self, event: str, pod: Resource) -> None:
        if event == "DELETED":
            with self._lock:
                proc = self._procs.pop(
                    (pod.metadata.namespace, pod.metadata.name), None
                )
            if proc is not None and proc.poll() is None:
                proc.terminate()

    def _pod_env(self, pod: Resource) -> dict[str, str]:
        env = dict(os.environ)
        env.update(self.extra_env)
        for e in pod.spec["containers"][0].get("env", []):
            # Rendered trial templates may carry typed values; process env
            # must be strings.
            env[e["name"]] = str(e["value"])
        coord = env.get("TPUJOB_COORDINATOR")
        if coord:
            # One port per gang *incarnation*: a restarted gang must not
            # bind the port its terminating predecessor may still hold.
            labels = pod.metadata.labels
            gang = (
                labels.get("kubeflow-tpu.org/job", ""),
                labels.get("kubeflow-tpu.org/gang-incarnation", "0"),
            )
            with self._lock:
                port = self._job_ports.setdefault(gang, _free_port())
            env["TPUJOB_COORDINATOR"] = f"localhost:{port}"
        return env

    def step(self) -> None:
        """Start new pods, reap finished ones. Call in a loop."""
        for pod in self.api.list("Pod"):
            key = (pod.metadata.namespace, pod.metadata.name)
            phase = pod.status.get("phase")
            with self._lock:
                proc = self._procs.get(key)
            if proc is None and phase is None:
                self._start(pod, key)
            elif proc is not None and proc.poll() is not None:
                # Report the exit BEFORE untracking: if the status write
                # fails (apiserver outage), the process stays tracked and
                # the next step() retries — otherwise the exit is lost
                # and the pod reads Running forever.
                self._set_phase(
                    pod, "Succeeded" if proc.returncode == 0 else "Failed"
                )
                with self._lock:
                    self._procs.pop(key, None)

    def _start(self, pod: Resource, key: tuple[str, str]) -> None:
        c = pod.spec["containers"][0]
        # argv must be strings; rendered trial templates may carry typed
        # parameter values (e.g. a float lr) in args.
        cmd = [
            str(x) for x in list(c.get("command", [])) + list(c.get("args", []))
        ]
        if not cmd:
            self._set_phase(pod, "Failed")
            return
        stdout = None
        log_path = None
        if self.capture_dir:
            os.makedirs(self.capture_dir, exist_ok=True)
            log_path = os.path.abspath(
                os.path.join(self.capture_dir, f"{pod.metadata.name}.log")
            )
            stdout = open(log_path, "w")
        log.info("starting pod %s: %s", pod.metadata.name, " ".join(cmd))
        try:
            proc = subprocess.Popen(
                cmd,
                env=self._pod_env(pod),
                cwd=self.cwd,
                stdout=stdout,
                stderr=subprocess.STDOUT if stdout else None,
            )
        except OSError as e:
            log.error("pod %s failed to start: %s", pod.metadata.name, e)
            self._set_phase(pod, "Failed")
            return
        finally:
            # The child holds its own copy of the fd; keeping ours open
            # would leak one per pod start.
            if stdout is not None:
                stdout.close()
        with self._lock:
            self._procs[key] = proc
        # One status write: Running phase plus (when capturing) where the
        # pod's stdout lands, so the apiserver facade can serve `kubectl
        # logs` (`/apis/Pod/<ns>/<name>/log`, the kubelet log-endpoint
        # analog). A separate logPath write would double the MODIFIED
        # events every watcher sees per pod start.
        try:
            fresh = self.api.get(
                "Pod", pod.metadata.name, pod.metadata.namespace
            )
        except NotFound:
            return
        fresh = fresh.thaw()
        changed = fresh.status.get("phase") != "Running"
        fresh.status["phase"] = "Running"
        if log_path and fresh.status.get("logPath") != log_path:
            fresh.status["logPath"] = log_path
            changed = True
        if changed:
            self.api.update_status(fresh)

    def _set_phase(self, pod: Resource, phase: str) -> None:
        try:
            fresh = self.api.get(
                "Pod", pod.metadata.name, pod.metadata.namespace
            )
        except NotFound:
            return
        if fresh.status.get("phase") != phase:
            fresh = fresh.thaw()
            fresh.status["phase"] = phase
            self.api.update_status(fresh)

    def running_count(self) -> int:
        with self._lock:
            return sum(1 for p in self._procs.values() if p.poll() is None)

    def shutdown(self) -> None:
        with self._lock:
            procs = list(self._procs.values())
            self._procs.clear()
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
