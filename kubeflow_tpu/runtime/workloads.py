"""Workload materializer: StatefulSet/Deployment controller + kubelet
stand-in for the local runtime.

On a real cluster the built-in controllers and the kubelet turn a
StatefulSet/Deployment into running pods and readiness status; the
platform-in-a-box (`python -m kubeflow_tpu.apps`) has neither, so
notebooks and tensorboards would sit "waiting" forever (the reference's
equivalent gap is covered by a live GKE cluster in every E2E run —
`testing/kf_is_ready_test.py`). This closes the loop locally:

- each StatefulSet/Deployment gets `replicas` pods named `<name>-<i>`,
  carrying the pod template's labels/spec and an ownerReference (so
  cascade delete works), created directly in phase Running — the
  LocalPodRunner only adopts pods with no phase, so materialized pods
  are never exec'd as subprocesses;
- scale-down (the notebook stop/cull path sets replicas 0) deletes the
  excess pods;
- `status.readyReplicas` / `status.replicas` are mirrored back onto the
  workload, which is what the notebook/tensorboard controllers read to
  report readiness.
"""

from __future__ import annotations

import copy
import logging

from kubeflow_tpu.api.objects import Resource, new_resource, owner_ref
from kubeflow_tpu.testing.fake_apiserver import (
    AlreadyExists,
    Conflict,
    FakeApiServer,
    Invalid,
    NotFound,
)

log = logging.getLogger(__name__)

WORKLOAD_KINDS = ("StatefulSet", "Deployment")
LABEL_WORKLOAD = "kubeflow-tpu.org/workload"
# Disambiguates a StatefulSet and a Deployment sharing a name in one
# namespace — without it they would adopt (and fight over) each other's
# pods.
LABEL_WORKLOAD_KIND = "kubeflow-tpu.org/workload-kind"


class WorkloadMaterializer:
    def __init__(self, api: FakeApiServer):
        self.api = api
        self._last_rejection: dict[str, str] = {}

    def step(self) -> None:
        for kind in WORKLOAD_KINDS:
            for workload in self.api.list(kind):
                try:
                    self._reconcile(workload)
                except (Conflict, AlreadyExists, NotFound):
                    pass  # raced with a controller; next step converges
                except Invalid as e:
                    # Admission (e.g. quota) rejected this workload's pod:
                    # contained to THIS workload — others still reconcile
                    # — and surfaced on the owner instead of spamming the
                    # runner log at 5 Hz with nothing tenant-visible.
                    self._note_rejection(workload, e)

    def _note_rejection(self, workload: Resource, error: Invalid) -> None:
        """One Event per rejection episode (keyed on the message) — the
        tenant sees WHY their notebook/tensorboard pods never appear."""
        marker = f"rejected:{workload.kind}/{workload.metadata.name}"
        if self._last_rejection.get(marker) == str(error):
            return
        self._last_rejection[marker] = str(error)
        try:
            self.api.record_event(
                workload, "PodRejected", str(error), type_="Warning"
            )
        except Exception:
            log.warning("%s: pod rejected: %s", marker, error)

    @staticmethod
    def _pod_prefix(workload: Resource) -> str:
        """STS pods keep K8s's ordinal form `<name>-<i>`; Deployment pods
        get a `-dp-` segment so a same-name STS and Deployment never
        collide on pod names (on real K8s, Deployment pod names carry
        replicaset hashes for the same reason)."""
        if workload.kind == "Deployment":
            return workload.metadata.name + "-dp-"
        return workload.metadata.name + "-"

    def _pods_of(self, workload: Resource) -> dict[int, Resource]:
        prefix = self._pod_prefix(workload)
        out: dict[int, Resource] = {}
        for pod in self.api.list("Pod", workload.metadata.namespace):
            labels = pod.metadata.labels
            if (
                labels.get(LABEL_WORKLOAD) != workload.metadata.name
                or labels.get(LABEL_WORKLOAD_KIND) != workload.kind
            ):
                continue
            suffix = pod.metadata.name.removeprefix(prefix)
            if suffix.isdigit():
                out[int(suffix)] = pod
        return out

    def _reconcile(self, workload: Resource) -> None:
        if workload.metadata.deletion_timestamp:
            return
        replicas = int(workload.spec.get("replicas", 1))
        template = workload.spec.get("template") or {}
        pods = self._pods_of(workload)

        created = 0
        for index in range(replicas):
            if index in pods:
                continue
            labels = dict(
                (template.get("metadata") or {}).get("labels") or {}
            )
            labels[LABEL_WORKLOAD] = workload.metadata.name
            labels[LABEL_WORKLOAD_KIND] = workload.kind
            pod = new_resource(
                "Pod",
                f"{self._pod_prefix(workload)}{index}",
                workload.metadata.namespace,
                spec=copy.deepcopy(template.get("spec") or {}),
                labels=labels,
            )
            pod.metadata.owner_references = [owner_ref(workload)]
            # Born Running: these pods model long-running servers (jupyter,
            # tensorboard); phase != None keeps LocalPodRunner from trying
            # to exec the container image as a local subprocess.
            pod.status["phase"] = "Running"
            self.api.create(pod)
            created += 1
            log.info(
                "materialized pod %s/%s", pod.metadata.namespace,
                pod.metadata.name,
            )

        for index, pod in pods.items():
            if index >= replicas:
                try:
                    self.api.delete(
                        "Pod", pod.metadata.name, pod.metadata.namespace
                    )
                except NotFound:
                    pass

        # Count pods created this pass too, so a single step converges
        # (no one-tick readyReplicas lag).
        ready = created + sum(
            1
            for index, pod in pods.items()
            if index < replicas and pod.status.get("phase") == "Running"
        )
        fresh = self.api.get(
            workload.kind, workload.metadata.name, workload.metadata.namespace
        ).thaw()
        desired_status = {"replicas": replicas, "readyReplicas": ready}
        if {
            k: fresh.status.get(k) for k in desired_status
        } != desired_status:
            fresh.status.update(desired_status)
            self.api.update_status(fresh)
