"""Model serving — the platform's tf-serving analog, TPU-native.

The reference deploys TensorFlow Serving as an external container and
verifies it with a golden-prediction REST test
(`testing/test_tf_serving.py:60-156`, POST
`:8500/v1/models/mnist:predict`). This package provides the in-repo
equivalent: a JAX model server speaking the same REST surface
(`/v1/models/<name>` status + `:predict` verb), with TPU-first execution —
requests are padded into a small set of static batch buckets so XLA
compiles one program per bucket instead of one per request size, and the
hot path is a single jitted apply on device. Tensors cross the wire as
binary frames (`serving/wire.py`, ``application/x-kftpu-tensor``)
negotiated on the same routes, with the JSON surface intact for
TF-Serving parity clients.
"""

from kubeflow_tpu.serving.admission import (
    AdmissionController,
    QuotaSpec,
)
from kubeflow_tpu.serving.batching import BatchingConfig, BatchingQueue
from kubeflow_tpu.serving.registry import (
    ModelNotFound,
    PagingConfig,
    ServableRegistry,
)
from kubeflow_tpu.serving.replica import (
    HttpReplica,
    LocalReplica,
    LocalReplicaRuntime,
    MultiModelReplica,
)
from kubeflow_tpu.serving.router import (
    NoReadyReplicas,
    Overloaded,
    ReplicaGone,
    ReplicaOverloaded,
    Router,
)
from kubeflow_tpu.serving.servable import Servable
from kubeflow_tpu.serving.server import (
    FrontDoorApp,
    ModelRepository,
    ModelServerApp,
)
from kubeflow_tpu.serving.wire import (
    TENSOR_CONTENT_TYPE,
    WireFormatError,
    decode_tensor,
    encode_tensor,
)

__all__ = [
    "AdmissionController",
    "BatchingConfig",
    "BatchingQueue",
    "FrontDoorApp",
    "HttpReplica",
    "LocalReplica",
    "LocalReplicaRuntime",
    "ModelNotFound",
    "ModelRepository",
    "ModelServerApp",
    "MultiModelReplica",
    "NoReadyReplicas",
    "Overloaded",
    "PagingConfig",
    "QuotaSpec",
    "ReplicaGone",
    "ReplicaOverloaded",
    "Router",
    "Servable",
    "ServableRegistry",
    "TENSOR_CONTENT_TYPE",
    "WireFormatError",
    "decode_tensor",
    "encode_tensor",
]
