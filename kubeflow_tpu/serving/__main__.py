"""Model-server binary.

    python -m kubeflow_tpu.serving --model name=<ckpt_dir> ... [--port 8500]

Each --model loads an orbax checkpoint written by the training loop and
serves it at /v1/models/<name>. With no --model flags a demo model is
served under the name "demo" so the REST surface can be probed standalone
(the tf-serving sample served mnist the same way). The :predict route
speaks both JSON and the binary tensor protocol
(``application/x-kftpu-tensor``, `serving/wire.py`) — router-side
`HttpReplica` clients negotiate binary automatically.

Replica mode (the ServingDeployment data plane, docs/serving.md):

    python -m kubeflow_tpu.serving --apiserver URL[,URL...] \
        --replica <name> [--namespace ns]

The worker joins the fleet the serving controller materialized: it reads
its own ``ServingReplica`` object for config (model, batching knobs,
modelVersion — the PR 2 watch machinery is the push channel), loads the
servable, stamps ``status.ready`` + its endpoint + queue stats, and hot
swaps the model whenever the controller bumps ``spec.modelVersion``
(repository.load makes the new version latest; the server's predictor
swaps batching queues off the request path). The apiserver address is a
comma-separated endpoint list (`endpoints_from_env`) — a worker spawned
against one facade today transparently gains failover the day its env
grows a second endpoint.
"""

from __future__ import annotations

import argparse
import logging
import os
import threading

from kubeflow_tpu.utils import threads

log = logging.getLogger(__name__)

REPLICA_KIND = "ServingReplica"


def build_servable_from_rspec(rspec: dict, *, device=None):
    """Materialize the replica spec's model: an orbax checkpoint when
    `checkpointDir` is set (version = checkpoint step), else the demo
    model at the spec's modelVersion."""
    import jax
    import numpy as np

    from kubeflow_tpu.models.resnet import resnet50, tiny_resnet
    from kubeflow_tpu.serving.servable import Servable

    name = rspec.get("model", "demo")
    max_batch = int(rspec.get("maxBatch", 64))
    ckpt_dir = rspec.get("checkpointDir") or ""
    if ckpt_dir:
        return Servable.from_checkpoint(
            name,
            resnet50(),
            ckpt_dir,
            np.zeros((1, 224, 224, 3), np.float32),
            max_batch=max_batch,
            train=False,
        )
    module = tiny_resnet(num_classes=10)
    variables = jax.jit(module.init)(
        jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32)
    )
    return Servable.from_module(
        name, module, variables,
        version=int(rspec.get("modelVersion") or 1),
        max_batch=max_batch,
        warmup_example=np.zeros((32, 32, 3), np.float32),
        device=device,
        train=False,
    )


def sync_replica_once(
    api,
    name: str,
    namespace: str,
    repository,
    *,
    build_servable,
    endpoint: str = "",
    queue_stats=None,
) -> int | None:
    """One reconcile of worker state against the ServingReplica object:
    load the spec'd model version if it isn't serving yet, then stamp
    status (ready/version/endpoint/queue signal). Returns the live
    version, or None when the object is gone (deployment deleted — the
    caller shuts down). Idempotent and crash-safe: all state lives in
    the object and the repository."""
    from kubeflow_tpu.testing.fake_apiserver import Conflict, NotFound

    try:
        replica = api.get(REPLICA_KIND, name, namespace)
    except NotFound:
        return None
    rspec = dict(replica.spec)
    model_specs = rspec.get("models") or []
    model_rows: dict[str, int] = {}
    if model_specs:
        # Multiplexed fleet: one worker serves every spec'd model.
        # Unlike the in-process MultiModelReplica there is no paging
        # here — a worker owns its whole address space, so everything
        # it loads stays resident; LRU paging is the router-side
        # replica's concern.
        from kubeflow_tpu.serving.replica import LocalReplicaRuntime

        live = 0
        for mspec in model_specs:
            mr = LocalReplicaRuntime.model_rspec(rspec, mspec)
            mname = mr["model"]
            want = int(mr.get("modelVersion") or 0)
            try:
                mlive = repository.get(mname).version
            except Exception:
                mlive = None
            if mlive is None or (want and mlive != want):
                servable = build_servable(mr)
                repository.load(servable)
                mlive = servable.version
                log.info(
                    "replica %s: serving %s version %s", name, mname, mlive
                )
            model_rows[mname] = mlive
            live = max(live, mlive)
    else:
        model = rspec.get("model", "demo")
        want_version = int(rspec.get("modelVersion") or 0)
        try:
            live = repository.get(model).version
        except Exception:
            live = None
        if live is None or (want_version and live != want_version):
            servable = build_servable(rspec)
            repository.load(servable)
            live = servable.version
            log.info(
                "replica %s: serving %s version %s", name, model, live
            )
    status = {
        "ready": True,
        "version": live,
        "endpoint": endpoint,
        "pid": os.getpid(),
    }
    if model_rows:
        status["models"] = model_rows
    if queue_stats is not None:
        stats = queue_stats()
        status["queueDepth"] = int(stats.get("queue_depth") or 0)
        status["inflight"] = int(stats.get("inflight") or 0)
    try:
        fresh = api.get(REPLICA_KIND, name, namespace).thaw()
        new_status = dict(fresh.status)
        new_status.update(status)
        if new_status != fresh.status:
            fresh.status = new_status
            api.update_status(fresh)
    except (NotFound, Conflict):
        pass  # next heartbeat retries against fresh state
    return live


def run_replica(
    api,
    name: str,
    namespace: str,
    repository,
    *,
    build_servable,
    endpoint: str = "",
    queue_stats=None,
    heartbeat_s: float = 1.0,
    stop: threading.Event | None = None,
) -> None:
    """Worker loop: sync once, then re-sync on every watch event touching
    our object (config push — no polling for spec changes) plus a slow
    heartbeat that keeps the status queue signal fresh."""
    stop = stop or threading.Event()
    dirty = threading.Event()

    def on_event(event: str, obj) -> None:
        if (
            obj.metadata.name == name
            and obj.metadata.namespace == namespace
        ):
            dirty.set()

    api.watch(on_event, REPLICA_KIND)
    while not stop.is_set():
        dirty.clear()
        live = sync_replica_once(
            api, name, namespace, repository,
            build_servable=build_servable,
            endpoint=endpoint,
            queue_stats=queue_stats,
        )
        if live is None:
            log.info("replica %s: object gone; shutting down", name)
            return
        dirty.wait(heartbeat_s)


def main() -> None:
    logging.basicConfig(level=logging.INFO)
    parser = argparse.ArgumentParser(prog="kubeflow-tpu-model-server")
    parser.add_argument("--host", default="0.0.0.0")
    # TF Serving's REST port (`test_tf_serving.py:107` hits :8500).
    parser.add_argument("--port", type=int, default=8500)
    parser.add_argument(
        "--model",
        action="append",
        default=[],
        metavar="NAME=CKPT_DIR",
        help="serve an orbax checkpoint as /v1/models/NAME (repeatable)",
    )
    parser.add_argument("--max-batch", type=int, default=64)
    parser.add_argument(
        "--batch-timeout-ms",
        type=float,
        default=None,
        metavar="MS",
        help="enable cross-request dynamic batching with this flush "
        "window (the TF-Serving batch_timeout_micros analog); "
        "concurrent requests merge into one accelerator execution",
    )
    parser.add_argument(
        "--apiserver",
        default=None,
        help="facade URL, or a comma-separated endpoint list for an "
        "active-passive HA pair (token via KFTPU_TOKEN, CA via "
        "KFTPU_CA); enables replica mode with --replica",
    )
    parser.add_argument(
        "--replica",
        default=None,
        metavar="NAME",
        help="ServingReplica object this worker embodies (replica mode)",
    )
    parser.add_argument("--namespace", default="default")
    parser.add_argument(
        "--advertise",
        default=None,
        metavar="HOST:PORT",
        help="endpoint to publish in ServingReplica status "
        "(default: 127.0.0.1:<port>)",
    )
    args = parser.parse_args()
    if bool(args.apiserver) != bool(args.replica):
        parser.error("--apiserver and --replica go together")

    import jax
    import numpy as np

    from kubeflow_tpu.models.resnet import resnet50, tiny_resnet
    from kubeflow_tpu.serving import (
        BatchingConfig,
        ModelRepository,
        ModelServerApp,
        Servable,
    )
    from kubeflow_tpu.web.wsgi import serve

    servables = []
    for spec in args.model:
        name, _, ckpt_dir = spec.partition("=")
        if not name or not ckpt_dir:
            parser.error(f"--model {spec!r} must be NAME=CKPT_DIR")
        servables.append(
            Servable.from_checkpoint(
                name,
                resnet50(),
                ckpt_dir,
                np.zeros((1, 224, 224, 3), np.float32),
                max_batch=args.max_batch,
                train=False,
            )
        )
    if not servables and not args.replica:
        module = tiny_resnet(num_classes=10)
        variables = jax.jit(module.init)(
            jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32)
        )
        servables.append(
            Servable.from_module(
                "demo", module, variables,
                max_batch=args.max_batch,
                warmup_example=np.zeros((32, 32, 3), np.float32),
                train=False,
            )
        )

    batching = (
        BatchingConfig(
            max_batch=args.max_batch, timeout_ms=args.batch_timeout_ms
        )
        if args.batch_timeout_ms is not None
        else None
    )
    repository = ModelRepository(servables)
    app = ModelServerApp(repository, batching=batching)
    server, thread = serve(app, host=args.host, port=args.port)
    logging.info(
        "model server on :%d serving %s",
        server.server_port, [s.name for s in servables],
    )

    if args.replica:
        from kubeflow_tpu.testing.apiserver_http import (
            HttpApiClient,
            endpoints_from_env,
        )

        client = HttpApiClient(endpoints_from_env(args.apiserver))
        endpoint = args.advertise or f"127.0.0.1:{server.server_port}"
        try:
            run_replica(
                client,
                args.replica,
                args.namespace,
                repository,
                build_servable=build_servable_from_rspec,
                endpoint=endpoint,
            )
        finally:
            app.close_batchers()
            client.close()
        return

    # Foreground serve: park on the server thread in bounded slices
    # (an untimed join would wedge silently if the server thread ever
    # stuck); ^C shuts the server down and bounds the final join.
    if threads.run_until_interrupt(thread):
        server.shutdown()
        app.close_batchers()
        threads.join_thread(
            thread, timeout=10.0, what="model server thread"
        )


if __name__ == "__main__":
    main()
