"""Model-server binary.

    python -m kubeflow_tpu.serving --model name=<ckpt_dir> ... [--port 8500]

Each --model loads an orbax checkpoint written by the training loop and
serves it at /v1/models/<name>. With no --model flags a demo model is
served under the name "demo" so the REST surface can be probed standalone
(the tf-serving sample served mnist the same way).
"""

from __future__ import annotations

import argparse
import logging


def main() -> None:
    logging.basicConfig(level=logging.INFO)
    parser = argparse.ArgumentParser(prog="kubeflow-tpu-model-server")
    parser.add_argument("--host", default="0.0.0.0")
    # TF Serving's REST port (`test_tf_serving.py:107` hits :8500).
    parser.add_argument("--port", type=int, default=8500)
    parser.add_argument(
        "--model",
        action="append",
        default=[],
        metavar="NAME=CKPT_DIR",
        help="serve an orbax checkpoint as /v1/models/NAME (repeatable)",
    )
    parser.add_argument("--max-batch", type=int, default=64)
    parser.add_argument(
        "--batch-timeout-ms",
        type=float,
        default=None,
        metavar="MS",
        help="enable cross-request dynamic batching with this flush "
        "window (the TF-Serving batch_timeout_micros analog); "
        "concurrent requests merge into one accelerator execution",
    )
    args = parser.parse_args()

    import jax
    import numpy as np

    from kubeflow_tpu.models.resnet import resnet50, tiny_resnet
    from kubeflow_tpu.serving import (
        BatchingConfig,
        ModelRepository,
        ModelServerApp,
        Servable,
    )
    from kubeflow_tpu.web.wsgi import serve

    servables = []
    for spec in args.model:
        name, _, ckpt_dir = spec.partition("=")
        if not name or not ckpt_dir:
            parser.error(f"--model {spec!r} must be NAME=CKPT_DIR")
        servables.append(
            Servable.from_checkpoint(
                name,
                resnet50(),
                ckpt_dir,
                np.zeros((1, 224, 224, 3), np.float32),
                max_batch=args.max_batch,
                train=False,
            )
        )
    if not servables:
        module = tiny_resnet(num_classes=10)
        variables = jax.jit(module.init)(
            jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32)
        )
        servables.append(
            Servable.from_module(
                "demo", module, variables,
                max_batch=args.max_batch,
                warmup_example=np.zeros((32, 32, 3), np.float32),
                train=False,
            )
        )

    batching = (
        BatchingConfig(
            max_batch=args.max_batch, timeout_ms=args.batch_timeout_ms
        )
        if args.batch_timeout_ms is not None
        else None
    )
    app = ModelServerApp(ModelRepository(servables), batching=batching)
    server, thread = serve(app, host=args.host, port=args.port)
    logging.info(
        "model server on :%d serving %s",
        server.server_port, [s.name for s in servables],
    )
    thread.join()


if __name__ == "__main__":
    main()
