"""Priority admission control + per-tenant quotas for the router.

The multi-workload concurrency literature (arXiv:2011.03641) and every
multi-tenant serving deployment land on the same front-door policy:
under overload, shed the traffic that declared itself sheddable FIRST,
and bound each tenant's share so one runaway client cannot starve the
rest even below overload. Two independent gates, both decided before a
request is acknowledged (a shed is an honest 429 — it never enters the
router's `acked == completed + failed` accounting):

- **Priority classes.** Each class owns a *headroom fraction* of fleet
  capacity: class p is admitted only while fleet-wide outstanding work
  is below ``capacity × headroom[p]``. Lower classes have smaller
  fractions, so as load rises they shed first and the slots between
  their ceiling and 1.0 stay reserved for higher classes — that reserve
  is what holds high-priority p99 while the fleet is offered 2× its
  capacity in low-priority traffic (the bench's starvation gate).
- **Per-tenant token buckets.** A tenant with a `QuotaSpec` spends one
  token per request from a bucket refilled at ``rate`` tokens/s up to
  ``burst``; an empty bucket sheds with a Retry-After hint of the time
  until the next token. Tenants without a quota are uncapped.

The controller is deliberately router-agnostic: `check_priority` and
`acquire_quota` return verdicts, `serving/router.py` turns them into
`Overloaded` (→ HTTP 429 with jittered Retry-After at the boundary).
"""

from __future__ import annotations

import dataclasses
import threading
import time

from kubeflow_tpu.utils.metrics import MetricsRegistry

# Default ladder: "critical" may use the whole fleet, "standard" sheds
# when the last 20% of slots are all that's left, "batch" when the top
# half is consumed. Deployments override per-CR.
DEFAULT_PRIORITIES: dict[str, float] = {
    "critical": 1.0,
    "standard": 0.8,
    "batch": 0.5,
}


@dataclasses.dataclass(frozen=True)
class QuotaSpec:
    """Token-bucket quota: sustained ``rate`` requests/s, bursting to
    ``burst`` back-to-back."""

    rate: float
    burst: float = 1.0

    def validate(self) -> None:
        if self.rate <= 0:
            raise ValueError(f"quota rate must be > 0, got {self.rate}")
        if self.burst < 1:
            raise ValueError(f"quota burst must be >= 1, got {self.burst}")


@dataclasses.dataclass(frozen=True)
class Verdict:
    """One admission decision. ``retry_after`` is the UNjittered backoff
    hint; the boundary spreads it (router's seeded jitter) before it
    becomes a Retry-After header."""

    admitted: bool
    reason: str = ""
    retry_after: float = 0.0


class _Bucket:
    __slots__ = ("tokens", "stamp", "lock")

    def __init__(self, burst: float, now: float):
        # Stamped from the CONTROLLER's clock, not time.monotonic() —
        # with an injected clock a monotonic stamp makes the first
        # refill compute a garbage elapsed-time delta.
        self.tokens = burst
        self.stamp = now
        self.lock = threading.Lock()


class AdmissionController:
    """Priority + quota policy, shared by every request the router sees.

    ``priorities`` maps class name → headroom fraction in (0, 1]; an
    unknown class on a request is a client error (the boundary's 400),
    surfaced as ValueError. ``quotas`` maps tenant → `QuotaSpec`."""

    def __init__(
        self,
        *,
        priorities: dict[str, float] | None = None,
        quotas: dict[str, QuotaSpec] | None = None,
        metrics: MetricsRegistry | None = None,
        clock=time.monotonic,
    ):
        self.priorities = dict(priorities or DEFAULT_PRIORITIES)
        for name, fraction in self.priorities.items():
            if not 0.0 < fraction <= 1.0:
                raise ValueError(
                    f"priority {name!r} headroom must be in (0, 1], "
                    f"got {fraction}"
                )
        self._clock = clock
        self.quotas: dict[str, QuotaSpec] = {}
        self._buckets: dict[str, _Bucket] = {}
        for tenant, quota in (quotas or {}).items():
            self.set_quota(tenant, quota)
        metrics = metrics or MetricsRegistry()
        self.shed_priority_total = metrics.counter(
            "serving_admission_shed_priority_total",
            "requests shed because their class was out of headroom",
            ("priority",),
        )
        self.shed_quota_total = metrics.counter(
            "serving_admission_shed_quota_total",
            "requests shed by an exhausted tenant token bucket",
            ("tenant",),
        )

    def set_quota(self, tenant: str, quota: QuotaSpec) -> None:
        quota.validate()
        self.quotas[tenant] = quota
        self._buckets[tenant] = _Bucket(quota.burst, self._clock())

    def remove_quota(self, tenant: str) -> None:
        self.quotas.pop(tenant, None)
        self._buckets.pop(tenant, None)

    # -- the two gates -----------------------------------------------------

    def check_priority(
        self, priority: str, *, outstanding: int, capacity: int
    ) -> Verdict:
        """Headroom gate, called under the router lock (pure arithmetic,
        no blocking). Sheds when this class's slice of capacity is
        already consumed by outstanding work."""
        fraction = self.priorities.get(priority)
        if fraction is None:
            raise ValueError(
                f"unknown priority class {priority!r}; "
                f"known: {sorted(self.priorities)}"
            )
        ceiling = capacity * fraction
        if outstanding >= ceiling:
            self.shed_priority_total.inc(priority=priority)
            return Verdict(
                False,
                reason=(
                    f"priority {priority!r} out of headroom "
                    f"({outstanding} outstanding >= "
                    f"{ceiling:.0f} of {capacity} slots)"
                ),
            )
        return Verdict(True)

    def _charge_one(self, key: str, quota: QuotaSpec) -> Verdict:
        bucket = self._buckets[key]
        with bucket.lock:
            now = self._clock()
            bucket.tokens = min(
                quota.burst,
                bucket.tokens + (now - bucket.stamp) * quota.rate,
            )
            bucket.stamp = now
            if bucket.tokens >= 1.0:
                bucket.tokens -= 1.0
                return Verdict(True)
            wait = (1.0 - bucket.tokens) / quota.rate
        self.shed_quota_total.inc(tenant=key)
        return Verdict(
            False,
            reason=f"tenant {key!r} over quota ({quota.rate}/s)",
            retry_after=wait,
        )

    def _refund_one(self, key: str, quota: QuotaSpec) -> None:
        bucket = self._buckets.get(key)
        if bucket is None:  # quota removed between charge and refund
            return
        with bucket.lock:
            bucket.tokens = min(quota.burst, bucket.tokens + 1.0)

    def acquire_quota(self, *keys: str | None) -> Verdict:
        """Token-bucket gate over every quota'd key at once — charged
        once per request (NOT once per dispatch retry — a request that
        spreads across replicas spent one token), and all-or-nothing
        across keys (tenant bucket + ``model:<name>`` bucket): a shed
        by any bucket refunds the tokens already charged, so a capped
        model does not silently drain its tenants. Keys without a
        quota pass untouched. Buckets are charged one lock at a time
        (charge, then refund on a later shed) — never nested, so two
        requests sharing a key subset cannot deadlock."""
        charged: list[tuple[str, QuotaSpec]] = []
        for key in keys:
            if key is None:
                continue
            quota = self.quotas.get(key)
            if quota is None:
                continue
            verdict = self._charge_one(key, quota)
            if not verdict.admitted:
                for prior_key, prior_quota in charged:
                    self._refund_one(prior_key, prior_quota)
                return verdict
            charged.append((key, quota))
        return Verdict(True)
