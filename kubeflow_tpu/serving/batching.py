"""Cross-request dynamic batching — the TF-Serving batcher analog.

The reference deploys TF-Serving for inference (`docs_dev/tf_serving.md`,
`testing/test_tf_serving.py`), whose signature capability is the batching
scheduler: concurrent small requests are merged into one accelerator
execution (`max_batch_size` + `batch_timeout_micros`) because a TPU/GPU
step at batch 1 leaves the matrix units nearly idle — batch-64 ResNet-50
inference measures ~24x the throughput of batch-1 on v5e
(`bench.py --workload serving`). `BatchingQueue` is that scheduler for
our servables:

- callers block in `predict()` while their instances join the pending
  batch;
- a scheduler thread flushes when the batch fills (`max_batch`) or the
  OLDEST entry has waited `timeout_ms` (latency bound, TF-Serving's
  `batch_timeout_micros`);
- each flush groups entries by per-instance signature (shape, dtype)
  and runs one `Servable.predict` per group (the servable's own bucket
  padding handles the ragged tail); each caller gets exactly its rows
  back, and a failed execution propagates only to the callers of its
  own group — a malformed-shape request can't fail innocent neighbors.

**Continuous batching** (`BatchingConfig.continuous`, default on): when a
flush is already cut, each signature group *late-admits* compatible
requests that arrived after the cut, up to `max_batch`, immediately
before it executes. Under load the cut-and-wait cycle makes a request
that misses a cut wait out the ENTIRE in-flight execution plus its own
timeout window; late admission rides it into the window that's about to
run, which is where the p50 win under sustained concurrency comes from
(docs/serving.md). The admission happens on the scheduler thread, under
the queue lock, on host memory only — no device sync is added to the
flush path (enforced by the `serving-batch-continuous` lint contract).

The queue also exports its autoscaling input signal: queue-depth and
in-flight-batch gauges through `MetricsRegistry`, and a `stats()`
snapshot the serving controller aggregates into ServingDeployment
status (docs/serving.md).
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Sequence

import numpy as np

from kubeflow_tpu.utils.metrics import MetricsRegistry


@dataclasses.dataclass(frozen=True)
class BatchingConfig:
    """TF-Serving batching knobs (batching_config.txt analog)."""

    max_batch: int = 64
    timeout_ms: float = 5.0
    # Backpressure: pending instances beyond this reject immediately
    # (TF-Serving's max_enqueued_batches) instead of growing the queue
    # unboundedly under overload.
    max_pending: int = 1024
    # Continuous batching: late-admit compatible arrivals into the
    # in-flight flush window (see module docstring). Off restores the
    # original cut-and-wait cycle — kept selectable so the bench can
    # publish the delta honestly.
    continuous: bool = True


class _Entry:
    __slots__ = (
        "instances", "event", "result", "error", "arrived", "signature",
    )

    def __init__(self, instances: np.ndarray, servable):
        self.instances = instances
        self.event = threading.Event()
        self.result: np.ndarray | None = None
        self.error: BaseException | None = None
        self.arrived = time.monotonic()
        # Computed ONCE at admission: the scheduler re-reads it on every
        # cut, grouping pass, and late-admission scan — under the queue
        # lock, where per-entry tuple building was pure contention.
        self.signature = _signature(servable, instances)


def _signature(servable, instances: np.ndarray) -> tuple:
    """Flush-group key: ``(model, version, shape-sans-batch, dtype)``.

    Queues are per-servable, so within one queue the first two elements
    are constant — but the key carries them anyway: a multiplexed
    replica (`serving/registry.py`) must never merge two models' (or two
    generations') rows into one device execution, and making the model
    part of the KEY keeps that true even if flush windows are ever
    pooled across queues."""
    return (
        servable.name,
        getattr(servable, "version", 0),
        instances.shape[1:],
        instances.dtype.str,
    )


class QueueFull(RuntimeError):
    """Backpressure signal (the server boundary maps it to HTTP 429 with
    a Retry-After header — `serving/server.py`)."""


class QueueClosed(RuntimeError):
    """The queue was shut down (e.g. its servable version was reloaded);
    a retry against a fresh queue is expected to succeed."""


class BatchingQueue:
    """Thread-safe dynamic batcher over one servable."""

    def __init__(
        self,
        servable,
        config: BatchingConfig | None = None,
        metrics: MetricsRegistry | None = None,
    ):
        self.servable = servable
        self.config = config or BatchingConfig()
        metrics = metrics or MetricsRegistry()
        self.batches_total = metrics.counter(
            "serving_batches_total", "accelerator executions", ("model",)
        )
        self.batched_instances_total = metrics.counter(
            "serving_batched_instances_total",
            "instances served through the batcher",
            ("model",),
        )
        self.rejected_total = metrics.counter(
            "serving_batch_rejected_total",
            "requests rejected by backpressure",
            ("model",),
        )
        self.late_admitted_total = metrics.counter(
            "serving_batch_late_admitted_total",
            "requests admitted into an already-cut flush window",
            ("model",),
        )
        # The autoscaler's input signal (ServingDeployment status rides
        # on the same numbers via stats()).
        self.queue_depth = metrics.gauge(
            "serving_queue_depth",
            "instances waiting in the batching queue",
            ("model",),
        )
        self.inflight_batches = metrics.gauge(
            "serving_inflight_batches",
            "accelerator batches currently executing",
            ("model",),
        )
        self._cv = threading.Condition()
        # Deque, not list: _cut_locked consumes from the head, and under
        # a deep queue list.pop(0) made every cut O(pending) while
        # holding the lock every caller needs.
        self._pending: collections.deque[_Entry] = collections.deque()
        self._pending_count = 0
        self._inflight: list[_Entry] = []
        self._wait_ewma_ms = 0.0
        self._closed = False
        self._thread = threading.Thread(
            target=self._loop,
            name=f"batcher-{servable.name}-v{servable.version}",
            daemon=True,
        )
        self._thread.start()

    # -- caller side -------------------------------------------------------

    def predict(self, instances: Sequence) -> np.ndarray:
        batch = np.asarray(instances)
        if batch.shape[0] == 0:
            raise ValueError("empty instances")
        entry = _Entry(batch, self.servable)
        with self._cv:
            if self._closed:
                raise QueueClosed(
                    f"batching queue for {self.servable.name!r} is closed"
                )
            # Backpressure gates on what's ALREADY queued, not the new
            # request's own size — an oversized request on an idle server
            # must be admitted (the servable chunks it), or its retries
            # would fail forever.
            if self._pending_count >= self.config.max_pending:
                self.rejected_total.inc(model=self.servable.name)
                raise QueueFull(
                    f"batching queue for {self.servable.name!r} is full "
                    f"({self._pending_count} pending)"
                )
            was_empty = not self._pending
            prev_count = self._pending_count
            self._pending.append(entry)
            self._pending_count += batch.shape[0]
            self.queue_depth.set(
                self._pending_count, model=self.servable.name
            )
            # Wake the scheduler only when this admission changes what
            # it would do: first entry arms the timeout window (it is
            # parked in an untimed wait), and crossing max_batch makes
            # the cut due early. Everything else it discovers on its own
            # timed wakeup — under a deep queue the old unconditional
            # notify_all was thousands of pure-overhead scheduler
            # wakeups a second (docs/perf.md §serving wire path).
            if was_empty or (
                prev_count < self.config.max_batch <= self._pending_count
            ):
                self._cv.notify()
        entry.event.wait()
        if entry.error is not None:
            raise entry.error
        return entry.result

    def stats(self) -> dict:
        """Snapshot of the autoscaling signal: queued instances, instances
        executing right now, and an EWMA of the queue wait (ms)."""
        with self._cv:
            return {
                "queue_depth": self._pending_count,
                "inflight": sum(
                    e.instances.shape[0] for e in self._inflight
                ),
                "queue_wait_ms": round(self._wait_ewma_ms, 3),
                "closed": self._closed,
            }

    def close(self) -> None:
        """Flush and stop; in-flight callers complete, later ones error."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=30)

    def kill(self) -> None:
        """Hard stop (chaos / replica-death simulation): unlike close(),
        nothing drains — pending AND in-flight callers fail immediately
        with QueueClosed, the way a SIGKILLed replica's open connections
        reset. The router treats that as replica death and retries
        idempotent requests elsewhere (`serving/router.py`)."""
        with self._cv:
            self._closed = True
            pending, self._pending = (
                list(self._pending), collections.deque()
            )
            self._pending_count = 0
            self.queue_depth.set(0, model=self.servable.name)
            inflight = list(self._inflight)
            self._cv.notify_all()
        err = QueueClosed(
            f"batching queue for {self.servable.name!r} was killed"
        )
        for entry in pending + inflight:
            if not entry.event.is_set():
                entry.error = err
                entry.event.set()

    # -- scheduler ---------------------------------------------------------

    def _take_batch(self) -> list[_Entry]:
        """Block until a flush is due; returns the entries to run (empty
        only when closing). Flush when pending fills max_batch, or the
        oldest entry's deadline passes, or the queue is closing (drain)."""
        timeout = self.config.timeout_ms / 1000.0
        with self._cv:
            while True:
                if self._pending and (
                    self._closed
                    or self._pending_count >= self.config.max_batch
                ):
                    return self._cut_locked()
                if not self._pending:
                    if self._closed:
                        return []
                    self._cv.wait()
                    continue
                # Entries pending but batch not full: the window closes
                # `timeout` after the OLDEST entry arrived — a steady
                # trickle of arrivals must not extend the oldest caller's
                # wait indefinitely.
                remaining = self._pending[0].arrived + timeout - time.monotonic()
                if remaining <= 0 or not self._cv.wait(remaining):
                    return self._cut_locked()

    def _cut_locked(self) -> list[_Entry]:
        take: list[_Entry] = []
        count = 0
        while self._pending:
            nxt = self._pending[0]
            n = nxt.instances.shape[0]
            if take and count + n > self.config.max_batch:
                break  # next entry rides the following flush
            take.append(self._pending.popleft())
            count += n
            if count >= self.config.max_batch:
                break
        self._pending_count -= count
        self.queue_depth.set(self._pending_count, model=self.servable.name)
        self._record_wait_locked(take)
        # Becomes in-flight the instant it leaves pending, under the same
        # lock — a kill() racing the cut must find every caller in one of
        # the two lists or it would strand them on an unset event.
        self._inflight = list(take)
        return take

    def _record_wait_locked(self, entries: list[_Entry]) -> None:
        now = time.monotonic()
        for e in entries:
            wait_ms = (now - e.arrived) * 1000.0
            self._wait_ewma_ms += 0.2 * (wait_ms - self._wait_ewma_ms)

    def _admit_late(self, key: tuple, count: int) -> list[_Entry]:
        """Continuous batching: pull compatible pending entries into the
        group that is ABOUT to execute, up to max_batch. Host-side list
        surgery under the queue lock only — the flush path gains no
        device work or sync (serving-batch-continuous lint contract)."""
        with self._cv:
            taken: list[_Entry] = []
            kept: list[_Entry] = []
            for e in self._pending:
                n = e.instances.shape[0]
                if (
                    count + n <= self.config.max_batch
                    and e.signature == key
                ):
                    taken.append(e)
                    count += n
                else:
                    kept.append(e)
            if taken:
                # Mismatched entries stay IN ARRIVAL ORDER — the next
                # cut still honors the oldest caller's deadline.
                self._pending = collections.deque(kept)
                admitted = sum(e.instances.shape[0] for e in taken)
                self._pending_count -= admitted
                self.queue_depth.set(
                    self._pending_count, model=self.servable.name
                )
                self.late_admitted_total.inc(
                    len(taken), model=self.servable.name
                )
                self._record_wait_locked(taken)
                # kill() must cover late admissions too — they are
                # in-flight the moment they leave pending.
                self._inflight.extend(taken)
            return taken

    def _loop(self) -> None:
        while True:
            entries = self._take_batch()
            if not entries:
                return  # closed and drained
            # Group by per-instance signature (shape-sans-batch, dtype):
            # requests only merge with compatible neighbors (TF-Serving
            # batches per signature too), so one client's odd-shaped
            # input can neither break the concatenate nor fail innocent
            # requests sharing the flush.
            groups: dict = {}
            for entry in entries:
                groups.setdefault(entry.signature, []).append(entry)
            try:
                for key, group in groups.items():
                    self._run_group(key, group)
            except BaseException as e:
                # An interrupt/exit is taking this scheduler thread
                # down: close the queue and unblock EVERY caller that
                # hasn't been signalled yet (later signature groups in
                # this flush, plus everything still pending), then let
                # it propagate — a dying batcher must never leave a
                # predict() parked on an event nobody will set.
                self._abort(entries, e)
                raise
            finally:
                with self._cv:
                    self._inflight = []
                    self.inflight_batches.set(0, model=self.servable.name)

    def _abort(self, entries: list[_Entry], e: BaseException) -> None:
        with self._cv:
            self._closed = True  # later predict() gets QueueClosed
            pending, self._pending = (
                list(self._pending), collections.deque()
            )
            self._pending_count = 0
            self.queue_depth.set(0, model=self.servable.name)
            inflight, self._inflight = self._inflight, []
            self._cv.notify_all()
        for entry in entries + inflight + pending:
            if not entry.event.is_set():
                entry.error = e
                entry.event.set()

    def _run_group(self, key: tuple, group: list[_Entry]) -> None:
        if self.config.continuous:
            late = self._admit_late(
                key, sum(e.instances.shape[0] for e in group)
            )
            group = group + late
        self.inflight_batches.set(1, model=self.servable.name)
        try:
            # A flush window holding ONE entry (the batch-1 steady state
            # at low concurrency) skips the concatenate — np.concatenate
            # copies even for a single input, and this is the hot path.
            merged = (
                group[0].instances
                if len(group) == 1
                else np.concatenate(
                    [e.instances for e in group], axis=0
                )
            )
            out = self.servable.predict(merged)
        except BaseException as e:
            # Execution failures propagate to THIS group only. An
            # interrupt/exit also fails the group (the callers must not
            # hang), then re-raises so _loop can abort the rest of the
            # flush and die loudly instead of swallowing a shutdown.
            for entry in group:
                entry.error = e
                entry.event.set()
            if not isinstance(e, Exception):
                raise
            return
        self.batches_total.inc(model=self.servable.name)
        self.batched_instances_total.inc(
            merged.shape[0], model=self.servable.name
        )
        offset = 0
        for entry in group:
            n = entry.instances.shape[0]
            entry.result = out[offset:offset + n]
            offset += n
            entry.event.set()
