"""Cross-request dynamic batching — the TF-Serving batcher analog.

The reference deploys TF-Serving for inference (`docs_dev/tf_serving.md`,
`testing/test_tf_serving.py`), whose signature capability is the batching
scheduler: concurrent small requests are merged into one accelerator
execution (`max_batch_size` + `batch_timeout_micros`) because a TPU/GPU
step at batch 1 leaves the matrix units nearly idle — batch-64 ResNet-50
inference measures ~24x the throughput of batch-1 on v5e
(`bench.py --workload serving`). `BatchingQueue` is that scheduler for
our servables:

- callers block in `predict()` while their instances join the pending
  batch;
- a scheduler thread flushes when the batch fills (`max_batch`) or the
  OLDEST entry has waited `timeout_ms` (latency bound, TF-Serving's
  `batch_timeout_micros`);
- each flush groups entries by per-instance signature (shape, dtype)
  and runs one `Servable.predict` per group (the servable's own bucket
  padding handles the ragged tail); each caller gets exactly its rows
  back, and a failed execution propagates only to the callers of its
  own group — a malformed-shape request can't fail innocent neighbors.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Sequence

import numpy as np

from kubeflow_tpu.utils.metrics import MetricsRegistry


@dataclasses.dataclass(frozen=True)
class BatchingConfig:
    """TF-Serving batching knobs (batching_config.txt analog)."""

    max_batch: int = 64
    timeout_ms: float = 5.0
    # Backpressure: pending instances beyond this reject immediately
    # (TF-Serving's max_enqueued_batches) instead of growing the queue
    # unboundedly under overload.
    max_pending: int = 1024


class _Entry:
    __slots__ = ("instances", "event", "result", "error", "arrived")

    def __init__(self, instances: np.ndarray):
        self.instances = instances
        self.event = threading.Event()
        self.result: np.ndarray | None = None
        self.error: BaseException | None = None
        self.arrived = time.monotonic()


class QueueFull(RuntimeError):
    """Backpressure signal (callers map it to HTTP 429/503)."""


class QueueClosed(RuntimeError):
    """The queue was shut down (e.g. its servable version was reloaded);
    a retry against a fresh queue is expected to succeed."""


class BatchingQueue:
    """Thread-safe dynamic batcher over one servable."""

    def __init__(
        self,
        servable,
        config: BatchingConfig | None = None,
        metrics: MetricsRegistry | None = None,
    ):
        self.servable = servable
        self.config = config or BatchingConfig()
        metrics = metrics or MetricsRegistry()
        self.batches_total = metrics.counter(
            "serving_batches_total", "accelerator executions", ("model",)
        )
        self.batched_instances_total = metrics.counter(
            "serving_batched_instances_total",
            "instances served through the batcher",
            ("model",),
        )
        self.rejected_total = metrics.counter(
            "serving_batch_rejected_total",
            "requests rejected by backpressure",
            ("model",),
        )
        self._cv = threading.Condition()
        self._pending: list[_Entry] = []
        self._pending_count = 0
        self._closed = False
        self._thread = threading.Thread(
            target=self._loop,
            name=f"batcher-{servable.name}-v{servable.version}",
            daemon=True,
        )
        self._thread.start()

    # -- caller side -------------------------------------------------------

    def predict(self, instances: Sequence) -> np.ndarray:
        batch = np.asarray(instances)
        if batch.shape[0] == 0:
            raise ValueError("empty instances")
        entry = _Entry(batch)
        with self._cv:
            if self._closed:
                raise QueueClosed(
                    f"batching queue for {self.servable.name!r} is closed"
                )
            # Backpressure gates on what's ALREADY queued, not the new
            # request's own size — an oversized request on an idle server
            # must be admitted (the servable chunks it), or its retries
            # would fail forever.
            if self._pending_count >= self.config.max_pending:
                self.rejected_total.inc(model=self.servable.name)
                raise QueueFull(
                    f"batching queue for {self.servable.name!r} is full "
                    f"({self._pending_count} pending)"
                )
            self._pending.append(entry)
            self._pending_count += batch.shape[0]
            self._cv.notify_all()
        entry.event.wait()
        if entry.error is not None:
            raise entry.error
        return entry.result

    def close(self) -> None:
        """Flush and stop; in-flight callers complete, later ones error."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=30)

    # -- scheduler ---------------------------------------------------------

    def _take_batch(self) -> list[_Entry]:
        """Block until a flush is due; returns the entries to run (empty
        only when closing). Flush when pending fills max_batch, or the
        oldest entry's deadline passes, or the queue is closing (drain)."""
        timeout = self.config.timeout_ms / 1000.0
        with self._cv:
            while True:
                if self._pending and (
                    self._closed
                    or self._pending_count >= self.config.max_batch
                ):
                    return self._cut_locked()
                if not self._pending:
                    if self._closed:
                        return []
                    self._cv.wait()
                    continue
                # Entries pending but batch not full: the window closes
                # `timeout` after the OLDEST entry arrived — a steady
                # trickle of arrivals must not extend the oldest caller's
                # wait indefinitely.
                remaining = self._pending[0].arrived + timeout - time.monotonic()
                if remaining <= 0 or not self._cv.wait(remaining):
                    return self._cut_locked()

    def _cut_locked(self) -> list[_Entry]:
        take: list[_Entry] = []
        count = 0
        while self._pending:
            nxt = self._pending[0]
            n = nxt.instances.shape[0]
            if take and count + n > self.config.max_batch:
                break  # next entry rides the following flush
            take.append(self._pending.pop(0))
            count += n
            if count >= self.config.max_batch:
                break
        self._pending_count -= count
        return take

    def _loop(self) -> None:
        while True:
            entries = self._take_batch()
            if not entries:
                return  # closed and drained
            # Group by per-instance signature (shape-sans-batch, dtype):
            # requests only merge with compatible neighbors (TF-Serving
            # batches per signature too), so one client's odd-shaped
            # input can neither break the concatenate nor fail innocent
            # requests sharing the flush.
            groups: dict = {}
            for entry in entries:
                key = (entry.instances.shape[1:], entry.instances.dtype.str)
                groups.setdefault(key, []).append(entry)
            try:
                for group in groups.values():
                    self._run_group(group)
            except BaseException as e:
                # An interrupt/exit is taking this scheduler thread
                # down: close the queue and unblock EVERY caller that
                # hasn't been signalled yet (later signature groups in
                # this flush, plus everything still pending), then let
                # it propagate — a dying batcher must never leave a
                # predict() parked on an event nobody will set.
                self._abort(entries, e)
                raise

    def _abort(self, entries: list[_Entry], e: BaseException) -> None:
        with self._cv:
            self._closed = True  # later predict() gets QueueClosed
            pending, self._pending = self._pending, []
            self._pending_count = 0
            self._cv.notify_all()
        for entry in entries + pending:
            if not entry.event.is_set():
                entry.error = e
                entry.event.set()

    def _run_group(self, group: list[_Entry]) -> None:
        try:
            merged = np.concatenate([e.instances for e in group], axis=0)
            out = self.servable.predict(merged)
        except BaseException as e:
            # Execution failures propagate to THIS group only. An
            # interrupt/exit also fails the group (the callers must not
            # hang), then re-raises so _loop can abort the rest of the
            # flush and die loudly instead of swallowing a shutdown.
            for entry in group:
                entry.error = e
                entry.event.set()
            if not isinstance(e, Exception):
                raise
            return
        self.batches_total.inc(model=self.servable.name)
        self.batched_instances_total.inc(
            merged.shape[0], model=self.servable.name
        )
        offset = 0
        for entry in group:
            n = entry.instances.shape[0]
            entry.result = out[offset:offset + n]
            offset += n
            entry.event.set()
