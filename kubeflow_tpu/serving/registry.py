"""Per-replica multi-model servable registry with LRU weight paging.

TF-Serving's core economy trick (arXiv:1605.08695) is multiplexing: one
server process hosts N servables so tenants share the accelerator
instead of each paying for an idle fleet. `ServableRegistry` is that
layer for our replicas:

- **Per-model continuous-batch queues.** Every registered model owns its
  own `BatchingQueue`, so flush groups are keyed on
  ``(model, version, bucket-signature)`` — one slow or backed-up model
  can neither delay another model's flush window nor eat its pending
  budget (pinned by `tests/test_serving_batching.py`).
- **LRU weight paging.** With ``max_resident`` set, only the
  most-recently-used models hold device weights + a scheduler thread;
  the rest cost a catalog entry. A request for a paged-out model
  triggers a *page-in* (rebuild the servable via the registry's factory
  — checkpoint restore + bucket warmup on a real deployment) which is a
  measured event (`serving_page_in_seconds`), and blocks ONLY that
  model's callers: resident models keep flushing throughout because the
  load runs outside the registry lock.
- **Crisp death.** `kill()` is the SIGKILL analog: every model's queued
  and in-flight work fails with `QueueClosed` (→ `ReplicaGone` at the
  router). `kill(model)` during a page-in fails only that model's
  waiting callers — the other queues never notice.

The page-in/roll interaction (docs/serving.md failure matrix): a roll
arriving while a page-in is in flight waits the load out instead of
yanking the fresh queue, so the loading generation is never dropped
with callers parked on it.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable

from kubeflow_tpu.serving.batching import (
    BatchingConfig,
    BatchingQueue,
    QueueClosed,
)
from kubeflow_tpu.utils.metrics import MetricsRegistry


class ModelNotFound(KeyError):
    """No such model in the catalog (HTTP boundary maps this to 404 —
    distinct from a paged-out model, which is served after a page-in)."""


@dataclasses.dataclass(frozen=True)
class PagingConfig:
    """LRU weight-paging policy for one registry.

    ``max_resident`` bounds how many models hold live weights + a
    batcher thread at once; 0 means unlimited (paging off — every
    registered model stays resident once loaded)."""

    max_resident: int = 0

    def validate(self) -> None:
        if self.max_resident < 0:
            raise ValueError(
                f"paging.maxResident must be >= 0, got {self.max_resident}"
            )


# Catalog entry lifecycle: registered -> loading -> resident -> (paged
# out) registered. A whole-registry kill/close parks everything in
# "closed".
_REGISTERED = "registered"
_LOADING = "loading"
_RESIDENT = "resident"
_CLOSED = "closed"


class _ModelEntry:
    __slots__ = (
        "name", "rspec", "state", "queue", "servable", "version",
        "ready", "error", "last_used", "generation", "page_ins",
        "last_page_in_s",
    )

    def __init__(self, name: str, rspec: dict):
        self.name = name
        self.rspec = dict(rspec)
        self.state = _REGISTERED
        self.queue: BatchingQueue | None = None
        self.servable = None
        self.version = int(rspec.get("modelVersion", 0) or 0)
        # Signaled whenever a load settles (success, failure, or kill);
        # waiters re-check state under the lock — never trust the event
        # alone.
        self.ready = threading.Event()
        self.error: BaseException | None = None
        self.last_used = time.monotonic()
        # Bumped on every load claim and every kill: a page-in that
        # finishes after its generation moved on discards its queue
        # instead of resurrecting a killed/rolled model.
        self.generation = 0
        self.page_ins = 0
        self.last_page_in_s = 0.0


class ServableRegistry:
    """Thread-safe multi-model catalog: name → (servable, queue), with
    LRU paging. ``factory(rspec)`` builds a servable from a per-model
    replica spec dict (the same shape the controller pushes through
    ServingReplica objects)."""

    def __init__(
        self,
        factory: Callable[[dict], Any],
        *,
        batching: BatchingConfig | None = None,
        paging: PagingConfig | None = None,
        metrics: MetricsRegistry | None = None,
    ):
        self._factory = factory
        self.batching = batching or BatchingConfig()
        self.paging = paging or PagingConfig()
        self.paging.validate()
        self._metrics = metrics or MetricsRegistry()
        self._lock = threading.Lock()
        self._entries: dict[str, _ModelEntry] = {}
        self._closed = False
        self.page_ins_total = self._metrics.counter(
            "serving_page_ins_total",
            "servable weight page-ins (cold loads included)",
            ("model",),
        )
        self.page_outs_total = self._metrics.counter(
            "serving_page_outs_total",
            "servables evicted to make room under maxResident",
            ("model",),
        )
        self.resident_models = self._metrics.gauge(
            "serving_resident_models",
            "models currently holding live weights",
        )
        self.page_in_seconds = self._metrics.gauge(
            "serving_page_in_seconds",
            "duration of the most recent page-in",
            ("model",),
        )

    # -- catalog -----------------------------------------------------------

    def ensure(self, rspec: dict) -> bool:
        """Register (or update the spec of) one model. Returns True when
        the catalog changed — a changed spec does NOT swap a resident
        servable by itself; `roll()` does that under drain."""
        name = rspec.get("model")
        if not name:
            raise ValueError("rspec.model must be non-empty")
        with self._lock:
            self._check_open_locked()
            entry = self._entries.get(name)
            if entry is None:
                self._entries[name] = _ModelEntry(name, rspec)
                return True
            changed = entry.rspec != dict(rspec)
            entry.rspec = dict(rspec)
            return changed

    def remove(self, name: str) -> None:
        """Unregister a model; its resident queue (if any) drains and
        closes. Unknown names are a no-op (idempotent reconcile)."""
        with self._lock:
            entry = self._entries.pop(name, None)
            queue = self._demote_locked(entry) if entry else None
            if entry is not None:
                entry.state = _CLOSED
                entry.error = QueueClosed(f"model {name!r} was removed")
                entry.ready.set()
        if queue is not None:
            queue.close()

    def models(self) -> list[str]:
        with self._lock:
            return sorted(self._entries)

    # -- serving hot path --------------------------------------------------

    def predict(self, model: str, instances):
        """Serve one request for `model`, paging it in if needed. The
        per-model queue path: lookup + LRU touch under the registry lock,
        then straight into that model's own `BatchingQueue` — no other
        model's state is read or written (the `serving-batch` lint
        contract pins this path host-sync- and collective-free)."""
        for attempt in range(3):
            queue = self._resident_queue(model)
            try:
                return queue.predict(instances)
            except QueueClosed:
                # The queue closed between lookup and call: paged out or
                # rolled under us. Re-enter — the next pass pages the
                # model back in. A killed registry re-raises instead.
                with self._lock:
                    entry = self._entries.get(model)
                    dead = (
                        self._closed
                        or entry is None
                        or entry.state == _CLOSED
                    )
                if dead or attempt == 2:
                    raise

    def _resident_queue(self, model: str) -> BatchingQueue:
        """Return the model's live queue, claiming (or waiting out) a
        page-in when it is not resident. Only THIS model's callers ever
        wait here; the load itself runs outside the registry lock."""
        claim = False
        with self._lock:
            self._check_open_locked()
            entry = self._entries.get(model)
            if entry is None:
                raise ModelNotFound(model)
            entry.last_used = time.monotonic()
            if entry.state == _RESIDENT:
                return entry.queue
            if entry.state == _CLOSED:
                raise QueueClosed(f"model {model!r} is closed")
            if entry.state == _REGISTERED:
                claim = True
                self._claim_load_locked(entry)
            generation = entry.generation
            ready = entry.ready
        if claim:
            self._page_in(entry, generation)
        else:
            ready.wait(timeout=300.0)
        with self._lock:
            if entry.state == _RESIDENT:
                return entry.queue
            error = entry.error
        raise error if error is not None else QueueClosed(
            f"page-in of model {model!r} did not complete"
        )

    def _claim_load_locked(self, entry: _ModelEntry) -> None:
        entry.state = _LOADING
        entry.generation += 1
        entry.ready = threading.Event()
        entry.error = None

    def _page_in(self, entry: _ModelEntry, generation: int) -> None:
        """Build the servable + queue OUTSIDE the lock (the measured
        event — checkpoint restore and bucket warmup on a real replica),
        then install it if our generation still owns the entry."""
        t0 = time.monotonic()
        try:
            servable = self._factory(dict(entry.rspec))
            queue = BatchingQueue(servable, self.batching, self._metrics)
        except BaseException as e:
            # Unwind even on KeyboardInterrupt/SystemExit — a model
            # stuck in _LOADING parks every future caller forever —
            # but only factory *errors* are recorded and swallowed;
            # interrupts re-raise after waking the parked callers.
            with self._lock:
                if entry.generation == generation and (
                    entry.state == _LOADING
                ):
                    entry.state = _REGISTERED
                    entry.error = e
                    entry.ready.set()
            if not isinstance(e, Exception):
                raise
            return
        elapsed = time.monotonic() - t0
        stale = None
        victims: list[BatchingQueue] = []
        with self._lock:
            if (
                self._closed
                or entry.generation != generation
                or entry.state != _LOADING
            ):
                # Killed or rolled while loading: the fresh queue must
                # not resurrect the model.
                stale = queue
            else:
                entry.queue = queue
                entry.servable = servable
                entry.version = int(getattr(servable, "version", 0) or 0)
                entry.state = _RESIDENT
                entry.page_ins += 1
                entry.last_page_in_s = elapsed
                self.page_ins_total.inc(model=entry.name)
                self.page_in_seconds.set(elapsed, model=entry.name)
                victims = self._evict_locked(keep=entry)
                self._update_resident_gauge_locked()
                entry.ready.set()
        if stale is not None:
            stale.close()
        for victim in victims:
            victim.close()

    # -- paging ------------------------------------------------------------

    def _evict_locked(self, keep: _ModelEntry) -> list[BatchingQueue]:
        """LRU page-out down to max_resident. Idle victims are preferred
        (their close() is instant); if every candidate has queued work
        the least-recently-used one drains — honest memory bound over
        latency. Returns the queues to close outside the lock."""
        limit = self.paging.max_resident
        if limit <= 0:
            return []
        victims: list[BatchingQueue] = []
        while True:
            resident = [
                e for e in self._entries.values()
                if e.state == _RESIDENT and e is not keep
            ]
            if len(resident) + 1 <= limit:
                break
            idle = []
            for e in resident:
                s = e.queue.stats() if e.queue is not None else {}
                if not s.get("queue_depth") and not s.get("inflight"):
                    idle.append(e)
            victim = min(
                idle or resident, key=lambda e: e.last_used
            )
            queue = self._demote_locked(victim)
            if queue is not None:
                victims.append(queue)
            self.page_outs_total.inc(model=victim.name)
        return victims

    def _demote_locked(self, entry: _ModelEntry) -> BatchingQueue | None:
        queue, entry.queue = entry.queue, None
        entry.servable = None
        if entry.state == _RESIDENT:
            entry.state = _REGISTERED
        return queue

    def _update_resident_gauge_locked(self) -> None:
        self.resident_models.set(
            sum(1 for e in self._entries.values() if e.state == _RESIDENT)
        )

    # -- roll / teardown ---------------------------------------------------

    def roll(self, model: str, rspec: dict | None = None) -> None:
        """Swap one model to its (possibly updated) spec: drain the old
        queue, page the new generation in. A page-in already in flight
        is waited out first — the roll never discards a loading
        generation with callers parked on it (failure matrix:
        page-in-racing-roll)."""
        with self._lock:
            self._check_open_locked()
            entry = self._entries.get(model)
            if entry is None:
                raise ModelNotFound(model)
            if rspec is not None:
                entry.rspec = dict(rspec)
        while True:
            with self._lock:
                if entry.state != _LOADING:
                    break
                ready = entry.ready
            ready.wait(timeout=300.0)
        old_queue = None
        with self._lock:
            if entry.state == _CLOSED:
                raise QueueClosed(f"model {model!r} is closed")
            was_resident = entry.state == _RESIDENT
            if was_resident:
                old_queue = self._demote_locked(entry)
            self._claim_load_locked(entry)
            generation = entry.generation
            self._update_resident_gauge_locked()
        if old_queue is not None:
            old_queue.close()
        if was_resident:
            # Only a live model reloads eagerly; a paged-out one just
            # carries the new spec until its next page-in.
            self._page_in(entry, generation)
        else:
            with self._lock:
                if entry.generation == generation and (
                    entry.state == _LOADING
                ):
                    entry.state = _REGISTERED
                    entry.ready.set()

    def kill(self, model: str | None = None) -> None:
        """Hard stop. With a model name: fail ONLY that model's queued
        and in-flight work (including callers waiting on its page-in) —
        the other models' queues keep flushing, and the killed model can
        page back in on a later request. Without: the replica-death
        analog — everything fails with QueueClosed and the registry
        refuses further work."""
        queues: list[BatchingQueue] = []
        with self._lock:
            if model is not None:
                entries = [self._entries[model]]  # KeyError → caller bug
            else:
                entries = list(self._entries.values())
                self._closed = True
            for entry in entries:
                entry.generation += 1
                err = QueueClosed(
                    f"model {entry.name!r} was killed"
                    + (" during page-in" if entry.state == _LOADING else "")
                )
                if entry.queue is not None:
                    queues.append(entry.queue)
                queue = self._demote_locked(entry)
                del queue  # collected via `queues`
                entry.state = _CLOSED if model is None else _REGISTERED
                entry.error = err
                entry.ready.set()
            self._update_resident_gauge_locked()
        for queue in queues:
            queue.kill()

    def close(self) -> None:
        """Graceful teardown: every resident queue drains and stops."""
        queues: list[BatchingQueue] = []
        with self._lock:
            self._closed = True
            for entry in self._entries.values():
                if entry.queue is not None:
                    queues.append(entry.queue)
                self._demote_locked(entry)
                entry.state = _CLOSED
                entry.error = QueueClosed(
                    f"model {entry.name!r} is closed"
                )
                entry.ready.set()
            self._update_resident_gauge_locked()
        for queue in queues:
            queue.close()

    def _check_open_locked(self) -> None:
        if self._closed:
            raise QueueClosed("servable registry is closed")

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        """Per-model snapshot the replica adapter folds into its own
        stats() (and the controller into ServingDeployment status)."""
        with self._lock:
            entries = list(self._entries.values())
            resident = sum(1 for e in entries if e.state == _RESIDENT)
            per_model = {}
            for e in entries:
                row = {
                    "state": e.state,
                    "version": e.version,
                    "page_ins": e.page_ins,
                    "last_page_in_s": round(e.last_page_in_s, 6),
                }
                # Lock order registry → queue-cv, same as the eviction
                # scan; the queue never takes the registry lock back.
                if e.queue is not None:
                    row.update(e.queue.stats())
                per_model[e.name] = row
            closed = self._closed
        return {
            "models": per_model,
            "resident": resident,
            "closed": closed,
        }
