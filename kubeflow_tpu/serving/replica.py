"""Replica adapters and the replica runtime behind the serving router.

The router (`serving/router.py`) speaks one small surface — ``name``,
``capacity``, ``predict()``, optional ``stats()`` — and two exception
contracts (`ReplicaGone`, `ReplicaOverloaded`). Two adapters implement
it:

- `LocalReplica`: a Servable behind its own continuous `BatchingQueue`
  in this process. The single-binary dev/bench shape, and the unit the
  chaos tests hard-kill (`kill()` fails in-flight callers exactly the
  way a SIGKILLed process resets its connections).
- `HttpReplica`: a model-server process reached over a pooled
  keep-alive HTTP transport speaking the binary tensor protocol
  (`serving/wire.py`, JSON negotiation fallback); transport failures
  and 5xx map to `ReplicaGone` (and invalidate the pool), 429 maps to
  `ReplicaOverloaded` with the server's own Retry-After hint.

`LocalReplicaRuntime` is the materialization backend the serving
controller drives (`controllers/serving.py`): ensure/stop/roll replicas
against a router, reporting per-replica readiness and queue stats for
the ServingDeployment status.
"""

from __future__ import annotations

import http.client
import json
import select
import threading

import numpy as np

from kubeflow_tpu.serving import wire
from kubeflow_tpu.serving.batching import (
    BatchingConfig,
    BatchingQueue,
    QueueClosed,
    QueueFull,
)
from kubeflow_tpu.serving.registry import (
    ModelNotFound,
    PagingConfig,
    ServableRegistry,
)
from kubeflow_tpu.serving.router import (
    ReplicaGone,
    ReplicaOverloaded,
    Router,
)
from kubeflow_tpu.utils.metrics import MetricsRegistry


class LocalReplica:
    """One servable behind one continuous batching queue, in-process."""

    def __init__(
        self,
        name: str,
        servable,
        config: BatchingConfig | None = None,
        metrics: MetricsRegistry | None = None,
    ):
        self.name = name
        self._config = config or BatchingConfig()
        self._metrics = metrics
        self._lock = threading.Lock()
        self._dead = False
        self._queue = BatchingQueue(servable, self._config, metrics)

    @property
    def capacity(self) -> int:
        return self._config.max_pending

    @property
    def version(self) -> int:
        with self._lock:
            return self._queue.servable.version

    @property
    def ready(self) -> bool:
        with self._lock:
            return not self._dead and not self._queue.stats()["closed"]

    def predict(self, instances, *, model: str | None = None) -> np.ndarray:
        with self._lock:
            dead, queue = self._dead, self._queue
        if dead:
            raise ReplicaGone(f"replica {self.name!r} is dead")
        if model is not None and model != queue.servable.name:
            # Single-model replica asked for a different servable: a
            # model error (404 at the boundary), never a retry.
            raise ModelNotFound(model)
        try:
            return queue.predict(instances)
        except QueueFull as e:
            raise ReplicaOverloaded(str(e)) from e
        except QueueClosed as e:
            # Killed or torn down mid-request — to the caller that is
            # indistinguishable from process death.
            raise ReplicaGone(str(e)) from e

    def stats(self) -> dict:
        with self._lock:
            queue = self._queue
        return {
            "ready": self.ready,
            "version": queue.servable.version,
            **queue.stats(),
        }

    def swap(self, servable) -> None:
        """Replace the model (checkpoint roll). The caller must have
        quiesced this replica first (`Router.roll` drains before calling
        swap); the old queue closes after the new one is taking over, so
        a racing direct caller errors with QueueClosed → retry."""
        with self._lock:
            old, self._queue = self._queue, BatchingQueue(
                servable, self._config, self._metrics
            )
        old.close()

    def kill(self) -> None:
        """Chaos: die the way SIGKILL dies — in-flight and queued callers
        all fail immediately with ReplicaGone (via QueueClosed)."""
        with self._lock:
            self._dead = True
            queue = self._queue
        queue.kill()

    def close(self) -> None:
        with self._lock:
            queue = self._queue
        queue.close()


class MultiModelReplica:
    """N servables behind ONE replica slot: the multiplexing adapter
    over a `ServableRegistry` (per-model continuous-batch queues + LRU
    weight paging). The router surface is the same as `LocalReplica`'s
    plus the ``model=`` selector; exception mapping:

    - `ModelNotFound` propagates (a model error → 404 at the boundary,
      never a retry — every replica carries the same catalog);
    - `QueueFull` → `ReplicaOverloaded` (that MODEL's queue is full —
      siblings may still have room, the router respreads);
    - `QueueClosed` out of a killed registry → `ReplicaGone`.

    ``capacity`` is the router backpressure budget for the whole
    replica. The default (one model's ``max_pending``) is deliberately
    conservative — the fleet sheds before any single queue must."""

    def __init__(
        self,
        name: str,
        registry: ServableRegistry,
        *,
        capacity: int | None = None,
    ):
        self.name = name
        self.registry = registry
        self.capacity = (
            capacity
            if capacity is not None
            else registry.batching.max_pending
        )
        self._dead = False

    @property
    def ready(self) -> bool:
        return not self._dead and not self.registry.stats()["closed"]

    def predict(self, instances, *, model: str | None = None) -> np.ndarray:
        if self._dead:
            raise ReplicaGone(f"replica {self.name!r} is dead")
        if model is None:
            models = self.registry.models()
            if len(models) != 1:
                raise ModelNotFound(
                    "multiplexed replica needs an explicit model "
                    f"(serving {len(models)})"
                )
            model = models[0]
        try:
            return self.registry.predict(model, instances)
        except QueueFull as e:
            raise ReplicaOverloaded(str(e)) from e
        except QueueClosed as e:
            raise ReplicaGone(str(e)) from e

    def stats(self) -> dict:
        """Per-model registry snapshot plus the aggregate queue signal
        the autoscaler reads (sum of depths, worst wait)."""
        rstats = self.registry.stats()
        per_model = rstats["models"]
        return {
            "ready": self.ready,
            "models": per_model,
            "resident": rstats["resident"],
            "queue_depth": sum(
                m.get("queue_depth", 0) for m in per_model.values()
            ),
            "queue_wait_ms": max(
                (m.get("queue_wait_ms", 0.0) for m in per_model.values()),
                default=0.0,
            ),
        }

    def roll_model(self, model: str, rspec: dict) -> None:
        """Swap ONE model's generation; the other queues keep serving.
        `LocalReplicaRuntime.roll` calls this with the replica drained —
        per-model rolls ride the existing drain machinery."""
        self.registry.roll(model, rspec)

    def kill(self) -> None:
        """Chaos: replica death fails every model's queued and in-flight
        work with ReplicaGone (via the registry's QueueClosed)."""
        self._dead = True
        self.registry.kill()

    def close(self) -> None:
        self.registry.close()


class HttpReplica:
    """A model-server process (`python -m kubeflow_tpu.serving`) behind
    the router, reached over a POOLED keep-alive transport speaking the
    binary tensor protocol (`serving/wire.py`), with JSON as the
    negotiation fallback.

    The seed opened one TCP connection per request so that replica
    death stayed crisp; pooling keeps the death contract crisp a
    different way (docs/serving.md §wire protocol):

    - Every pooled socket carries the pool's **generation** stamp.
      `invalidate_pool()` (called on any transport failure, on router
      drain, and on close) bumps the generation and closes idle
      sockets; a request returning a socket from an older generation
      discards it instead of re-pooling — a socket from a dead or
      pre-drain incarnation can never serve a later request.
    - A **stale idle socket** — the peer reaped the keep-alive, so the
      socket shows EOF/reset BEFORE any request bytes are written — is
      detected by a zero-timeout readability probe at checkout and
      transparently replaced by one fresh dial. That is the only
      transparent retry.
    - Any failure **after bytes hit the wire** (send error, reset
      mid-response) still raises `ReplicaGone`, exactly as
      conn-per-request did: the router's idempotent-retry accounting
      and the `acked == completed + failed` invariant see the same
      crisp death signal.

    Protocol negotiation: requests go out as
    ``Content-Type: application/x-kftpu-tensor`` frames with a matching
    Accept. A server that has never answered a frame and 4xx's the
    first one is assumed JSON-only and the replica drops to the JSON
    surface for good (`binary=False` forces it from the start)."""

    def __init__(
        self,
        name: str,
        address: str,
        model: str,
        *,
        capacity: int = 256,
        timeout: float = 30.0,
        binary: bool = True,
        pool_size: int = 32,
    ):
        self.name = name
        host, _, port = address.rpartition(":")
        self._host, self._port = host, int(port)
        self._model = model
        self.capacity = capacity
        self._timeout = timeout
        self._pool_size = pool_size
        self._pool_lock = threading.Lock()
        self._idle: list[http.client.HTTPConnection] = []
        self._generation = 0
        self._dials = 0
        self._bytes_sent = 0
        self._bytes_received = 0
        # Negotiation state: try frames until the server rejects one
        # before ever accepting one. Flags are written OUTSIDE the pool
        # lock on purpose — they are monotonic one-way latches.
        self._binary = binary
        self._binary_confirmed = False

    # -- pooled transport --------------------------------------------------

    @staticmethod
    def _sock_idle_alive(conn) -> bool:
        """Zero-timeout staleness probe on an idle pooled socket: a
        readable idle HTTP connection means EOF, reset, or protocol
        garbage — all stale. No request bytes have been written yet, so
        discarding it is invisible to the death contract."""
        sock = conn.sock
        if sock is None:
            return False
        try:
            readable, _, _ = select.select([sock], [], [], 0)
        except (OSError, ValueError):
            return False
        return not readable

    def _checkout(self) -> tuple[int, http.client.HTTPConnection]:
        """A healthy connection + the generation it was issued under.
        Stale idle sockets are discarded (see `_sock_idle_alive`) and
        replaced by exactly one fresh dial."""
        while True:
            with self._pool_lock:
                generation = self._generation
                conn = self._idle.pop() if self._idle else None
                if conn is None:
                    self._dials += 1
            if conn is None:
                return generation, http.client.HTTPConnection(
                    self._host, self._port, timeout=self._timeout
                )
            if self._sock_idle_alive(conn):
                return generation, conn
            conn.close()

    def _checkin(self, generation: int, conn, resp) -> None:
        reusable = (
            conn.sock is not None
            and not resp.will_close
            and resp.isclosed()  # body fully read; framing intact
        )
        with self._pool_lock:
            if (
                reusable
                and generation == self._generation
                and len(self._idle) < self._pool_size
            ):
                self._idle.append(conn)
                return
        conn.close()

    def _account(self, sent: int, received: int) -> None:
        with self._pool_lock:
            self._bytes_sent += sent
            self._bytes_received += received

    def invalidate_pool(self) -> None:
        """Mark-dead / drain hook: bump the generation so nothing from
        the old incarnation is ever reused, and close idle sockets."""
        with self._pool_lock:
            self._generation += 1
            idle, self._idle = self._idle, []
        for conn in idle:
            conn.close()

    def close(self) -> None:
        self.invalidate_pool()

    def transport_stats(self) -> dict:
        """Observability for the bench and tests: dials tells you the
        pool is actually pooling, the byte counters feed the
        serving_wire_bytes_per_request row."""
        with self._pool_lock:
            return {
                "dials": self._dials,
                "idle": len(self._idle),
                "generation": self._generation,
                "bytes_sent": self._bytes_sent,
                "bytes_received": self._bytes_received,
            }

    def _request(
        self, method: str, path: str, body: bytes | None, headers: dict
    ) -> tuple[int, bytes, str | None, str]:
        """One request over the pool. Transport failure = the replica
        is gone: invalidate the pool (no sibling thread may reuse a
        socket into the dead incarnation) and raise `ReplicaGone`."""
        generation, conn = self._checkout()
        try:
            conn.request(method, path, body or b"", headers)
            resp = conn.getresponse()
            data = resp.read()
            status = resp.status
            retry_after = resp.getheader("Retry-After")
            content_type = resp.getheader("Content-Type") or ""
        except (OSError, http.client.HTTPException) as e:
            conn.close()
            self.invalidate_pool()
            raise ReplicaGone(
                f"replica {self.name!r} unreachable: {e}"
            ) from e
        self._account(len(body or b""), len(data))
        self._checkin(generation, conn, resp)
        return status, data, retry_after, content_type

    # -- request surface ---------------------------------------------------

    def predict(self, instances, *, model: str | None = None) -> np.ndarray:
        arr = np.asarray(instances)
        use_binary = self._binary
        if use_binary:
            body = wire.encode_tensor(arr)
            headers = {
                "Content-Type": wire.TENSOR_CONTENT_TYPE,
                "Accept": wire.TENSOR_CONTENT_TYPE,
            }
        else:
            body = json.dumps({"instances": arr.tolist()}).encode()
            headers = {
                "Content-Type": "application/json",
                "Accept": "application/json",
            }
        # Multiplexed dispatch rides the path, same as TF-Serving: the
        # router's model= selects which servable on the worker serves
        # this request; None keeps the replica's configured default.
        target = model or self._model
        status, data, retry_after, content_type = self._request(
            "POST", f"/v1/models/{target}:predict", body, headers
        )
        if (
            use_binary
            and not self._binary_confirmed
            and status in (400, 415, 501)
        ):
            # Negotiation failure: a server that never spoke a frame
            # rejected one — an old JSON-only surface. Fall back for
            # good; a genuinely bad input gets the same 4xx from the
            # JSON retry and propagates below.
            self._binary = False
            return self.predict(instances, model=model)
        if status == 429:
            raise ReplicaOverloaded(
                f"replica {self.name!r} shed the request",
                retry_after=float(retry_after or 0.05),
            )
        if status >= 500:
            self.invalidate_pool()
            raise ReplicaGone(
                f"replica {self.name!r} failed: HTTP {status}"
            )
        if status != 200:
            raise RuntimeError(
                f"replica {self.name!r} rejected the request: "
                f"HTTP {status}: {data[:200]!r}"
            )
        if wire.is_tensor_request({"content-type": content_type}):
            if use_binary:
                self._binary_confirmed = True
            return wire.decode_tensor(data)
        return np.asarray(json.loads(data)["predictions"])

    def stats(self) -> dict:
        """Honest readiness: probe ``GET /v1/models/<m>`` on the pooled
        connection instead of hardcoding ready. A wedged-but-listening
        worker (model never loaded, repository empty) now reports
        not-ready into the status aggregation instead of vanishing
        behind a hardcoded True."""
        try:
            status, _, _, _ = self._request(
                "GET", f"/v1/models/{self._model}", None, {}
            )
        except ReplicaGone:
            return {"ready": False}
        return {"ready": status == 200}


class LocalReplicaRuntime:
    """In-process replica fleet the serving controller materializes into.

    ``servable_factory(rspec)`` builds a Servable from a rendered replica
    spec (`api/serving.replica_spec`) — from a checkpoint dir in the real
    deployment, from a toy module in tests/bench.
    """

    def __init__(
        self,
        router: Router,
        servable_factory,
        metrics: MetricsRegistry | None = None,
    ):
        self.router = router
        self._factory = servable_factory
        self._metrics = metrics

    @staticmethod
    def _config(rspec: dict) -> BatchingConfig:
        batching = rspec.get("batching") or {}
        return BatchingConfig(
            max_batch=int(rspec.get("maxBatch", 64)),
            timeout_ms=float(batching.get("timeoutMs", 5.0)),
            max_pending=int(batching.get("maxPending", 1024)),
            continuous=bool(batching.get("continuous", True)),
        )

    def names(self) -> list[str]:
        return self.router.replica_names()

    def apply_model_policy(self, models) -> None:
        """Controller hook: push the CR catalog's admission policy
        (per-model priority class + quota buckets) onto the fleet's
        router on every reconcile."""
        self.router.set_model_policy(models)

    @staticmethod
    def model_rspec(rspec: dict, mspec: dict) -> dict:
        """Render ONE model's replica spec from the fleet rspec + its
        entry in ``models: [...]`` — the same single-model shape the
        servable factory has always consumed, so one factory serves
        both fleet flavors."""
        return {
            "model": mspec["name"],
            "maxBatch": rspec.get("maxBatch", 64),
            "batching": dict(rspec.get("batching") or {}),
            "checkpointDir": mspec.get(
                "checkpointDir", rspec.get("checkpointDir", "")
            ),
            "modelVersion": int(mspec.get("modelVersion", 0) or 0),
        }

    def ensure(self, name: str, rspec: dict) -> None:
        """Idempotent: bring the named replica up if it isn't already.
        An rspec carrying ``models: [...]`` materializes a multiplexed
        replica (ServableRegistry + LRU paging) instead of the
        single-servable shape."""
        if self.router.replica(name) is not None:
            return
        models = rspec.get("models")
        if models:
            paging = rspec.get("paging") or {}
            registry = ServableRegistry(
                self._factory,
                batching=self._config(rspec),
                paging=PagingConfig(
                    max_resident=int(paging.get("maxResident", 0) or 0)
                ),
                metrics=self._metrics,
            )
            for mspec in models:
                registry.ensure(self.model_rspec(rspec, mspec))
            self.router.add(MultiModelReplica(name, registry))
            return
        servable = self._factory(rspec)
        self.router.add(
            LocalReplica(
                name, servable, self._config(rspec), self._metrics
            )
        )

    def stop(self, name: str) -> None:
        """Scale-down teardown: drain first so in-flight work completes,
        then take the replica out of the fleet."""
        replica = self.router.replica(name)
        if replica is None:
            return
        self.router.drain(name)
        self.router.remove(name)
        replica.close()

    def roll(self, name: str, rspec: dict) -> float:
        """Drain-based hot swap to the spec's model version(s); returns
        the seconds the replica was out of rotation. On a multiplexed
        replica only the OUTDATED models reload — per-model rolls ride
        the same drain machinery, one replica at a time."""
        replica = self.router.replica(name)
        if replica is None:
            raise KeyError(f"unknown replica {name!r}")
        if isinstance(replica, MultiModelReplica):
            return self.router.roll(
                name, lambda: self._sync_models(replica, rspec)
            )
        return self.router.roll(
            name, lambda: replica.swap(self._factory(rspec))
        )

    def _sync_models(
        self, replica: MultiModelReplica, rspec: dict
    ) -> None:
        """Converge a (drained) multiplexed replica onto the rspec's
        model list: add new entries, reload models whose desired version
        moved (resident ones eagerly, paged-out ones lazily on their
        next page-in), drop models no longer listed."""
        desired = rspec.get("models") or []
        live = replica.registry.stats()["models"]
        for mspec in desired:
            mr = self.model_rspec(rspec, mspec)
            row = live.get(mspec["name"])
            want = int(mr.get("modelVersion", 0) or 0)
            if (
                row is not None
                and row["state"] == "resident"
                and want
                and row["version"] != want
            ):
                replica.roll_model(mspec["name"], mr)
            else:
                replica.registry.ensure(mr)
        keep = {m["name"] for m in desired}
        for name in replica.registry.models():
            if name not in keep:
                replica.registry.remove(name)

    def stats(self, name: str) -> dict | None:
        replica = self.router.replica(name)
        if replica is None:
            return None
        return replica.stats()


class ProcessReplicaRuntime:
    """Replica fleet as REAL model-server processes
    (``python -m kubeflow_tpu.serving --apiserver ... --replica ...``) —
    the production shape behind ``spec.runtime: process``.

    The split of responsibilities is deliberately thinner than
    `LocalReplicaRuntime`'s: this runtime only SPAWNS and REAPS
    processes. Config (model, batching, modelVersion) reaches a worker
    through its ServingReplica object over the apiserver facade — the
    worker self-rolls on config push (`serving/__main__.run_replica`),
    stamps its own status, and advertises its endpoint there. So there
    is no ``stats``/``roll`` surface here, ON PURPOSE: the serving
    controller's replica-object fallback path carries readiness and the
    roll, exactly as it would for workers on another machine.

    When a ``router`` is given, each worker's advertised endpoint is
    registered as an `HttpReplica` once it appears — in-process clients
    (the RL actors, the bench) then reach process replicas through the
    same drain-aware router surface as local ones.
    """

    def __init__(
        self,
        api,
        apiserver_url: str,
        *,
        router: Router | None = None,
        namespace: str = "default",
        extra_env: dict | None = None,
        python: str | None = None,
    ):
        import sys

        self.api = api
        self.apiserver_url = apiserver_url
        self.router = router
        self._namespace = namespace
        self._extra_env = dict(extra_env or {})
        self._python = python or sys.executable
        self._procs: dict = {}

    def names(self) -> list[str]:
        return list(self._procs)

    def ensure(self, name: str, rspec: dict) -> None:
        """Idempotent: spawn the worker process if it isn't running
        (a crashed worker is respawned on the next reconcile), and
        register its advertised endpoint once it has one."""
        import os
        import subprocess

        proc = self._procs.get(name)
        if proc is None or proc.poll() is not None:
            if proc is not None and self.router is not None:
                # The old incarnation's endpoint is dead with it —
                # including any pooled keep-alive sockets into it.
                stale = self.router.replica(name)
                self.router.remove(name)
                if stale is not None and hasattr(stale, "close"):
                    stale.close()
            self._procs[name] = subprocess.Popen(
                [
                    self._python, "-m", "kubeflow_tpu.serving",
                    "--host", "127.0.0.1", "--port", "0",
                    "--apiserver", self.apiserver_url,
                    "--replica", name,
                    "--namespace", self._namespace,
                ],
                env={
                    **os.environ,
                    "JAX_PLATFORMS": "cpu",
                    **self._extra_env,
                },
                stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
            )
        self._register(name)

    def _register(self, name: str) -> None:
        """Put the worker's advertised endpoint behind the router (once
        per live endpoint; the worker stamps it when it is ready)."""
        from kubeflow_tpu.testing.fake_apiserver import NotFound

        if self.router is None or self.router.replica(name) is not None:
            return
        try:
            robj = self.api.get("ServingReplica", name, self._namespace)
        except NotFound:
            return
        endpoint = robj.status.get("endpoint")
        if endpoint and robj.status.get("ready"):
            self.router.add(
                HttpReplica(
                    name, endpoint, robj.spec.get("model", "demo")
                )
            )

    def stop(self, name: str) -> None:
        """Teardown: out of the router first (stop admitting), then the
        process. The worker also exits on its own when its object is
        deleted — the SIGTERM just makes teardown prompt."""
        if self.router is not None and self.router.replica(name):
            replica = self.router.replica(name)
            self.router.drain(name)
            self.router.remove(name)
            if hasattr(replica, "close"):
                replica.close()
        proc = self._procs.pop(name, None)
        if proc is None or proc.poll() is not None:
            return
        proc.terminate()
        try:
            proc.wait(timeout=5)
        except Exception:
            proc.kill()
            proc.wait(timeout=5)

    def shutdown(self) -> None:
        for name in list(self._procs):
            self.stop(name)
