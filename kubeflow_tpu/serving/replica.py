"""Replica adapters and the replica runtime behind the serving router.

The router (`serving/router.py`) speaks one small surface — ``name``,
``capacity``, ``predict()``, optional ``stats()`` — and two exception
contracts (`ReplicaGone`, `ReplicaOverloaded`). Two adapters implement
it:

- `LocalReplica`: a Servable behind its own continuous `BatchingQueue`
  in this process. The single-binary dev/bench shape, and the unit the
  chaos tests hard-kill (`kill()` fails in-flight callers exactly the
  way a SIGKILLed process resets its connections).
- `HttpReplica`: a model-server process reached over HTTP
  (`serving/__main__.py`); connection failures and 5xx map to
  `ReplicaGone`, 429 maps to `ReplicaOverloaded` with the server's own
  Retry-After hint.

`LocalReplicaRuntime` is the materialization backend the serving
controller drives (`controllers/serving.py`): ensure/stop/roll replicas
against a router, reporting per-replica readiness and queue stats for
the ServingDeployment status.
"""

from __future__ import annotations

import http.client
import json
import threading

import numpy as np

from kubeflow_tpu.serving.batching import (
    BatchingConfig,
    BatchingQueue,
    QueueClosed,
    QueueFull,
)
from kubeflow_tpu.serving.router import (
    ReplicaGone,
    ReplicaOverloaded,
    Router,
)
from kubeflow_tpu.utils.metrics import MetricsRegistry


class LocalReplica:
    """One servable behind one continuous batching queue, in-process."""

    def __init__(
        self,
        name: str,
        servable,
        config: BatchingConfig | None = None,
        metrics: MetricsRegistry | None = None,
    ):
        self.name = name
        self._config = config or BatchingConfig()
        self._metrics = metrics
        self._lock = threading.Lock()
        self._dead = False
        self._queue = BatchingQueue(servable, self._config, metrics)

    @property
    def capacity(self) -> int:
        return self._config.max_pending

    @property
    def version(self) -> int:
        with self._lock:
            return self._queue.servable.version

    @property
    def ready(self) -> bool:
        with self._lock:
            return not self._dead and not self._queue.stats()["closed"]

    def predict(self, instances) -> np.ndarray:
        with self._lock:
            dead, queue = self._dead, self._queue
        if dead:
            raise ReplicaGone(f"replica {self.name!r} is dead")
        try:
            return queue.predict(instances)
        except QueueFull as e:
            raise ReplicaOverloaded(str(e)) from e
        except QueueClosed as e:
            # Killed or torn down mid-request — to the caller that is
            # indistinguishable from process death.
            raise ReplicaGone(str(e)) from e

    def stats(self) -> dict:
        with self._lock:
            queue = self._queue
        return {
            "ready": self.ready,
            "version": queue.servable.version,
            **queue.stats(),
        }

    def swap(self, servable) -> None:
        """Replace the model (checkpoint roll). The caller must have
        quiesced this replica first (`Router.roll` drains before calling
        swap); the old queue closes after the new one is taking over, so
        a racing direct caller errors with QueueClosed → retry."""
        with self._lock:
            old, self._queue = self._queue, BatchingQueue(
                servable, self._config, self._metrics
            )
        old.close()

    def kill(self) -> None:
        """Chaos: die the way SIGKILL dies — in-flight and queued callers
        all fail immediately with ReplicaGone (via QueueClosed)."""
        with self._lock:
            self._dead = True
            queue = self._queue
        queue.kill()

    def close(self) -> None:
        with self._lock:
            queue = self._queue
        queue.close()


class HttpReplica:
    """A model-server process (`python -m kubeflow_tpu.serving`) behind
    the router. One connection per request: the chaos variant SIGKILLs
    the process mid-load, and a pooled half-dead keepalive socket would
    blur the death signal the router's retry path depends on."""

    def __init__(
        self,
        name: str,
        address: str,
        model: str,
        *,
        capacity: int = 256,
        timeout: float = 30.0,
    ):
        self.name = name
        host, _, port = address.rpartition(":")
        self._host, self._port = host, int(port)
        self._model = model
        self.capacity = capacity
        self._timeout = timeout

    def predict(self, instances) -> np.ndarray:
        body = json.dumps(
            {"instances": np.asarray(instances).tolist()}
        ).encode()
        conn = http.client.HTTPConnection(
            self._host, self._port, timeout=self._timeout
        )
        try:
            conn.request(
                "POST",
                f"/v1/models/{self._model}:predict",
                body,
                {"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            data = resp.read()
            status = resp.status
            retry_after = resp.getheader("Retry-After")
        except (OSError, http.client.HTTPException) as e:
            raise ReplicaGone(
                f"replica {self.name!r} unreachable: {e}"
            ) from e
        finally:
            conn.close()
        if status == 429:
            raise ReplicaOverloaded(
                f"replica {self.name!r} shed the request",
                retry_after=float(retry_after or 0.05),
            )
        if status >= 500:
            raise ReplicaGone(
                f"replica {self.name!r} failed: HTTP {status}"
            )
        if status != 200:
            raise RuntimeError(
                f"replica {self.name!r} rejected the request: "
                f"HTTP {status}: {data[:200]!r}"
            )
        return np.asarray(json.loads(data)["predictions"])

    def stats(self) -> dict:
        return {"ready": True}


class LocalReplicaRuntime:
    """In-process replica fleet the serving controller materializes into.

    ``servable_factory(rspec)`` builds a Servable from a rendered replica
    spec (`api/serving.replica_spec`) — from a checkpoint dir in the real
    deployment, from a toy module in tests/bench.
    """

    def __init__(
        self,
        router: Router,
        servable_factory,
        metrics: MetricsRegistry | None = None,
    ):
        self.router = router
        self._factory = servable_factory
        self._metrics = metrics

    @staticmethod
    def _config(rspec: dict) -> BatchingConfig:
        batching = rspec.get("batching") or {}
        return BatchingConfig(
            max_batch=int(rspec.get("maxBatch", 64)),
            timeout_ms=float(batching.get("timeoutMs", 5.0)),
            max_pending=int(batching.get("maxPending", 1024)),
            continuous=bool(batching.get("continuous", True)),
        )

    def names(self) -> list[str]:
        return self.router.replica_names()

    def ensure(self, name: str, rspec: dict) -> None:
        """Idempotent: bring the named replica up if it isn't already."""
        if self.router.replica(name) is not None:
            return
        servable = self._factory(rspec)
        self.router.add(
            LocalReplica(
                name, servable, self._config(rspec), self._metrics
            )
        )

    def stop(self, name: str) -> None:
        """Scale-down teardown: drain first so in-flight work completes,
        then take the replica out of the fleet."""
        replica = self.router.replica(name)
        if replica is None:
            return
        self.router.drain(name)
        self.router.remove(name)
        replica.close()

    def roll(self, name: str, rspec: dict) -> float:
        """Drain-based hot swap to the spec's model version; returns the
        seconds the replica was out of rotation."""
        replica = self.router.replica(name)
        if replica is None:
            raise KeyError(f"unknown replica {name!r}")
        return self.router.roll(
            name, lambda: replica.swap(self._factory(rspec))
        )

    def stats(self, name: str) -> dict | None:
        replica = self.router.replica(name)
        if replica is None:
            return None
        return replica.stats()


class ProcessReplicaRuntime:
    """Replica fleet as REAL model-server processes
    (``python -m kubeflow_tpu.serving --apiserver ... --replica ...``) —
    the production shape behind ``spec.runtime: process``.

    The split of responsibilities is deliberately thinner than
    `LocalReplicaRuntime`'s: this runtime only SPAWNS and REAPS
    processes. Config (model, batching, modelVersion) reaches a worker
    through its ServingReplica object over the apiserver facade — the
    worker self-rolls on config push (`serving/__main__.run_replica`),
    stamps its own status, and advertises its endpoint there. So there
    is no ``stats``/``roll`` surface here, ON PURPOSE: the serving
    controller's replica-object fallback path carries readiness and the
    roll, exactly as it would for workers on another machine.

    When a ``router`` is given, each worker's advertised endpoint is
    registered as an `HttpReplica` once it appears — in-process clients
    (the RL actors, the bench) then reach process replicas through the
    same drain-aware router surface as local ones.
    """

    def __init__(
        self,
        api,
        apiserver_url: str,
        *,
        router: Router | None = None,
        namespace: str = "default",
        extra_env: dict | None = None,
        python: str | None = None,
    ):
        import sys

        self.api = api
        self.apiserver_url = apiserver_url
        self.router = router
        self._namespace = namespace
        self._extra_env = dict(extra_env or {})
        self._python = python or sys.executable
        self._procs: dict = {}

    def names(self) -> list[str]:
        return list(self._procs)

    def ensure(self, name: str, rspec: dict) -> None:
        """Idempotent: spawn the worker process if it isn't running
        (a crashed worker is respawned on the next reconcile), and
        register its advertised endpoint once it has one."""
        import os
        import subprocess

        proc = self._procs.get(name)
        if proc is None or proc.poll() is not None:
            if proc is not None and self.router is not None:
                # The old incarnation's endpoint is dead with it.
                self.router.remove(name)
            self._procs[name] = subprocess.Popen(
                [
                    self._python, "-m", "kubeflow_tpu.serving",
                    "--host", "127.0.0.1", "--port", "0",
                    "--apiserver", self.apiserver_url,
                    "--replica", name,
                    "--namespace", self._namespace,
                ],
                env={
                    **os.environ,
                    "JAX_PLATFORMS": "cpu",
                    **self._extra_env,
                },
                stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
            )
        self._register(name)

    def _register(self, name: str) -> None:
        """Put the worker's advertised endpoint behind the router (once
        per live endpoint; the worker stamps it when it is ready)."""
        from kubeflow_tpu.testing.fake_apiserver import NotFound

        if self.router is None or self.router.replica(name) is not None:
            return
        try:
            robj = self.api.get("ServingReplica", name, self._namespace)
        except NotFound:
            return
        endpoint = robj.status.get("endpoint")
        if endpoint and robj.status.get("ready"):
            self.router.add(
                HttpReplica(
                    name, endpoint, robj.spec.get("model", "demo")
                )
            )

    def stop(self, name: str) -> None:
        """Teardown: out of the router first (stop admitting), then the
        process. The worker also exits on its own when its object is
        deleted — the SIGTERM just makes teardown prompt."""
        if self.router is not None and self.router.replica(name):
            self.router.drain(name)
            self.router.remove(name)
        proc = self._procs.pop(name, None)
        if proc is None or proc.poll() is not None:
            return
        proc.terminate()
        try:
            proc.wait(timeout=5)
        except Exception:
            proc.kill()
            proc.wait(timeout=5)

    def shutdown(self) -> None:
        for name in list(self._procs):
            self.stop(name)
