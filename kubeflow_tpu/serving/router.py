"""Drain-aware request router over a fleet of serving replicas.

TF-Serving deployments put an external L7 balancer in front of N model
servers and rely on it for spread/retry; we route natively so the router
can see the batching queues it feeds (docs/parity.md carries the
deviation). Contracts:

- **Spread**: `predict()` dispatches to the admitting replica with the
  fewest outstanding requests (least-loaded, not round-robin — replica
  service times diverge the moment one is draining or cold).
- **Retry on replica death**: a replica that dies mid-request
  (`ReplicaGone` — connection reset, SIGKILL, hard queue kill) is marked
  dead and the request retries on a survivor, *if* the caller declared it
  idempotent. Inference is idempotent by default; double execution is
  safe, a dropped acknowledged request is not.
- **Load shedding**: when fleet-wide outstanding requests reach the
  admitting replicas' aggregate queue capacity, `predict()` raises
  `Overloaded` carrying `retry_after` — the server boundary turns that
  into an honest HTTP 429 + `Retry-After` *before* queues grow
  unboundedly, instead of letting every queue time out at once.
- **Drain** (`drain()` / `roll()`): stop admitting to one replica, let
  its in-flight work finish, swap the model, re-admit. A checkpoint roll
  is therefore zero-downtime: the rest of the fleet keeps admitting the
  whole time. A replica killed *mid-drain* fails its in-flight requests
  with `ReplicaGone`, which re-enter `predict()`'s retry path on another
  replica — the drain completes either way.

Acknowledgement accounting: a request is *acknowledged* once it passes
admission (i.e. it was not shed). The router's terminal accounting keeps
`acked == completed + failed`; the serving bench's chaos variant asserts
`failed == 0` while survivors exist — zero dropped acknowledged requests.
"""

from __future__ import annotations

import random
import threading
import time

from kubeflow_tpu.serving.admission import AdmissionController, QuotaSpec
from kubeflow_tpu.utils.metrics import MetricsRegistry


class RouterError(RuntimeError):
    pass


class NoReadyReplicas(RouterError):
    """No live replica exists at all (distinct from Overloaded: there is
    nobody to wait for, so retrying without operator action is futile)."""


class Overloaded(RouterError):
    """Load shed: the fleet is at capacity. `retry_after` (seconds) is
    the honest backoff hint the HTTP boundary forwards as Retry-After."""

    def __init__(self, msg: str, retry_after: float):
        super().__init__(msg)
        self.retry_after = retry_after


class ReplicaGone(RuntimeError):
    """The replica died or was torn down mid-request (connection reset,
    SIGKILL, queue hard-kill). Raised by replica adapters; the router
    converts it into mark-dead + retry-on-survivor."""


class ReplicaOverloaded(RuntimeError):
    """One replica refused the request (its queue is full); the router
    tries another — only a fleet-wide refusal becomes `Overloaded`."""

    def __init__(self, msg: str, retry_after: float = 0.05):
        super().__init__(msg)
        self.retry_after = retry_after


class _Slot:
    __slots__ = ("replica", "admitting", "dead", "outstanding")

    def __init__(self, replica):
        self.replica = replica
        self.admitting = True
        self.dead = False
        self.outstanding = 0


class Router:
    """Thread-safe fan-out of `predict()` across ready replicas.

    Replicas are any objects with ``name``, ``capacity`` (max queued
    requests it will hold — backpressure budget), and
    ``predict(instances)`` raising `ReplicaGone` / `ReplicaOverloaded`
    per the contracts above (`serving/replica.py` provides the local and
    HTTP adapters). On a multiplexed fleet every replica additionally
    accepts ``predict(instances, model=...)`` (the `MultiModelReplica`
    adapter over a `ServableRegistry`), and an `AdmissionController`
    gates requests by priority class + tenant quota before they count
    as acknowledged.
    """

    def __init__(
        self,
        metrics: MetricsRegistry | None = None,
        *,
        max_attempts: int = 4,
        retry_after_s: float = 0.25,
        dispatch_timeout_s: float = 30.0,
        admission: AdmissionController | None = None,
        retry_jitter_seed: int = 0,
    ):
        self._cv = threading.Condition()
        # Admission policy (priority headroom + tenant quotas) — None
        # keeps the original capacity-only shed, so single-model fleets
        # are untouched.
        self.admission = admission
        # ±50% spread on every Retry-After hint: a fixed value
        # synchronizes every shed client into a retry thundering herd
        # that re-sheds as one wave. Seeded so chaos gates replay the
        # same schedule run-to-run.
        self._retry_rng = random.Random(retry_jitter_seed)
        self._slots: dict[str, _Slot] = {}
        # Admission aggregates, maintained at every membership/state
        # change instead of recomputed per dispatch: _admit_locked sits
        # on the hot path of every request, and rebuilding the alive
        # list plus two sums per call was measurable lock-hold time at
        # bench concurrency (docs/perf.md §serving wire path).
        self._alive: list[_Slot] = []
        self._capacity = 0
        self._outstanding = 0
        self.max_attempts = max_attempts
        self.retry_after_s = retry_after_s
        self.dispatch_timeout_s = dispatch_timeout_s
        # Catalog-declared default priority class per model (CR
        # spec.models[].priority) — applied only when a request names
        # no class of its own.
        self._model_priority: dict[str, str] = {}
        metrics = metrics or MetricsRegistry()
        self._metrics_registry = metrics
        self.acked_total = metrics.counter(
            "serving_router_acked_total",
            "requests admitted past load shedding",
        )
        self.completed_total = metrics.counter(
            "serving_router_completed_total",
            "acknowledged requests that returned a result",
        )
        self.failed_total = metrics.counter(
            "serving_router_failed_total",
            "acknowledged requests the router could not complete",
        )
        self.shed_total = metrics.counter(
            "serving_router_shed_total",
            "requests shed at admission (HTTP 429 at the boundary)",
        )
        self.retried_total = metrics.counter(
            "serving_router_retried_total",
            "dispatches retried on another replica after replica death",
        )
        self.outstanding_gauge = metrics.gauge(
            "serving_router_outstanding",
            "requests currently dispatched to replicas",
        )

    # -- fleet membership --------------------------------------------------

    def add(self, replica) -> None:
        with self._cv:
            self._slots[replica.name] = _Slot(replica)
            self._refresh_locked()
            self._cv.notify_all()

    def remove(self, name: str) -> None:
        with self._cv:
            self._slots.pop(name, None)
            self._refresh_locked()
            self._cv.notify_all()

    def replica(self, name: str):
        with self._cv:
            slot = self._slots.get(name)
            return slot.replica if slot is not None else None

    def replica_names(self) -> list[str]:
        with self._cv:
            return sorted(self._slots)

    def ready_names(self) -> list[str]:
        with self._cv:
            return sorted(
                name
                for name, s in self._slots.items()
                if s.admitting and not s.dead
            )

    def set_model_policy(self, models) -> None:
        """Wire the CR catalog's admission policy (spec.models[]) into
        this router: each model's declared priority class becomes the
        default for requests that name none, and a nonzero
        ``quotaRate``/``quotaBurst`` becomes a per-model token bucket
        (key ``model:<name>``) charged alongside the tenant bucket.

        Idempotent under reconcile resync: an unchanged QuotaSpec keeps
        its live bucket (re-creating it would refill the burst every
        resync and the quota would never bind); only a changed spec
        resets, and models that dropped their quota (or left the
        catalog) lose their bucket."""
        self._model_priority = {m.name: m.priority for m in models}
        wanted = {
            f"model:{m.name}": QuotaSpec(
                rate=m.quota_rate, burst=m.quota_burst
            )
            for m in models
            if m.quota_rate > 0
        }
        if not wanted and self.admission is None:
            return
        if self.admission is None:
            self.admission = AdmissionController(
                metrics=self._metrics_registry
            )
        for key, quota in wanted.items():
            if self.admission.quotas.get(key) != quota:
                self.admission.set_quota(key, quota)
        for key in list(self.admission.quotas):
            if key.startswith("model:") and key not in wanted:
                self.admission.remove_quota(key)

    def stats(self) -> dict:
        """Aggregate autoscaling signal: fleet-wide outstanding plus each
        replica's own queue stats (the controller folds this into
        ServingDeployment status)."""
        with self._cv:
            slots = list(self._slots.items())
        per_replica = {}
        for name, slot in slots:
            stats_fn = getattr(slot.replica, "stats", None)
            try:
                rstats = stats_fn() if stats_fn else {}
            except Exception:
                rstats = {}
            per_replica[name] = {
                "admitting": slot.admitting,
                "dead": slot.dead,
                "outstanding": slot.outstanding,
                **rstats,
            }
        return {
            "outstanding": sum(s.outstanding for _, s in slots),
            "replicas": per_replica,
        }

    # -- dispatch ----------------------------------------------------------

    def _refresh_locked(self) -> None:
        """Rebuild the admission aggregates after any membership or
        admitting/dead flip. Replica capacity is read here, once per
        state change — a replica whose capacity attribute mutates
        mid-flight is out of contract."""
        self._alive = [
            s for s in self._slots.values() if not s.dead and s.admitting
        ]
        self._capacity = sum(
            max(int(s.replica.capacity), 1) for s in self._alive
        )

    def _retry_hint(self, base: float | None = None) -> float:
        """Retry-After with deterministic ±50% jitter: drawn from the
        seeded RNG so a replayed chaos run sheds the same schedule, but
        spread across [0.5, 1.5]× base so shed clients do not return as
        one synchronized wave (the thundering-herd regression)."""
        base = self.retry_after_s if base is None or base <= 0 else base
        return base * (0.5 + self._retry_rng.random())

    def _admit_locked(
        self, tried: set, priority: str = "standard"
    ) -> "_Slot | None":
        """Admission + selection under the lock. Raises NoReadyReplicas /
        Overloaded; returns None when every eligible replica was already
        tried this request (caller decides whether to wait and re-spread).

        `_outstanding` counts every dispatched-not-finished request,
        including those still in flight on replicas that have since been
        drained or removed — they hold real queue slots somewhere until
        they finish, so the shed decision is (slightly conservatively)
        honest about them."""
        alive = self._alive
        if not alive:
            if not any(not s.dead for s in self._slots.values()):
                raise NoReadyReplicas("no live serving replicas")
            # Everything live is draining; momentary — ask for a retry.
            raise Overloaded(
                "all replicas draining", retry_after=self._retry_hint()
            )
        if self.admission is not None:
            # Priority headroom first: a low class sheds at ITS ceiling
            # even before the fleet-wide capacity check would — the
            # reserved slots above the ceiling are what keep
            # high-priority p99 flat under 2× offered low-pri load.
            verdict = self.admission.check_priority(
                priority,
                outstanding=self._outstanding,
                capacity=self._capacity,
            )
            if not verdict.admitted:
                raise Overloaded(
                    verdict.reason,
                    retry_after=self._retry_hint(verdict.retry_after),
                )
        if self._outstanding >= self._capacity:
            raise Overloaded(
                f"fleet at capacity ({self._outstanding} outstanding >= "
                f"{self._capacity} queue slots)",
                retry_after=self._retry_hint(),
            )
        if not tried:  # the common path builds no per-request list
            return min(alive, key=lambda s: s.outstanding)
        candidates = [s for s in alive if s.replica.name not in tried]
        if not candidates:
            return None
        return min(candidates, key=lambda s: s.outstanding)

    def _finish_locked(self, slot: _Slot) -> None:
        slot.outstanding -= 1
        self._outstanding -= 1
        self.outstanding_gauge.dec()
        self._cv.notify_all()

    def predict(
        self,
        instances,
        *,
        model: str | None = None,
        priority: str | None = "standard",
        tenant: str | None = None,
        idempotent: bool = True,
    ):
        """Route one request. Raises `Overloaded` (shed — never acked),
        `NoReadyReplicas`, or the model error from the replica that
        served it. An acknowledged idempotent request survives replica
        death as long as one replica remains.

        `model` selects the servable on a multiplexed fleet (None keeps
        the single-model replicas' default); `priority`/`tenant` feed the
        admission controller when one is attached — a quota token is
        charged ONCE per request here, not per dispatch retry.
        `priority=None` defers to the model's catalog-declared class
        (`set_model_policy`), falling back to "standard"."""
        if priority is None:
            priority = self._model_priority.get(model or "", "standard")
        if self.admission is not None:
            verdict = self.admission.acquire_quota(
                tenant, f"model:{model}" if model else None
            )
            if not verdict.admitted:
                self.shed_total.inc()
                raise Overloaded(
                    verdict.reason,
                    retry_after=self._retry_hint(verdict.retry_after),
                )
        deadline = time.monotonic() + self.dispatch_timeout_s
        tried: set = set()
        acked = False
        attempts = 0
        while True:
            with self._cv:
                try:
                    slot = self._admit_locked(tried, priority)
                except Overloaded:
                    if not acked:
                        self.shed_total.inc()
                    else:
                        self.failed_total.inc()
                    raise
                except NoReadyReplicas:
                    if acked:
                        self.failed_total.inc()
                    raise
                if slot is None:
                    # Tried every admitting replica this pass (each one
                    # refused or died). Back off briefly and re-spread —
                    # admission said there IS capacity.
                    if time.monotonic() >= deadline:
                        if acked:
                            self.failed_total.inc()
                        else:
                            self.shed_total.inc()
                        raise Overloaded(
                            "every replica refused within the dispatch "
                            "deadline",
                            retry_after=self._retry_hint(),
                        )
                    tried = set()
                    self._cv.wait(0.005)
                    continue
                if not acked:
                    acked = True
                    self.acked_total.inc()
                slot.outstanding += 1
                self._outstanding += 1
                self.outstanding_gauge.inc()
                name = slot.replica.name
                replica = slot.replica
            try:
                if model is None:
                    result = replica.predict(instances)
                else:
                    result = replica.predict(instances, model=model)
            except ReplicaGone:
                with self._cv:
                    slot.dead = True
                    slot.admitting = False
                    self._refresh_locked()
                    self._finish_locked(slot)
                attempts += 1
                if not idempotent or attempts >= self.max_attempts:
                    self.failed_total.inc()
                    raise
                self.retried_total.inc()
                tried.add(name)
                continue
            except ReplicaOverloaded:
                # The replica's own queue beat our accounting (races with
                # direct callers); not a death — try a sibling.
                with self._cv:
                    self._finish_locked(slot)
                tried.add(name)
                continue
            except BaseException:
                # Model/input error: the replica executed and failed the
                # request on its merits — propagate, don't retry.
                with self._cv:
                    self._finish_locked(slot)
                self.failed_total.inc()
                raise
            with self._cv:
                self._finish_locked(slot)
            self.completed_total.inc()
            return result

    # -- drain / roll ------------------------------------------------------

    def drain(self, name: str, timeout: float = 30.0) -> bool:
        """Stop admitting to `name` and wait for its in-flight requests
        to finish (complete OR fail over to a sibling — a kill mid-drain
        converts the remainder into retries, see module docstring).
        Returns True once outstanding hits zero within `timeout`.

        A fully quiesced replica also gets its transport pool
        invalidated (if it has one — `HttpReplica.invalidate_pool`):
        the caller is about to swap or restart the process behind the
        address, and a pooled keep-alive socket into the pre-drain
        incarnation must never serve a post-roll request."""
        deadline = time.monotonic() + timeout
        with self._cv:
            slot = self._slots.get(name)
            if slot is None:
                return True
            slot.admitting = False
            self._refresh_locked()
            while slot.outstanding > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(remaining)
            replica = slot.replica
        invalidate = getattr(replica, "invalidate_pool", None)
        if invalidate is not None:
            invalidate()
        return True

    def admit(self, name: str) -> None:
        """Re-admit a drained (or replaced) replica. The caller vouches
        that the replica behind the slot is healthy again."""
        with self._cv:
            slot = self._slots.get(name)
            if slot is None:
                raise KeyError(f"unknown replica {name!r}")
            slot.admitting = True
            slot.dead = False
            self._refresh_locked()
            self._cv.notify_all()

    def roll(self, name: str, swap_fn, timeout: float = 30.0) -> float:
        """Zero-downtime hot swap: drain → swap_fn() → re-admit. Returns
        the wall seconds the replica was out of rotation. swap_fn runs
        with the replica fully quiesced (no in-flight work)."""
        start = time.monotonic()
        if not self.drain(name, timeout=timeout):
            raise TimeoutError(
                f"replica {name!r} did not drain within {timeout}s"
            )
        swap_fn()
        self.admit(name)
        return time.monotonic() - start
