"""Servable: a loaded model behind a bucketed, jit-compiled predict fn.

TPU-first design notes:

- **Static batch buckets.** XLA compiles one program per input shape; a
  server that forwards raw request batch sizes would recompile on every
  new size (20-40s each on TPU). Requests are padded up to the nearest
  bucket (powers of two up to ``max_batch``), so the server compiles at
  most ``log2(max_batch)+1`` programs, all warmed at load time.
- **Device residency.** Params are placed on device once at load; the hot
  path moves only the request batch.
- **Larger requests** are split into ``max_batch`` chunks and re-batched
  through the same buckets — throughput stays on the biggest program.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def _buckets(max_batch: int) -> list[int]:
    out, b = [], 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return out


@dataclasses.dataclass
class Servable:
    """One model version the server can execute."""

    name: str
    apply_fn: Callable[[Any, jax.Array], jax.Array]
    variables: Any
    version: int = 1
    max_batch: int = 64
    # Pin execution to a specific device (e.g. jax.devices("cpu")[0] for
    # a frontend-co-located executor, or benchmarking the serving stack
    # without a tunneled accelerator in the loop). None = default device.
    device: Any = None

    def __post_init__(self):
        self.variables = (
            jax.device_put(self.variables, self.device)
            if self.device is not None
            else jax.device_put(self.variables)
        )
        self._jitted = jax.jit(self.apply_fn)
        self._bucket_sizes = _buckets(self.max_batch)

    def _to_device(self, batch) -> jax.Array:
        if self.device is not None:
            # Straight host→device placement: jnp.asarray first would
            # round-trip through the DEFAULT device (the tunneled TPU)
            # before landing on the pinned one.
            return jax.device_put(batch, self.device)
        return jnp.asarray(batch)

    @classmethod
    def from_module(
        cls,
        name: str,
        module,
        variables: Any,
        *,
        version: int = 1,
        max_batch: int = 64,
        warmup_example=None,
        device=None,
        **apply_kwargs,
    ) -> "Servable":
        """Wrap a flax module (``module.apply``) as a servable. Pass
        ``warmup_example`` (one instance, no batch dim) to compile every
        batch bucket before the servable takes traffic."""

        def apply_fn(variables, batch):
            return module.apply(variables, batch, **apply_kwargs)

        servable = cls(
            name, apply_fn, variables, version=version,
            max_batch=max_batch, device=device,
        )
        if warmup_example is not None:
            servable.warmup_with(warmup_example)
        return servable

    @classmethod
    def from_checkpoint(
        cls,
        name: str,
        module,
        ckpt_dir,
        example_input: jax.Array,
        *,
        max_batch: int = 64,
        **apply_kwargs,
    ) -> "Servable":
        """Restore params from an orbax checkpoint dir written by the
        training loop (`kubeflow_tpu.train.checkpoint`). The abstract state
        comes from a module init on the example input; the servable version
        is the checkpoint step, so clients can see which step is live."""
        from kubeflow_tpu.train.checkpoint import Checkpointer

        variables = jax.eval_shape(
            lambda: module.init(jax.random.PRNGKey(0), example_input)
        )
        # read_only: serving must never rename a training run's steps
        # (e.g. a committed save whose manifest is still in flight).
        ckpt = Checkpointer(ckpt_dir, read_only=True)
        try:
            restored = ckpt.restore_latest(variables)
        finally:
            ckpt.close()
        if restored is None:
            raise FileNotFoundError(f"no checkpoint under {ckpt_dir}")
        variables, step = restored.state, restored.step
        return cls.from_module(
            name, module, variables,
            version=max(step, 1), max_batch=max_batch,
            # The checkpoint path is the serving deployment path, so warm
            # every bucket here — first-compile must not land on a request.
            warmup_example=np.asarray(example_input)[0],
            **apply_kwargs,
        )

    def _bucket_for(self, n: int) -> int:
        for b in self._bucket_sizes:
            if n <= b:
                return b
        return self.max_batch

    def predict(self, instances: Sequence) -> np.ndarray:
        """Run inference on a list of instances (one array-like each).

        Pads to the nearest bucket, executes the jitted program, slices the
        padding back off. Oversized requests are chunked at max_batch.
        """
        batch = np.asarray(instances)
        if batch.shape[0] == 0:
            raise ValueError("empty instances")
        if batch.shape[0] > self.max_batch:
            parts = [
                self.predict(batch[i : i + self.max_batch])
                for i in range(0, batch.shape[0], self.max_batch)
            ]
            return np.concatenate(parts, axis=0)
        n = batch.shape[0]
        bucket = self._bucket_for(n)
        if bucket != n:
            pad = np.zeros((bucket - n, *batch.shape[1:]), batch.dtype)
            batch = np.concatenate([batch, pad], axis=0)
        out = self._jitted(self.variables, self._to_device(batch))
        return np.asarray(out)[:n]

    def warmup_with(self, example_instance) -> None:
        """Compile every bucket before serving traffic (first compile on
        TPU is tens of seconds; it must not land on a user request)."""
        one = np.asarray(example_instance)[None]
        for b in self._bucket_sizes:
            batch = np.repeat(one, b, axis=0)
            self._jitted(
                self.variables, self._to_device(batch)
            ).block_until_ready()
