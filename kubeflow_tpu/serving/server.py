"""Model server: TF-Serving-compatible REST surface over JAX servables.

Parity contract (`testing/test_tf_serving.py:107-118`): clients POST
``/v1/models/<name>:predict`` with ``{"instances": [...]}`` and get
``{"predictions": [...]}`` back; the E2E test compares predictions to a
golden JSON within tolerance. ``GET /v1/models/<name>`` reports version
state the way TF Serving's model-status API does.
"""

from __future__ import annotations

import logging
from typing import Iterable

from kubeflow_tpu.serving.servable import Servable
from kubeflow_tpu.utils.metrics import MetricsRegistry
from kubeflow_tpu.web import (
    App,
    HttpError,
    Request,
    Response,
    json_response,
)

log = logging.getLogger(__name__)


class ModelRepository:
    """Named servables, hot-swappable by version (load() replaces)."""

    def __init__(self, servables: Iterable[Servable] = ()):
        self._models: dict[str, Servable] = {}
        for s in servables:
            self.load(s)

    def load(self, servable: Servable) -> None:
        prev = self._models.get(servable.name)
        self._models[servable.name] = servable
        if prev is not None:
            log.info(
                "model %s: version %d -> %d",
                servable.name, prev.version, servable.version,
            )

    def get(self, name: str) -> Servable:
        try:
            return self._models[name]
        except KeyError:
            raise HttpError(404, f"model {name!r} not found") from None

    def names(self) -> list[str]:
        return sorted(self._models)


class ModelServerApp(App):
    def __init__(
        self,
        repository: ModelRepository,
        *,
        metrics: MetricsRegistry | None = None,
    ):
        super().__init__("model-server")
        self.repository = repository
        metrics = metrics or MetricsRegistry()
        self.request_count = metrics.counter(
            "serving_requests_total", "predict requests", ("model", "outcome")
        )
        self._metrics_registry = metrics
        # The :predict verb lives inside the final path segment (TF Serving
        # convention), so one route captures `name` or `name:verb` and the
        # handler splits it.
        self.add_route("/v1/models/<name>", self.model_get)
        self.add_route("/v1/models/<name>", self.model_post, ("POST",))
        self.add_route("/v1/models", self.models_list)
        self.add_route("/metrics", self.metrics_text)

    @staticmethod
    def _split_verb(raw: str) -> tuple[str, str | None]:
        if ":" in raw:
            name, verb = raw.split(":", 1)
            return name, verb
        return raw, None

    def models_list(self, req: Request) -> Response:
        return json_response({"models": self.repository.names()})

    def model_get(self, req: Request) -> Response:
        name, verb = self._split_verb(req.path_params["name"])
        if verb is not None:
            raise HttpError(405, f"verb {verb!r} requires POST")
        model = self.repository.get(name)
        return json_response(
            {
                "model_version_status": [
                    {
                        "version": str(model.version),
                        "state": "AVAILABLE",
                        "status": {"error_code": "OK", "error_message": ""},
                    }
                ]
            }
        )

    def model_post(self, req: Request) -> Response:
        name, verb = self._split_verb(req.path_params["name"])
        if verb != "predict":
            raise HttpError(400, f"unsupported verb {verb!r}")
        model = self.repository.get(name)
        body = req.json()
        instances = body.get("instances")
        if not isinstance(instances, list) or not instances:
            self.request_count.inc(model=name, outcome="invalid")
            raise HttpError(400, "body must have a non-empty 'instances' list")
        try:
            predictions = model.predict(instances)
        except HttpError:
            raise
        except Exception as e:
            import jax

            if isinstance(e, jax.errors.JaxRuntimeError):
                # Device/runtime fault (preemption, OOM) on well-formed
                # input — a server error, not the client's; let the App
                # catch-all surface it as 500 so retries/alerts fire.
                self.request_count.inc(model=name, outcome="error")
                raise
            # Everything else is malformed input: ragged lists (ValueError
            # from np.asarray), wrong rank/shape (flax ScopeParamShapeError
            # or jax TypeError) — all bad requests.
            self.request_count.inc(model=name, outcome="invalid")
            log.info("predict on %s rejected: %s", name, e)
            raise HttpError(400, f"bad instances: {e}") from None
        self.request_count.inc(model=name, outcome="ok")
        return json_response({"predictions": predictions.tolist()})

    def metrics_text(self, req: Request) -> Response:
        return Response(
            body=self._metrics_registry.expose_text().encode(),
            content_type="text/plain; version=0.0.4",
        )
