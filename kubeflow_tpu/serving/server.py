"""Model server: TF-Serving-compatible REST surface over JAX servables.

Parity contract (`testing/test_tf_serving.py:107-118`): clients POST
``/v1/models/<name>:predict`` with ``{"instances": [...]}`` and get
``{"predictions": [...]}`` back; the E2E test compares predictions to a
golden JSON within tolerance. ``GET /v1/models/<name>`` reports version
state the way TF Serving's model-status API does.

Wire negotiation (`serving/wire.py`, docs/serving.md §wire protocol):
the same :predict route also accepts ``Content-Type:
application/x-kftpu-tensor`` frames — decoded with ``np.frombuffer``,
no JSON, no per-element Python objects — and answers in kind when the
Accept header (or the request's own content type) asks for it. JSON
requests get byte-identical JSON responses; nothing about the parity
contract moves.
"""

from __future__ import annotations

import logging
import random
import threading
from typing import Iterable

from kubeflow_tpu.serving import wire
from kubeflow_tpu.serving.batching import BatchingQueue, QueueClosed, QueueFull
from kubeflow_tpu.serving.registry import ModelNotFound
from kubeflow_tpu.serving.router import (
    NoReadyReplicas,
    Overloaded,
    ReplicaGone,
    Router,
)
from kubeflow_tpu.serving.servable import Servable
from kubeflow_tpu.utils.metrics import MetricsRegistry
from kubeflow_tpu.web import (
    App,
    HttpError,
    Request,
    Response,
    json_response,
)

log = logging.getLogger(__name__)

# Priority/tenant ride request headers so the admission decision needs
# no body parse (a shed request's body is never decoded past the WSGI
# read).
PRIORITY_HEADER = "x-kftpu-priority"
TENANT_HEADER = "x-kftpu-tenant"


def _format_retry_after(seconds: float) -> str:
    """Retry-After with two decimals. RFC 7231 wants integer seconds;
    we deliberately emit fractional ones (docs/serving.md §admission) —
    our clients parse float, and rounding a jittered sub-second hint up
    to 1 would re-synchronize the very herd the jitter de-correlates."""
    return f"{max(0.01, seconds):.2f}"


class ModelRepository:
    """Named servables, several live versions per model.

    TF-Serving semantics: loading a new version makes it the default
    (latest) for unversioned requests while older versions stay
    addressable at ``/versions/<v>`` until unloaded — the window a
    client-side rollout needs."""

    def __init__(self, servables: Iterable[Servable] = ()):
        # Guards the version table: the WSGI server is threaded, and
        # load()/unload() are the live-rollout path — a reader must never
        # observe a half-applied mutation.
        self._lock = threading.Lock()
        self._models: dict[str, dict[int, Servable]] = {}
        for s in servables:
            self.load(s)

    def load(self, servable: Servable) -> None:
        with self._lock:
            versions = self._models.setdefault(servable.name, {})
            if versions:
                log.info(
                    "model %s: +version %d (latest was %d)",
                    servable.name, servable.version, max(versions),
                )
            versions[servable.version] = servable

    def unload(self, name: str, version: int) -> None:
        with self._lock:
            versions = self._models.get(name) or {}
            if version not in versions:
                raise HttpError(
                    404, f"model {name!r} version {version} not found"
                )
            del versions[version]
            if not versions:
                del self._models[name]

    def get(self, name: str, version: int | None = None) -> Servable:
        with self._lock:
            versions = self._models.get(name)
            if not versions:
                raise HttpError(404, f"model {name!r} not found")
            if version is None:
                return versions[max(versions)]
            try:
                return versions[version]
            except KeyError:
                raise HttpError(
                    404, f"model {name!r} version {version} not found"
                ) from None

    def versions(self, name: str) -> list[Servable]:
        with self._lock:
            versions = self._models.get(name)
            if not versions:
                raise HttpError(404, f"model {name!r} not found")
            return [versions[v] for v in sorted(versions)]

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._models)


class ModelServerApp(App):
    def __init__(
        self,
        repository: ModelRepository,
        *,
        metrics: MetricsRegistry | None = None,
        batching=None,
        retry_jitter_seed: int = 0,
    ):
        """`batching`: a `serving.BatchingConfig` turns on the TF-Serving
        batching-scheduler analog — concurrent requests merge into one
        accelerator execution per flush (`serving/batching.py`)."""
        super().__init__("model-server")
        self.repository = repository
        self._batching = batching
        self._batchers: dict = {}
        self._batcher_lock = threading.Lock()
        # ±50% Retry-After spread, seeded (chaos gates replay): a fixed
        # hint synchronizes every shed client into one retry wave.
        self._retry_rng = random.Random(retry_jitter_seed)
        metrics = metrics or MetricsRegistry()
        self.request_count = metrics.counter(
            "serving_requests_total", "predict requests", ("model", "outcome")
        )
        self._metrics_registry = metrics
        # The :predict verb lives inside the final path segment (TF Serving
        # convention), so one route captures `name` or `name:verb` and the
        # handler splits it.
        self.add_route("/v1/models/<name>", self.model_get)
        self.add_route("/v1/models/<name>", self.model_post, ("POST",))
        self.add_route(
            "/v1/models/<name>/versions/<version>", self.model_get
        )
        self.add_route(
            "/v1/models/<name>/versions/<version>", self.model_post, ("POST",)
        )
        self.add_route("/v1/models", self.models_list)
        self.add_route("/metrics", self.metrics_text)

    @staticmethod
    def _split_verb(raw: str) -> tuple[str, str | None]:
        if ":" in raw:
            name, verb = raw.split(":", 1)
            return name, verb
        return raw, None

    def models_list(self, req: Request) -> Response:
        return json_response({"models": self.repository.names()})

    @staticmethod
    def _version_param(req: Request) -> tuple[int | None, str | None]:
        """(version, verb) from a /versions/<v> segment, when present.
        The :verb suffix rides the LAST path segment (TF-Serving URL
        convention), which is the version on versioned routes."""
        raw = req.path_params.get("version")
        if raw is None:
            return None, None
        raw, verb = ModelServerApp._split_verb(raw)
        try:
            return int(raw), verb
        except ValueError:
            raise HttpError(400, f"version must be an integer, got {raw!r}")

    def model_get(self, req: Request) -> Response:
        name, verb = self._split_verb(req.path_params["name"])
        version, vverb = self._version_param(req)
        if verb is not None or vverb is not None:
            raise HttpError(405, "verbs require POST")
        if version is not None:
            statuses = [self.repository.get(name, version)]
        else:
            # Unversioned status reports every live version (TF-Serving's
            # model-status API shape).
            statuses = self.repository.versions(name)
        return json_response(
            {
                "model_version_status": [
                    {
                        "version": str(m.version),
                        "state": "AVAILABLE",
                        "status": {"error_code": "OK", "error_message": ""},
                    }
                    for m in statuses
                ]
            }
        )

    def model_post(self, req: Request) -> Response:
        name, verb = self._split_verb(req.path_params["name"])
        version, vverb = self._version_param(req)
        if version is not None:
            if verb is not None:
                # /v1/models/m:predict/versions/1 — the verb belongs on
                # the LAST segment; reject rather than silently ignore.
                raise HttpError(
                    400, "on versioned routes the :verb goes after the "
                    "version, e.g. /versions/1:predict",
                )
            verb = vverb
        if verb != "predict":
            raise HttpError(400, f"unsupported verb {verb!r}")
        model = self.repository.get(name, version)
        if wire.is_tensor_request(req.headers):
            instances = self._binary_instances(req, name)
        else:
            body = req.json()
            instances = body.get("instances")
            if not isinstance(instances, list) or not instances:
                self.request_count.inc(model=name, outcome="invalid")
                raise HttpError(
                    400, "body must have a non-empty 'instances' list"
                )
        try:
            try:
                predictions = self._predictor(model)(instances)
            except QueueClosed:
                # Raced a version reload: the stale queue closed between
                # lookup and predict. One retry hits the fresh queue.
                predictions = self._predictor(model)(instances)
        except HttpError:
            raise
        except QueueFull as e:
            # Backpressure (TF-Serving's max_enqueued_batches): an honest
            # 429 WITH Retry-After at the boundary — every caller used to
            # re-derive the backoff hint itself. A full queue clears at
            # flush cadence, so the hint is one flush window, floored at
            # 1s (Retry-After is integer seconds on the wire).
            self.request_count.inc(model=name, outcome="overload")
            raise HttpError(
                429, str(e), headers=[("Retry-After", self._retry_after())]
            ) from None
        except Exception as e:
            import jax

            if isinstance(e, jax.errors.JaxRuntimeError):
                # Device/runtime fault (preemption, OOM) on well-formed
                # input — a server error, not the client's; let the App
                # catch-all surface it as 500 so retries/alerts fire.
                self.request_count.inc(model=name, outcome="error")
                raise
            # Everything else is malformed input: ragged lists (ValueError
            # from np.asarray), wrong rank/shape (flax ScopeParamShapeError
            # or jax TypeError) — all bad requests.
            self.request_count.inc(model=name, outcome="invalid")
            log.info("predict on %s rejected: %s", name, e)
            raise HttpError(400, f"bad instances: {e}") from None
        self.request_count.inc(model=name, outcome="ok")
        if wire.wants_tensor_response(req.headers):
            return self._binary_prediction_response(predictions)
        return json_response({"predictions": predictions.tolist()})

    def _binary_instances(self, req: Request, name: str):
        """Decode a tensor-framed request body. The returned array is a
        read-only view over the request bytes — downstream (batching
        concat, device put) copies, nothing mutates in place."""
        try:
            arr = wire.decode_tensor(req.body)
        except wire.WireFormatError as e:
            self.request_count.inc(model=name, outcome="invalid")
            raise HttpError(400, f"bad tensor frame: {e}") from None
        if arr.ndim < 1 or arr.shape[0] < 1:
            self.request_count.inc(model=name, outcome="invalid")
            raise HttpError(
                400, "tensor batch needs a non-empty leading dimension"
            )
        return arr

    @staticmethod
    def _binary_prediction_response(predictions) -> Response:
        return Response(
            body=wire.encode_tensor(predictions),
            content_type=wire.TENSOR_CONTENT_TYPE,
        )

    def _retry_after(self) -> str:
        """One flush window (floored at 1s — the queue clears at flush
        cadence), jittered ±50% from the seeded RNG so shed clients do
        not return as one synchronized wave."""
        timeout_ms = getattr(self._batching, "timeout_ms", 0.0) or 0.0
        base = float(max(1, -(-int(timeout_ms) // 1000)))
        return _format_retry_after(
            base * (0.5 + self._retry_rng.random())
        )

    def _predictor(self, model):
        """model.predict, or its batching queue when batching is on.

        The REPOSITORY is the authority on which servable object is
        current for (name, version) — a requester racing a reload may
        hold the pre-reload object, and keying the replace decision on it
        would let two generations ping-pong, each closing the other's
        queue. The stale requester is simply served by the current
        generation's queue (correct post-rollout behavior). Queues for
        unloaded versions are pruned here (close drained off the request
        path)."""
        if self._batching is None:
            return model.predict
        try:
            current = self.repository.get(model.name, model.version)
        except HttpError:
            # Unloaded between route lookup and here; serve the caller's
            # object directly, unbatched — last request out the door.
            return model.predict
        key = (model.name, model.version)
        stale = []
        with self._batcher_lock:
            queue = self._batchers.get(key)
            if queue is None or queue.servable is not current:
                if queue is not None:
                    stale.append(queue)
                queue = self._batchers[key] = BatchingQueue(
                    current, self._batching, metrics=self._metrics_registry
                )
            # Prune queues whose model/version is no longer served —
            # every unloaded rollout generation would otherwise pin its
            # weights and scheduler thread until process exit.
            for other_key in list(self._batchers):
                try:
                    live = self.repository.get(*other_key)
                except HttpError:
                    live = None
                if live is not self._batchers[other_key].servable:
                    if other_key != key:
                        stale.append(self._batchers.pop(other_key))
        for old in stale:
            # Drain replaced queues off the request path — close() joins
            # the scheduler through the remaining device work.
            threading.Thread(
                target=old.close, name="batcher-drain", daemon=True
            ).start()
        return queue.predict

    def close_batchers(self) -> None:
        """Drain and stop every batching queue (server shutdown)."""
        with self._batcher_lock:
            queues = list(self._batchers.values())
            self._batchers.clear()
        for queue in queues:
            queue.close()

    def metrics_text(self, req: Request) -> Response:
        return Response(
            body=self._metrics_registry.expose_text().encode(),
            content_type="text/plain; version=0.0.4",
        )


class FrontDoorApp(App):
    """The multi-model front door: one HTTP surface over the drain-aware
    `Router` for a whole (possibly multiplexed) fleet.

    Same routes and negotiation as `ModelServerApp` — ``/v1/models/<m>``
    stops being decorative: the path segment selects the servable on
    every replica, priority class and tenant ride the
    ``X-KFTPU-Priority`` / ``X-KFTPU-Tenant`` headers, and the router's
    verdicts map onto honest status codes:

    - `Overloaded` (capacity, priority headroom, or tenant quota) →
      429 with the router's already-jittered ``retry_after`` as a
      fractional-seconds Retry-After;
    - `NoReadyReplicas` / a dead fleet mid-request → 503;
    - `ModelNotFound` → 404 (every replica carries the same catalog);
    - an unknown priority class → 400 (client error, not a shed).
    """

    def __init__(
        self,
        router: Router,
        *,
        metrics: MetricsRegistry | None = None,
    ):
        super().__init__("serving-front-door")
        self.router = router
        metrics = metrics or MetricsRegistry()
        self._metrics_registry = metrics
        self.request_count = metrics.counter(
            "serving_front_door_requests_total",
            "front-door predict requests",
            ("model", "outcome"),
        )
        self.add_route("/v1/models/<name>", self.model_get)
        self.add_route("/v1/models/<name>", self.model_post, ("POST",))
        self.add_route("/v1/models", self.models_list)
        self.add_route("/metrics", self.metrics_text)

    # -- catalog views (aggregated across the fleet) -----------------------

    def _catalog(self) -> dict:
        """model → per-replica state rows, from the router's aggregated
        stats (MultiModelReplica exposes its registry snapshot there)."""
        catalog: dict[str, dict[str, dict]] = {}
        for rname, row in self.router.stats()["replicas"].items():
            for model, mrow in (row.get("models") or {}).items():
                catalog.setdefault(model, {})[rname] = mrow
        return catalog

    def models_list(self, req: Request) -> Response:
        return json_response({"models": sorted(self._catalog())})

    def model_get(self, req: Request) -> Response:
        name, verb = ModelServerApp._split_verb(req.path_params["name"])
        if verb is not None:
            raise HttpError(405, "verbs require POST")
        rows = self._catalog().get(name)
        if rows is None:
            raise HttpError(404, f"model {name!r} not found")
        resident = sum(
            1 for r in rows.values() if r.get("state") == "resident"
        )
        return json_response(
            {
                "model_version_status": [
                    {
                        "version": str(
                            max(r.get("version", 0) for r in rows.values())
                        ),
                        "state": "AVAILABLE",
                        "status": {"error_code": "OK", "error_message": ""},
                    }
                ],
                "replicas": {
                    rname: {
                        "state": r.get("state", "resident"),
                        "version": r.get("version", 0),
                    }
                    for rname, r in rows.items()
                },
                "resident_replicas": resident,
            }
        )

    # -- predict -----------------------------------------------------------

    def model_post(self, req: Request) -> Response:
        name, verb = ModelServerApp._split_verb(req.path_params["name"])
        if verb != "predict":
            raise HttpError(400, f"unsupported verb {verb!r}")
        if wire.is_tensor_request(req.headers):
            try:
                instances = wire.decode_tensor(req.body)
            except wire.WireFormatError as e:
                self.request_count.inc(model=name, outcome="invalid")
                raise HttpError(400, f"bad tensor frame: {e}") from None
            if instances.ndim < 1 or instances.shape[0] < 1:
                self.request_count.inc(model=name, outcome="invalid")
                raise HttpError(
                    400, "tensor batch needs a non-empty leading dimension"
                )
        else:
            body = req.json()
            instances = body.get("instances")
            if not isinstance(instances, list) or not instances:
                self.request_count.inc(model=name, outcome="invalid")
                raise HttpError(
                    400, "body must have a non-empty 'instances' list"
                )
        # No header → None → the router applies the model's
        # catalog-declared default class before falling back to
        # "standard".
        priority = req.headers.get(PRIORITY_HEADER) or None
        tenant = req.headers.get(TENANT_HEADER) or None
        try:
            predictions = self.router.predict(
                instances, model=name, priority=priority, tenant=tenant
            )
        except Overloaded as e:
            # Honest shed: never acked by the router, surfaced as 429
            # with the (already jittered) backoff hint.
            self.request_count.inc(model=name, outcome="overload")
            raise HttpError(
                429,
                str(e),
                headers=[
                    ("Retry-After", _format_retry_after(e.retry_after))
                ],
            ) from None
        except (NoReadyReplicas, ReplicaGone) as e:
            # No fleet left (or it died out from under an acked request
            # after the retry budget) — unavailable, retryable.
            self.request_count.inc(model=name, outcome="unavailable")
            raise HttpError(503, str(e)) from None
        except ModelNotFound:
            self.request_count.inc(model=name, outcome="invalid")
            raise HttpError(404, f"model {name!r} not found") from None
        except ValueError as e:
            # Unknown priority class, ragged instances — client errors.
            self.request_count.inc(model=name, outcome="invalid")
            raise HttpError(400, str(e)) from None
        self.request_count.inc(model=name, outcome="ok")
        if wire.wants_tensor_response(req.headers):
            return Response(
                body=wire.encode_tensor(predictions),
                content_type=wire.TENSOR_CONTENT_TYPE,
            )
        return json_response({"predictions": predictions.tolist()})

    def metrics_text(self, req: Request) -> Response:
        return Response(
            body=self._metrics_registry.expose_text().encode(),
            content_type="text/plain; version=0.0.4",
        )
