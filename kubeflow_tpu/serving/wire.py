"""Binary tensor wire protocol for the serving data plane (ISSUE 15).

The JSON predict surface costs every tensor TWO text round-trips per
hop: ``tolist()`` materializes one Python object per element,
``json.dumps`` renders ~19 bytes of decimal text per float32, and the
server pays the mirror image (``json.loads`` → ``np.asarray``). For the
request hot path that is pure platform overhead — TF-Serving's answer
is a binary RPC surface (gRPC `TensorProto`, arXiv:1605.08695); ours is
an npy-style frame negotiated over the SAME REST routes:

    KFT1 <u32 header-len> <header ascii> <raw little-endian bytes>

where the header is ``<dtype.str>:<dim0,dim1,...>`` (e.g.
``<f4:32,32,3``). Decoding is ``np.frombuffer`` + ``reshape`` — zero
text, zero per-element Python objects, one allocation. Negotiation is
plain HTTP content negotiation on ``/v1/models/<m>:predict``:

- request: ``Content-Type: application/x-kftpu-tensor`` carries a
  tensor frame instead of ``{"instances": ...}`` JSON;
- response: a client that sends ``Accept: application/x-kftpu-tensor``
  gets the predictions back as a frame; everyone else gets the
  byte-identical JSON envelope TF-Serving parity clients expect
  (`testing/test_tf_serving.py`). JSON is the fallback whenever
  negotiation fails — an old server 4xx's the frame and the client
  (`serving/replica.HttpReplica`) drops to JSON for that replica.

The functions here are lint-pinned by the `serving-batch` program
contract: the binary path must never grow a ``tolist()`` or a
per-element JSON encode (docs/serving.md §wire protocol).
"""

from __future__ import annotations

import math
import struct

import numpy as np

# The negotiated media type. Content-Type on requests, Accept +
# Content-Type on responses.
TENSOR_CONTENT_TYPE = "application/x-kftpu-tensor"

_MAGIC = b"KFT1"
_LEN = struct.Struct("<I")
# A header is "<dtype.str>:<comma-dims>"; anything bigger than this is
# a corrupt frame, not a real tensor header.
_MAX_HEADER = 4096

# Numeric tensor kinds only: bool, (un)signed int, float, complex.
# Strings ('U'/'S'), void/records ('V'), datetimes ('M'/'m') and object
# arrays never cross this wire — a servable can't batch them, and
# several of them smuggle pickle-adjacent decode paths.
_ALLOWED_KINDS = frozenset("biufc")


class WireFormatError(ValueError):
    """The frame is not a valid tensor (bad magic, truncated payload,
    malformed header). The HTTP boundary maps this to 400."""


def encode_tensor(arr) -> bytes:
    """Frame an array: magic, header length, ``dtype|shape`` header,
    then the raw little-endian bytes. One buffer copy (``tobytes``),
    no per-element work."""
    arr = np.asarray(arr)
    if arr.dtype.hasobject:
        raise WireFormatError("object arrays cannot cross the wire")
    if arr.dtype.kind not in _ALLOWED_KINDS:
        raise WireFormatError(
            f"dtype kind {arr.dtype.kind!r} ({arr.dtype.str}) is not a "
            f"wire tensor type"
        )
    if arr.dtype.byteorder == ">":
        arr = arr.astype(arr.dtype.newbyteorder("<"))
    # Shape BEFORE ascontiguousarray: it promotes 0-d scalars to 1-d.
    shape = arr.shape
    arr = np.ascontiguousarray(arr)
    # ":" separator — "|" appears in single-byte dtype strs ("|i1").
    header = (
        f"{arr.dtype.str}:{','.join(str(d) for d in shape)}"
    ).encode("ascii")
    return b"".join(
        (_MAGIC, _LEN.pack(len(header)), header, arr.tobytes())
    )


def decode_tensor(data: bytes) -> np.ndarray:
    """Decode a frame produced by `encode_tensor` via ``np.frombuffer``
    (the returned array is a read-only view over ``data`` — callers
    that mutate must copy). Raises `WireFormatError` on anything that
    is not an intact frame."""
    if len(data) < len(_MAGIC) + _LEN.size or not data.startswith(_MAGIC):
        raise WireFormatError("not a kftpu tensor frame (bad magic)")
    (header_len,) = _LEN.unpack_from(data, len(_MAGIC))
    if header_len > _MAX_HEADER:
        raise WireFormatError(f"tensor header too large ({header_len})")
    body_off = len(_MAGIC) + _LEN.size + header_len
    if len(data) < body_off:
        raise WireFormatError("truncated tensor header")
    header = data[len(_MAGIC) + _LEN.size:body_off]
    try:
        dtype_str, _, dims = header.decode("ascii").partition(":")
        dtype = np.dtype(dtype_str)
        shape = tuple(int(d) for d in dims.split(",")) if dims else ()
    except (UnicodeDecodeError, TypeError, ValueError) as e:
        raise WireFormatError(f"malformed tensor header: {e}") from e
    # Decode guards (ISSUE 17 satellite): every malformed header must be
    # a WireFormatError here — a raw ValueError out of reshape would
    # escape the server's 400 mapping and 500 the WSGI handler.
    if dtype.hasobject:
        raise WireFormatError("object dtype refused")
    if dtype.kind not in _ALLOWED_KINDS:
        raise WireFormatError(
            f"dtype kind {dtype.kind!r} ({dtype_str}) is not a wire "
            f"tensor type"
        )
    if any(d < 0 for d in shape):
        # reshape treats -1 as "infer this dim" — from the wire that is
        # attacker-controlled reshaping, not a tensor.
        raise WireFormatError(f"negative dimension in header: {shape}")
    # Arbitrary-precision product: np.prod over int64 silently WRAPS on
    # a crafted huge-dims header, which can collide with the payload
    # length and push a bogus shape into reshape.
    expected = dtype.itemsize * math.prod(shape)
    payload = memoryview(data)[body_off:]
    if len(payload) != expected:
        raise WireFormatError(
            f"tensor payload is {len(payload)} bytes, header claims "
            f"{expected} ({dtype_str}, shape {shape})"
        )
    return np.frombuffer(payload, dtype=dtype).reshape(shape)


def wants_tensor_response(headers: dict) -> bool:
    """Response-side negotiation from (lowercased) request headers: an
    explicit ``Accept: application/x-kftpu-tensor`` wins, an explicit
    JSON Accept loses, and absent any Accept a tensor REQUEST implies a
    tensor response (a binary client that forgot the Accept header must
    not silently pay the JSON decode on the reply leg)."""
    accept = headers.get("accept", "")
    if TENSOR_CONTENT_TYPE in accept:
        return True
    if "application/json" in accept:
        return False
    return is_tensor_request(headers)


def is_tensor_request(headers: dict) -> bool:
    content_type = headers.get("content-type", "")
    return content_type.split(";")[0].strip() == TENSOR_CONTENT_TYPE
