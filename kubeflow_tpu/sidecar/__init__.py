"""Worker sidecar for gang-scheduled TPU jobs.

The openmpi-controller analog (SURVEY.md §2 #18): a per-worker sidecar
that sequences the main container against the rest of the gang.
"""

from kubeflow_tpu.sidecar.controller import (
    SIGCONT_FILE,
    SIGTERM_FILE,
    SidecarController,
)

__all__ = ["SIGCONT_FILE", "SIGTERM_FILE", "SidecarController"]
