"""Sidecar CLI (`openmpi-controller/controller/main.py:7-29` analog).

    python -m kubeflow_tpu.sidecar \
        --workdir /kubeflow-tpu/data --job myjob --namespace team \
        [--coordinator host:port] [--results /out --artifacts /store]

Main-container entrypoints block on the SIGCONT file in --workdir before
starting, and exit when SIGTERM appears — identical contract to the
reference's shared-volume signal files.
"""

from __future__ import annotations

import argparse
import logging
import sys

from kubeflow_tpu.sidecar.controller import (
    SidecarController,
    default_device_probe,
    local_dir_uploader,
)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="kubeflow-tpu-sidecar")
    parser.add_argument("--workdir", required=True)
    parser.add_argument("--job", required=True)
    parser.add_argument("--namespace", default="default")
    parser.add_argument("--coordinator", default=None)
    parser.add_argument(
        "--apiserver",
        default=None,
        help="API server base URL — or comma-separated HA endpoint "
        "list — to watch the TpuJob phase (e.g. http://apiserver:8001)",
    )
    parser.add_argument("--results", default=None)
    parser.add_argument("--artifacts", default=None)
    parser.add_argument("--poll-seconds", type=float, default=10.0)
    parser.add_argument("--timeout-seconds", type=float, default=600.0)
    parser.add_argument(
        "--skip-device-probe",
        action="store_true",
        help="don't wait for the TPU runtime (CPU smoke tests)",
    )
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    api = None
    if args.apiserver:
        from kubeflow_tpu.testing.apiserver_http import (
            HttpApiClient,
            endpoints_from_env,
        )

        api = HttpApiClient(endpoints_from_env(args.apiserver))

    controller = SidecarController(
        workdir=args.workdir,
        job_name=args.job,
        namespace=args.namespace,
        api=api,
        coordinator=args.coordinator,
        device_probe=(
            (lambda: True) if args.skip_device_probe else default_device_probe
        ),
        upload=local_dir_uploader(args.artifacts) if args.artifacts else None,
        poll_seconds=args.poll_seconds,
        timeout_seconds=args.timeout_seconds,
    )
    phase = controller.run(results_dir=args.results)
    print(f"sidecar: job {args.job} terminal phase: {phase}")
    return 0 if phase == "Succeeded" else 1


if __name__ == "__main__":
    sys.exit(main())
