"""Gang-worker sidecar — the openmpi-controller analog.

Parity with `components/openmpi-controller/controller/controller.py:17-118`
(SURVEY.md §2 #18, §3.3 OpenMPI variant), re-aimed at JAX multislice:

- **start gate** (`wait_ready` :53-57): the reference gated workers on
  the mpirun driver's readiness plus S3 data download, then wrote a
  `SIGCONT` file the main container's entrypoint blocks on. Here the
  gate is: the jax.distributed *coordinator* is TCP-reachable (the
  TPU-native replacement for "driver is up" — coordinator bootstrap
  ordering is the multislice hard part, SURVEY.md §7.3) and the input
  dataset is staged;
- **termination watch** (`wait_done` :59-103): poll the master/gang
  status via the API server every `poll_seconds` (util.py:24-34 polls
  pod phase every 10s); when the job reaches a terminal phase, write
  `SIGTERM` so the worker exits even if its own process hangs — a hung
  all-reduce holds the whole slice otherwise;
- **artifact upload** (:110-118): stage the results directory out to the
  artifact store (S3 in the reference; pluggable callable here);
- the reference's `wait for nvidia driver` becomes `wait_device_ready`:
  poll until the TPU runtime reports chips.

Everything injectable so the sequencing logic is testable without pods —
the reference never achieved that (SURVEY.md §4.3).
"""

from __future__ import annotations

import logging
import pathlib
import shutil
import socket
import time
from typing import Callable

from kubeflow_tpu.testing.fake_apiserver import FakeApiServer, NotFound

log = logging.getLogger(__name__)

# Signal files on the volume shared with the main container
# (`controller.py:10-14` constants).
SIGCONT_FILE = "SIGCONT"
SIGTERM_FILE = "SIGTERM"

TERMINAL_PHASES = ("Succeeded", "Failed")


def parse_hostport(address: str) -> tuple[str, int]:
    host, sep, port = address.rpartition(":")
    if not sep or not port.isdigit():
        raise ValueError(f"coordinator address {address!r} must be host:port")
    return host, int(port)


def coordinator_reachable(address: str, timeout: float = 1.0) -> bool:
    """Is the jax.distributed coordinator accepting connections?"""
    host, port = parse_hostport(address)
    try:
        with socket.create_connection((host, port), timeout=timeout):
            return True
    except OSError:
        return False


def default_device_probe() -> bool:
    """TPU runtime ready? (the `wait for nvidia driver` analog)."""
    try:
        import jax

        return len(jax.devices()) > 0
    except Exception:
        return False


class SidecarController:
    def __init__(
        self,
        *,
        workdir: str | pathlib.Path,
        job_name: str,
        namespace: str = "default",
        # Anything with the FakeApiServer get() surface works — the
        # in-process store or an HttpApiClient pointed at its facade.
        api: FakeApiServer | None = None,
        coordinator: str | None = None,
        coordinator_probe: Callable[[], bool] | None = None,
        device_probe: Callable[[], bool] | None = None,
        download: Callable[[], None] | None = None,
        upload: Callable[[pathlib.Path], None] | None = None,
        poll_seconds: float = 10.0,
        timeout_seconds: float = 600.0,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.workdir = pathlib.Path(workdir)
        self.job_name = job_name
        self.namespace = namespace
        self.api = api
        if coordinator_probe is not None:
            self.coordinator_probe = coordinator_probe
        elif coordinator:
            parse_hostport(coordinator)  # fail fast on a malformed flag
            self.coordinator_probe = lambda: coordinator_reachable(coordinator)
        else:
            self.coordinator_probe = lambda: True
        self.device_probe = device_probe or (lambda: True)
        self.download = download
        self.upload = upload
        self.poll_seconds = poll_seconds
        self.timeout_seconds = timeout_seconds
        self.clock = clock
        self.sleep = sleep

    # -- signal files ------------------------------------------------------

    def _signal(self, name: str) -> None:
        self.workdir.mkdir(parents=True, exist_ok=True)
        (self.workdir / name).touch()
        log.info("sidecar: wrote %s", name)

    def has_signal(self, name: str) -> bool:
        return (self.workdir / name).exists()

    # -- phases ------------------------------------------------------------

    def _wait_for(self, what: str, probe: Callable[[], bool]) -> None:
        deadline = self.clock() + self.timeout_seconds
        while not probe():
            if self.clock() >= deadline:
                raise TimeoutError(f"sidecar: timed out waiting for {what}")
            log.info("sidecar: waiting for %s", what)
            self.sleep(self.poll_seconds)

    def wait_ready(self) -> None:
        """Gate the worker: device up, coordinator up, data staged —
        then SIGCONT (`controller.py:53-57`)."""
        self._wait_for("tpu runtime", self.device_probe)
        self._wait_for("coordinator", self.coordinator_probe)
        if self.download is not None:
            self.download()
        self._signal(SIGCONT_FILE)

    def job_phase(self) -> str | None:
        if self.api is None:
            return None
        try:
            job = self.api.get("TpuJob", self.job_name, self.namespace)
        except NotFound:
            # Master object gone ⇒ treat as terminated (the reference
            # treats a vanished master pod as done, `controller.py:95-99`).
            return "Failed"
        except Exception as e:
            # Transient apiserver trouble (connection refused, 5xx during
            # a restart) must not kill the watch — a dead sidecar never
            # writes SIGTERM and the main container hangs forever. Treat
            # as "phase unknown"; the wait_done deadline still bounds us.
            log.warning("sidecar: job poll failed (%s); will retry", e)
            return None
        return job.status.get("phase")

    def wait_done(self) -> str:
        """Poll the gang's job object until terminal, then SIGTERM
        (`controller.py:77-103`). Returns the terminal phase."""
        deadline = self.clock() + self.timeout_seconds
        while True:
            phase = self.job_phase()
            if phase in TERMINAL_PHASES:
                break
            if self.clock() >= deadline:
                phase = "Failed"
                log.warning("sidecar: job watch timed out; forcing SIGTERM")
                break
            self.sleep(self.poll_seconds)
        self._signal(SIGTERM_FILE)
        return phase or "Failed"

    def upload_results(self, results_dir: str | pathlib.Path) -> None:
        """Ship artifacts out (`controller.py:110-118` S3 upload)."""
        if self.upload is not None:
            self.upload(pathlib.Path(results_dir))

    def run(self, results_dir: str | pathlib.Path | None = None) -> str:
        """Full sidecar lifecycle: gate → watch → signal → upload.

        With no API client the sidecar degenerates to a start gate only
        (no job watch is possible) and reports "Unknown"."""
        self.wait_ready()
        if self.api is None:
            log.warning("sidecar: no apiserver; start-gate only mode")
            phase = "Unknown"
        else:
            phase = self.wait_done()
        if results_dir is not None:
            self.upload_results(results_dir)
        return phase


def local_dir_uploader(dest: str | pathlib.Path) -> Callable[[pathlib.Path], None]:
    """Artifact store backed by a directory (the zero-egress stand-in for
    the reference's `aws s3 cp --recursive`)."""

    def upload(src: pathlib.Path) -> None:
        dest_path = pathlib.Path(dest)
        dest_path.mkdir(parents=True, exist_ok=True)
        if src.is_dir():
            shutil.copytree(src, dest_path, dirs_exist_ok=True)
        elif src.exists():
            shutil.copy2(src, dest_path / src.name)

    return upload
