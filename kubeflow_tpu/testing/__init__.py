"""Test infrastructure: the in-process fake API server and fixtures.

The reference had no in-process cluster simulacrum beyond envtest
(SURVEY.md §4.3) and tested everything against real GKE. This package is
the fixture it lacked: controllers and web backends run against
`FakeApiServer` with real optimistic-concurrency, finalizer, and
owner-reference semantics — deterministic, no cluster.
"""

from kubeflow_tpu.testing.fake_apiserver import (
    AlreadyExists,
    Conflict,
    FakeApiServer,
    NotFound,
)
