"""HTTP facade + client for the in-process API server.

The reference's sidecars and tests talk to the real K8s apiserver over
HTTP (`openmpi-controller/controller/util.py` uses the kubernetes client;
`testing/deploy_utils.py:31-71`). Our control plane stores resources in
`FakeApiServer`; this module serves that store over REST so *separate
processes* (sidecar CLI, e2e workers, probers) get the same boundary:

    GET    /apis/<kind>                      ?namespace=&labelSelector=k=v&version=
    GET    /apis/<kind>/<ns>/<name>          ('_' namespace = cluster scope; ?version=)
    POST   /apis/<kind>
    PUT    /apis/<kind>/<ns>/<name>[/status]
    DELETE /apis/<kind>/<ns>/<name>

Multi-version kinds: POST/PUT bodies may carry any served apiVersion
(storage normalizes to the hub version); GETs pass `?version=` to read at
a specific served version.

`HttpApiClient` mirrors the FakeApiServer method surface (get/list/create/
update/update_status/delete) so controller-side code is client-agnostic.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request

from kubeflow_tpu.api.objects import Resource
from kubeflow_tpu.utils import tracing
from kubeflow_tpu.testing.fake_apiserver import (
    AlreadyExists,
    Conflict,
    FakeApiServer,
    Invalid,
    NotFound,
)
from kubeflow_tpu.web.wsgi import App, HttpError, Request, Response, json_response


def _ns_seg(namespace: str) -> str:
    return namespace or "_"


def _seg_ns(seg: str) -> str:
    return "" if seg == "_" else seg


class ApiServerApp(App):
    """REST facade. Unauthenticated — this is the in-cluster trust domain
    (the reference controllers talk to the apiserver with pod
    serviceaccounts; web-tier authn/authz stays in the web apps)."""

    def __init__(self, api: FakeApiServer):
        super().__init__("apiserver")
        self.api = api
        self.add_route("/apis/<kind>", self.list_kind)
        self.add_route("/apis/<kind>", self.create, ("POST",))
        self.add_route("/apis/<kind>/<ns>/<name>", self.get)
        self.add_route("/apis/<kind>/<ns>/<name>", self.update, ("PUT",))
        self.add_route("/apis/<kind>/<ns>/<name>", self.delete, ("DELETE",))
        self.add_route(
            "/apis/<kind>/<ns>/<name>/status", self.update_status, ("PUT",)
        )
        # In-process trace collector drain (the platform's jaeger-query
        # stand-in): returns and clears all finished spans.
        self.add_route("/debug/traces", self.drain_traces)

    def drain_traces(self, req: Request) -> Response:
        from kubeflow_tpu.utils import tracing

        return json_response(
            {
                "spans": tracing.tracer.export(),
                "dropped": tracing.tracer.dropped,
            }
        )

    def list_kind(self, req: Request) -> Response:
        selector = None
        if "labelSelector" in req.query:
            selector = dict(
                part.split("=", 1)
                for part in req.query["labelSelector"].split(",")
                if "=" in part
            )
        namespace = req.query.get("namespace")
        items = self.api.list(
            req.path_params["kind"],
            namespace=_seg_ns(namespace) if namespace is not None else None,
            label_selector=selector,
        )
        items = [self._at_version(r, req) for r in items]
        return json_response({"items": [r.to_dict() for r in items]})

    def _at_version(self, obj: Resource, req: Request) -> Resource:
        version = req.query.get("version")
        if not version:
            return obj
        # Invalid propagates: wsgi maps it to 422 and HttpApiClient maps
        # 422 back to Invalid, so both clients surface the same error.
        return self.api.convert_to(obj, version)

    def get(self, req: Request) -> Response:
        obj = self.api.get(
            req.path_params["kind"],
            req.path_params["name"],
            _seg_ns(req.path_params["ns"]),
        )
        return json_response(self._at_version(obj, req).to_dict())

    def create(self, req: Request) -> Response:
        obj = Resource.from_dict(req.json())
        if obj.kind != req.path_params["kind"]:
            raise HttpError(400, "kind mismatch between path and body")
        return json_response(self.api.create(obj).to_dict(), status=201)

    def _body_matching_path(self, req: Request) -> Resource:
        """The path is authoritative: a body naming a different object than
        the REST path is a client bug, not a write to the named object."""
        obj = Resource.from_dict(req.json())
        if (
            obj.kind != req.path_params["kind"]
            or obj.metadata.name != req.path_params["name"]
            or (obj.metadata.namespace or "") != (_seg_ns(req.path_params["ns"]) or "")
        ):
            raise HttpError(400, "kind/namespace/name mismatch between path and body")
        return obj

    def update(self, req: Request) -> Response:
        return json_response(
            self.api.update(self._body_matching_path(req)).to_dict()
        )

    def update_status(self, req: Request) -> Response:
        return json_response(
            self.api.update_status(self._body_matching_path(req)).to_dict()
        )

    def delete(self, req: Request) -> Response:
        self.api.delete(
            req.path_params["kind"],
            req.path_params["name"],
            _seg_ns(req.path_params["ns"]),
        )
        return json_response({"deleted": True})


class HttpApiClient:
    """Remote twin of FakeApiServer's CRUD surface."""

    def __init__(self, base_url: str, timeout: float = 10.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    def _call(self, method: str, path: str, body: dict | None = None) -> dict:
        req = urllib.request.Request(
            self.base_url + path,
            method=method,
            data=json.dumps(body).encode() if body is not None else None,
            # An active span's trace id rides along, so a reconcile's
            # apiserver calls land in the same trace (`utils.tracing`).
            headers={
                "Content-Type": "application/json",
                **tracing.trace_header(),
            },
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read())
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")
            if e.code == 404:
                raise NotFound(detail)
            if e.code == 409:
                # The server folds AlreadyExists and Conflict onto 409;
                # disambiguate from the message.
                if "already exists" in detail:
                    raise AlreadyExists(detail)
                raise Conflict(detail)
            if e.code == 422:
                raise Invalid(detail)
            raise

    def get(
        self,
        kind: str,
        name: str,
        namespace: str = "default",
        version: str | None = None,
    ) -> Resource:
        query = f"?{urllib.parse.urlencode({'version': version})}" if version else ""
        return Resource.from_dict(
            self._call("GET", f"/apis/{kind}/{_ns_seg(namespace)}/{name}{query}")
        )

    def list(
        self,
        kind: str,
        namespace: str | None = None,
        label_selector: dict[str, str] | None = None,
        version: str | None = None,
    ) -> list[Resource]:
        params = {}
        if version:
            params["version"] = version
        if namespace is not None:
            params["namespace"] = _ns_seg(namespace)
        if label_selector:
            params["labelSelector"] = ",".join(
                f"{k}={v}" for k, v in label_selector.items()
            )
        query = f"?{urllib.parse.urlencode(params)}" if params else ""
        data = self._call("GET", f"/apis/{kind}{query}")
        return [Resource.from_dict(d) for d in data["items"]]

    def create(self, obj: Resource) -> Resource:
        return Resource.from_dict(
            self._call("POST", f"/apis/{obj.kind}", obj.to_dict())
        )

    def update(self, obj: Resource) -> Resource:
        return Resource.from_dict(
            self._call(
                "PUT",
                f"/apis/{obj.kind}/{_ns_seg(obj.metadata.namespace)}/"
                f"{obj.metadata.name}",
                obj.to_dict(),
            )
        )

    def update_status(self, obj: Resource) -> Resource:
        return Resource.from_dict(
            self._call(
                "PUT",
                f"/apis/{obj.kind}/{_ns_seg(obj.metadata.namespace)}/"
                f"{obj.metadata.name}/status",
                obj.to_dict(),
            )
        )

    def delete(self, kind: str, name: str, namespace: str = "default") -> None:
        self._call("DELETE", f"/apis/{kind}/{_ns_seg(namespace)}/{name}")
