"""HTTP facade + client for the in-process API server.

The reference's sidecars and tests talk to the real K8s apiserver over
HTTP (`openmpi-controller/controller/util.py` uses the kubernetes client;
`testing/deploy_utils.py:31-71`). Our control plane stores resources in
`FakeApiServer`; this module serves that store over REST so *separate
processes* (sidecar CLI, e2e workers, probers) get the same boundary:

    GET    /apis/<kind>                      ?namespace=&labelSelector=k=v&version=
    GET    /apis/<kind>?watch=true           &resourceVersion=N&timeoutSeconds=S
    GET    /apis/<kind>/<ns>/<name>          ('_' namespace = cluster scope; ?version=)
    POST   /apis/<kind>                      (?apply=true → create-or-update)
    PUT    /apis/<kind>/<ns>/<name>[/status]
    DELETE /apis/<kind>/<ns>/<name>

Multi-version kinds: POST/PUT bodies may carry any served apiVersion
(storage normalizes to the hub version); GETs pass `?version=` to read at
a specific served version.

Watch semantics match the real apiserver's (the reference's controllers
are watch-driven across process boundaries — controller-runtime's
`SetupWithManager`, `notebook_controller.go:516`): a long-poll returns
events with rv > resourceVersion plus the rv to resume from; a bookmark
older than the journal horizon gets 410 Gone, and the client recovers the
way an informer does (re-list, deliver synthetic events, re-watch).

`HttpApiClient` mirrors the FakeApiServer method surface (get/list/create/
update/update_status/delete/apply/record_event/watch) so controller-side
code — including `controllers/runtime.Controller` — is client-agnostic:
the same reconciler binary runs in-process against the store or in a
separate process against this facade.
"""

from __future__ import annotations

import collections
import json
import urllib.parse

import logging
import random
import threading
import time

import os

from kubeflow_tpu.api.objects import Resource
from kubeflow_tpu.api.rbac import resource_for_kind, subject_access_review
from kubeflow_tpu.api.tokens import TokenRegistry
from kubeflow_tpu.utils import tracing
from kubeflow_tpu.testing.fake_apiserver import (
    AlreadyExists,
    ApiError,
    Conflict,
    FakeApiServer,
    Forbidden,
    Gone,
    Invalid,
    NotFound,
    Unavailable,
    WatchHandler,
)

log = logging.getLogger(__name__)
from kubeflow_tpu.web.wsgi import (
    App,
    HttpError,
    Request,
    Response,
    StreamResponse,
    json_response,
)


def _ns_seg(namespace: str) -> str:
    return namespace or "_"


def _seg_ns(seg: str) -> str:
    return "" if seg == "_" else seg


class WatchCache:
    """Shared watch cache: each journal event is serialized to its
    compact-JSON wire form EXACTLY ONCE, and the cached bytes fan out
    to every consumer — streaming connections write the cached line,
    long-poll responses are assembled from the cached fragments. This
    is the apiserver watch-cache property (serialize once, no matter
    how many watchers), folded onto our transport: 50 watchers of one
    event cost one json.dumps and 50 socket writes (docs/perf.md).

    Keyed by (rv, type): both stores stamp every journal event with a
    fresh rv, so the key is unique per event; DELETED events carry
    their own fresh rv by construction. Bounded FIFO — rv is monotonic,
    so eviction order is age order. Thread-safe; a rare concurrent miss
    serializes twice, which only costs the duplicate work."""

    def __init__(self, size: int = 4096):
        self._entries: collections.OrderedDict[tuple[int, str], bytes] = (
            collections.OrderedDict()
        )
        self._size = size
        self._lock = threading.Lock()
        self.serializations = 0  # misses: actual json.dumps calls
        self.hits = 0

    def event_bytes(self, rv: int, etype: str, obj: Resource) -> bytes:
        """Wire form of one watch event, without trailing newline:
        {"type":...,"rv":...,"object":{...}}. The object payload comes
        from the snapshot's own cached wire bytes (`Resource.
        wire_bytes`), so even the one serialization per event is shared
        with get/list responses of the same snapshot."""
        key = (rv, etype)
        with self._lock:
            cached = self._entries.get(key)
            if cached is not None:
                self.hits += 1
                return cached
        data = (
            b'{"type":"' + etype.encode() + b'","rv":' + str(rv).encode()
            + b',"object":' + obj.wire_bytes() + b"}"
        )
        with self._lock:
            self.serializations += 1
            self._entries[key] = data
            while len(self._entries) > self._size:
                self._entries.popitem(last=False)
        return data


class ApiServerApp(App):
    """REST facade.

    With `tokens`, every request must carry `Authorization: Bearer
    <token>` naming a registered identity, and every operation is gated
    by a SubjectAccessReview over the stored RBAC objects — the trust
    model the reference runs under (controllers authenticate with pod
    serviceaccount tokens, `notebook_controller.go:516` manager config;
    web backends SAR every request, `crud_backend/authz.py:46-80`; even
    /metrics sits behind kube-rbac-proxy,
    `notebook-controller/config/default/manager_auth_proxy_patch.yaml`).
    Status is a distinct RBAC subresource (`<resource>/status`), so only
    the owning runtime identity can be granted status writes.

    Without `tokens` the facade is open — the in-process test seam only
    (the kube-apiserver insecure-localhost-port analog); the platform
    launcher and e2e harnesses always pass a registry."""

    def __init__(
        self,
        api: FakeApiServer,
        log_root: str | None = None,
        tokens: TokenRegistry | None = None,
    ):
        super().__init__("apiserver")
        self.api = api
        self.tokens = tokens
        # Shared watch cache: one serialization per journal event across
        # ALL watch connections, streaming and long-poll alike.
        self.watch_cache = WatchCache()
        if tokens is not None:
            self.before_request(self._authenticate)
        # Containment root for /log: only files under the runner's
        # capture dir are served. status is client-writable, so serving
        # status.logPath unconstrained would be an arbitrary-file-read
        # primitive. None disables log serving entirely.
        import pathlib

        self.log_root = (
            pathlib.Path(log_root).resolve() if log_root else None
        )
        self.add_route("/apis/<kind>", self.list_kind)
        self.add_route("/apis/<kind>", self.create, ("POST",))
        self.add_route("/apis/<kind>/<ns>/<name>", self.get)
        self.add_route("/apis/<kind>/<ns>/<name>", self.update, ("PUT",))
        self.add_route("/apis/<kind>/<ns>/<name>", self.delete, ("DELETE",))
        self.add_route(
            "/apis/<kind>/<ns>/<name>/status", self.update_status, ("PUT",)
        )
        # kubelet log-endpoint analog: serves the pod's captured stdout
        # (LocalPodRunner publishes status.logPath). Pod-only.
        self.add_route("/apis/Pod/<ns>/<name>/log", self.pod_log)
        # In-process trace collector drain (the platform's jaeger-query
        # stand-in): returns and clears all finished spans.
        self.add_route("/debug/traces", self.drain_traces)

    # -- authn/authz -------------------------------------------------------

    def _authenticate(self, req: Request) -> Response | None:
        """Before-request hook (secure mode): resolve the bearer token to
        an identity or 401. /healthz stays open for probes."""
        if req.path == "/healthz":
            return None
        header = req.headers.get("authorization", "")
        scheme, _, token = header.partition(" ")
        user = (
            self.tokens.authenticate(token.strip())
            if scheme.lower() == "bearer" and token.strip()
            else None
        )
        if user is None:
            from kubeflow_tpu.web.wsgi import error_response

            return error_response(
                401,
                "no valid bearer token (secure facade: every request "
                "needs 'Authorization: Bearer <token>')",
            )
        req.user = user
        return None

    def _authorize(
        self, req: Request, verb: str, resource: str, namespace: str
    ) -> None:
        """SAR gate for one operation; no-op in open mode. 403 carries the
        crud_backend-style readable denial (`authz.py:46-80`)."""
        if self.tokens is None:
            return
        if not subject_access_review(
            self.api, req.user, verb, resource, namespace
        ):
            scope = (
                f"in namespace {namespace!r}" if namespace else "cluster-wide"
            )
            raise HttpError(
                403,
                f"user {req.user!r} is not allowed to {verb} {resource} "
                f"{scope}",
            )

    def _lease_guard(self, req: Request):
        """Optional write fencing: a leader-elected client arms its
        lease guard and every write carries it in this header; the store
        verifies holder+generation atomically with the commit
        (`fake_apiserver._check_lease_guard`). Correctness fencing
        against deposed leaders, not an authz boundary — RBAC already
        gated the write above."""
        raw = req.headers.get("x-kftpu-lease-guard")
        if not raw:
            return None
        try:
            ns, name, holder, transitions = json.loads(raw)
            return (str(ns), str(name), str(holder), int(transitions))
        except (ValueError, TypeError) as e:
            raise HttpError(
                400, f"malformed X-Kftpu-Lease-Guard header: {e}"
            )

    def _may_watch(self, user: str, obj: Resource, cache: dict) -> bool:
        """Per-event watch filter for the multiplexed `_` stream: deliver
        only objects whose (kind, namespace) the identity may watch, so a
        least-privilege controller can hold one stream without cluster-wide
        read (the apiserver's per-resource watch authorization, folded
        into our single-stream transport)."""
        key = (obj.kind, obj.metadata.namespace or "")
        if key not in cache:
            cache[key] = subject_access_review(
                self.api, user, "watch", resource_for_kind(obj.kind), key[1]
            )
        return cache[key]

    def drain_traces(self, req: Request) -> Response:
        from kubeflow_tpu.utils import tracing

        # Draining is destructive (export clears the buffer): gate it
        # behind the write verb so a view-bound identity can't wipe the
        # shared tracer.
        self._authorize(req, "delete", "traces", "")
        return json_response(
            {
                "spans": tracing.tracer.export(),
                "dropped": tracing.tracer.dropped,
            }
        )

    def list_kind(self, req: Request) -> Response:
        if req.query.get("watch") in ("true", "1"):
            return self._watch(req)
        selector = None
        if "labelSelector" in req.query:
            selector = dict(
                part.split("=", 1)
                for part in req.query["labelSelector"].split(",")
                if "=" in part
            )
        namespace = req.query.get("namespace")
        self._authorize(
            req,
            "list",
            resource_for_kind(req.path_params["kind"]),
            _seg_ns(namespace) if namespace is not None else "",
        )
        # The list's rv is the watch bookmark (informer list-then-watch).
        # Read it BEFORE listing: an object committed between the two
        # reads is then re-delivered by the watch (at-least-once), whereas
        # rv-after-list would place it behind the bookmark and lose it.
        rv = self.api.current_rv
        items = self.api.list(
            req.path_params["kind"],
            namespace=_seg_ns(namespace) if namespace is not None else None,
            label_selector=selector,
        )
        items = [self._at_version(r, req) for r in items]
        # Assembled from each snapshot's cached wire bytes: a list of N
        # objects costs a byte join, not N serializations per request.
        body = (
            b'{"items":[' + b",".join(r.wire_bytes() for r in items)
            + b'],"resourceVersion":' + str(rv).encode() + b"}"
        )
        return Response(body)

    def _watch(self, req: Request) -> Response:
        """Watch transport, two forms.

        Long-poll (default): block until events land past the bookmark
        (or timeoutSeconds), return them with the rv to resume from.
        `_` as the kind watches everything (the client multiplexes one
        stream across all its registered handlers).

        Streaming (`stream=true`): ONE chunked HTTP response held open
        across events — each line is a JSON event, with BOOKMARK lines
        marking quiet progress (heartbeat + rv advance) and an ERROR
        line carrying the would-be HTTP status (410 journal horizon,
        503 fail-stop) before the stream ends. This is the client-go
        informer transport (`notebook_controller.go:516` watches ride
        one shared connection): event latency is delivery latency, not
        poll cadence, and a keep-alive client re-uses the connection's
        single TLS handshake for the whole stream."""
        try:
            since = int(req.query.get("resourceVersion", "0"))
        except ValueError:
            raise HttpError(400, "resourceVersion must be an integer")
        kind = req.path_params["kind"]
        namespace = req.query.get("namespace")
        if kind != "_":
            # Concrete-kind stream: authorize eagerly (403 beats silently
            # delivering nothing). The `_` stream filters per event below.
            self._authorize(
                req,
                "watch",
                resource_for_kind(kind),
                _seg_ns(namespace) if namespace is not None else "",
            )
        if req.query.get("stream") in ("true", "1"):
            return self._watch_stream(req, since, kind, namespace)
        timeout = min(float(req.query.get("timeoutSeconds", "10")), 60.0)
        try:
            events, rv = self.api.wait_events(
                since,
                kind=None if kind == "_" else kind,
                namespace=_seg_ns(namespace) if namespace is not None else None,
                timeout=timeout,
            )
        except Gone as e:
            raise HttpError(410, str(e))
        events = self._filter_watchable(req, kind, events)
        # Assemble the envelope from the cached per-event wire bytes —
        # N long-pollers of one event share a single serialization.
        frags = [
            self.watch_cache.event_bytes(ev_rv, ev, obj)
            for ev_rv, ev, obj in events
        ]
        body = (
            b'{"events":[' + b",".join(frags)
            + b'],"resourceVersion":' + str(rv).encode() + b"}"
        )
        return Response(body)

    def _filter_watchable(self, req: Request, kind: str, events):
        """Per-event SAR filter for the multiplexed `_` stream."""
        if self.tokens is None or kind != "_":
            return events
        cache: dict = {}
        return [
            (ev_rv, ev, obj)
            for ev_rv, ev, obj in events
            if self._may_watch(req.user, obj, cache)
        ]

    # How long one streaming response lives before the server ends it
    # cleanly (the kube-apiserver min-request-timeout analog): bounds a
    # dead client's grip on its thread; a live client just re-opens on
    # its pooled (already-handshaken) connection.
    STREAM_DURATION = 240.0
    # Bookmark cadence: each quiet slice emits a BOOKMARK line, serving
    # as heartbeat (the peer detects a dead server in seconds) and rv
    # advance (a resume after disconnect skips the drained history).
    STREAM_SLICE = 5.0

    def _watch_stream(
        self, req: Request, since: int, kind: str, namespace: str | None
    ) -> StreamResponse:
        from kubeflow_tpu.web.wsgi import encode_json

        duration = min(
            float(req.query.get("timeoutSeconds", self.STREAM_DURATION)),
            3600.0,
        )

        def line(payload: dict) -> bytes:
            return encode_json(payload) + b"\n"

        def gen():
            # Exceptions here happen AFTER App.handle returned (the
            # handler thread is mid-chunked-response), so the error
            # mapping rides the stream as an ERROR line instead of an
            # HTTP status.
            import time as _time

            rv = since
            deadline = _time.monotonic() + duration
            while True:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    return  # clean end; client resumes from its rv
                try:
                    events, new_rv = self.api.wait_events(
                        rv,
                        kind=None if kind == "_" else kind,
                        namespace=(
                            _seg_ns(namespace) if namespace is not None
                            else None
                        ),
                        timeout=min(self.STREAM_SLICE, remaining),
                    )
                except Gone as e:
                    yield line(
                        {"type": "ERROR", "status": 410, "message": str(e)}
                    )
                    return
                except Exception as e:  # Unavailable, shutdown races
                    yield line(
                        {"type": "ERROR", "status": 503, "message": str(e)}
                    )
                    return
                # One chunk per wakeup, not per event: the whole batch
                # (cached wire bytes per event — serialized once across
                # every streaming/long-poll connection) plus its
                # bookmark rides a single framed write, so a burst of
                # W events costs one syscall instead of W+1.
                out = bytearray()
                for ev_rv, ev, obj in self._filter_watchable(
                    req, kind, events
                ):
                    out += self.watch_cache.event_bytes(ev_rv, ev, obj)
                    out += b"\n"
                rv = new_rv
                out += line({"type": "BOOKMARK", "resourceVersion": rv})
                yield bytes(out)

        return StreamResponse(gen(), content_type="application/json")

    def _at_version(self, obj: Resource, req: Request) -> Resource:
        version = req.query.get("version")
        if not version:
            return obj
        # Invalid propagates: wsgi maps it to 422 and HttpApiClient maps
        # 422 back to Invalid, so both clients surface the same error.
        return self.api.convert_to(obj, version)

    def get(self, req: Request) -> Response:
        self._authorize(
            req,
            "get",
            resource_for_kind(req.path_params["kind"]),
            _seg_ns(req.path_params["ns"]),
        )
        obj = self.api.get(
            req.path_params["kind"],
            req.path_params["name"],
            _seg_ns(req.path_params["ns"]),
        )
        return Response(self._at_version(obj, req).wire_bytes())

    def create(self, req: Request) -> Response:
        obj = Resource.from_dict(req.json())
        if obj.kind != req.path_params["kind"]:
            raise HttpError(400, "kind mismatch between path and body")
        resource = resource_for_kind(obj.kind)
        namespace = obj.metadata.namespace or ""
        if self.tokens is not None and obj.status:
            # Status-subresource integrity on create: a body arriving with
            # status would otherwise persist it (the store honors it;
            # update() already doesn't), letting a create-only identity
            # forge e.g. phase=Succeeded. Like the real apiserver we drop
            # it — unless the identity holds the status grant anyway, so
            # runtimes that materialize already-Running objects (the
            # WorkloadMaterializer pattern) keep working remotely.
            if not subject_access_review(
                self.api, req.user, "update", resource + "/status", namespace
            ):
                obj.status = {}
        if req.query.get("apply") in ("true", "1"):
            # Server-side apply is create-or-update: the identity needs
            # both (the reference's SSA patch demands `patch`; our edit
            # role carries create+update+patch together).
            self._authorize(req, "create", resource, namespace)
            self._authorize(req, "update", resource, namespace)
            # Server-side apply: create-or-update with the store's own
            # no-op detection (post-admission, post-conversion compare) so
            # remote reconcilers don't re-trigger their own watches.
            return json_response(
                self.api.apply(
                    obj, lease_guard=self._lease_guard(req)
                ).to_dict()
            )
        self._authorize(req, "create", resource, namespace)
        return json_response(
            self.api.create(
                obj, lease_guard=self._lease_guard(req)
            ).to_dict(),
            status=201,
        )

    def _body_matching_path(self, req: Request) -> Resource:
        """The path is authoritative: a body naming a different object than
        the REST path is a client bug, not a write to the named object."""
        obj = Resource.from_dict(req.json())
        if (
            obj.kind != req.path_params["kind"]
            or obj.metadata.name != req.path_params["name"]
            or (obj.metadata.namespace or "") != (_seg_ns(req.path_params["ns"]) or "")
        ):
            raise HttpError(400, "kind/namespace/name mismatch between path and body")
        return obj

    def update(self, req: Request) -> Response:
        self._authorize(
            req,
            "update",
            resource_for_kind(req.path_params["kind"]),
            _seg_ns(req.path_params["ns"]),
        )
        return json_response(
            self.api.update(
                self._body_matching_path(req),
                lease_guard=self._lease_guard(req),
            ).to_dict()
        )

    def update_status(self, req: Request) -> Response:
        # Distinct subresource: granting `tpujobs` update does NOT grant
        # `tpujobs/status` — only the owning runtime identity's role
        # carries the status rule (the reference's controllers get
        # `.../status` verbs in their RBAC manifests; web apps never do).
        self._authorize(
            req,
            "update",
            resource_for_kind(req.path_params["kind"]) + "/status",
            _seg_ns(req.path_params["ns"]),
        )
        return json_response(
            self.api.update_status(
                self._body_matching_path(req),
                lease_guard=self._lease_guard(req),
            ).to_dict()
        )

    def delete(self, req: Request) -> Response:
        self._authorize(
            req,
            "delete",
            resource_for_kind(req.path_params["kind"]),
            _seg_ns(req.path_params["ns"]),
        )
        self.api.delete(
            req.path_params["kind"],
            req.path_params["name"],
            _seg_ns(req.path_params["ns"]),
            lease_guard=self._lease_guard(req),
        )
        return json_response({"deleted": True})

    def pod_log(self, req: Request) -> Response:
        import pathlib

        # The kubelet log endpoint's RBAC resource (`pods/log`, verb get).
        self._authorize(
            req, "get", "pods/log", _seg_ns(req.path_params["ns"])
        )
        if self.log_root is None:
            raise HttpError(
                404, "log serving not configured (no capture directory)"
            )
        pod = self.api.get(
            "Pod", req.path_params["name"], _seg_ns(req.path_params["ns"])
        )
        log_path = pod.status.get("logPath")
        if not log_path:
            raise HttpError(
                404,
                f"pod {pod.metadata.name!r} has no captured logs (the "
                "local runtime publishes status.logPath when capture is "
                "on)",
            )
        path = pathlib.Path(log_path).resolve()
        # status is client-writable: refuse anything outside the capture
        # root (resolve() collapses ../ and symlinks first).
        if not path.is_relative_to(self.log_root):
            raise HttpError(
                404, f"log path for {pod.metadata.name!r} is outside the "
                "capture directory",
            )
        if not path.is_file():
            raise HttpError(404, f"log file {log_path!r} is gone")
        return Response(path.read_bytes(), content_type="text/plain")


class CircuitBreaker:
    """Per-endpoint circuit breaker (the client-go rate-limiter posture,
    plus fail-fast): `threshold` consecutive transport-class failures
    open the circuit for `cooldown` seconds, during which calls shed
    immediately instead of hammering a struggling endpoint; after the
    cooldown one probe per window is allowed (half-open), and a single
    success closes the circuit. Functional error statuses (404/409/422)
    are successes here — the endpoint answered."""

    def __init__(self, threshold: int = 5, cooldown: float = 2.0):
        self.threshold = threshold
        self.cooldown = cooldown
        self.failures = 0
        self.trips = 0  # observability: times the circuit opened
        self._probe_at = 0.0
        self._lock = threading.Lock()

    def allow(self) -> bool:
        with self._lock:
            if self.failures < self.threshold:
                return True
            now = time.monotonic()
            if now >= self._probe_at:
                # Half-open: claim this window's single probe slot.
                self._probe_at = now + self.cooldown
                return True
            return False

    def success(self) -> None:
        with self._lock:
            self.failures = 0

    def failure(self) -> None:
        with self._lock:
            self.failures += 1
            if self.failures >= self.threshold:
                # Crossing the threshold opens the circuit (one trip); a
                # failed half-open probe re-trips it and restarts the
                # cooldown, so a flapping endpoint shows its full
                # history in `trips` rather than one eternal episode.
                self.trips += 1
                self._probe_at = time.monotonic() + self.cooldown

    @property
    def open(self) -> bool:
        with self._lock:
            return (
                self.failures >= self.threshold
                and time.monotonic() < self._probe_at
            )


def endpoints_from_env(value: str) -> list[str]:
    """Parse the launcher env contract's apiserver address: a single URL
    or a comma-separated endpoint list (active-passive HA pairs). Every
    e2e worker builds its client from this, so a worker spawned against
    one facade today transparently gains failover the day its env grows
    a second endpoint."""
    urls = [u.strip() for u in value.split(",") if u.strip()]
    if not urls:
        raise ValueError(f"no apiserver endpoints in {value!r}")
    return urls


class _Endpoint:
    """One apiserver address an `HttpApiClient` may talk to: parsed
    location plus this endpoint's own keep-alive connection pool and
    handshake counter. Circuit breakers are also per-endpoint (keyed by
    `_breaker_for`), so one dead facade's open circuits never gate its
    standby."""

    def __init__(self, url: str):
        self.url = url.rstrip("/")
        parts = urllib.parse.urlsplit(self.url)
        self.host = parts.hostname or "127.0.0.1"
        self.https = parts.scheme == "https"
        self.port = parts.port or (443 if self.https else 80)
        self.pool: list = []
        self.handshakes = 0

    def __repr__(self) -> str:
        return f"_Endpoint({self.url!r})"


class _ConnectFailed(Exception):
    """Dialing an endpoint failed before ANY request byte was sent — the
    one transport failure that is unambiguous for every method (the
    server cannot have committed anything), so the client may rotate to
    the next endpoint and replay even a write."""

    def __init__(self, cause: OSError):
        super().__init__(str(cause))
        self.cause = cause


class HttpApiClient:
    """Remote twin of FakeApiServer's CRUD + watch surface.

    `watch()` makes this a real informer client: one multiplexed
    long-poll stream feeds every registered handler, resuming from the
    last seen resourceVersion across reconnects and recovering from 410
    Gone via list-then-rewatch (synthetic MODIFIED events). A
    `controllers/runtime.Controller` built over this client is therefore
    event-driven across the process boundary — zero list polling.

    `base_url` may be an endpoint LIST (active-passive HA: the kube
    client's multi-master server list). The client talks to one
    endpoint at a time and fails over — sticky, so one takeover costs
    one rotation, not a probe per request — when that endpoint refuses
    connections, when its circuit is open (repeated failures shed to
    the next endpoint instead of failing fast into the caller), or when
    a watch stream dies with it. Only a CONNECT failure may transparently
    re-send a write to the next endpoint (nothing was sent, so nothing
    can double-apply); once bytes are on the wire the usual ambiguous-
    failure rules apply unchanged. Watchers resuming on the standby ride
    the normal bookmark path: a bookmark the standby's journal can't
    serve gets 410 Gone and the informer relists — duplicate-free for
    level-triggered consumers by construction. A single-element list (or
    a plain string) behaves exactly like the historical single
    `base_url`."""

    def __init__(
        self,
        base_url,
        timeout: float = 10.0,
        watch_poll_timeout: float = 5.0,
        watch_retry: float = 0.5,
        token: str | None = None,
        ca: str | None = None,
        allow_plaintext_token: bool | None = None,
        write_retries: int = 3,
        retry_base: float = 0.05,
        retry_cap: float = 1.0,
        breaker_threshold: int = 5,
        breaker_cooldown: float = 2.0,
        stream_failure_threshold: int = 3,
        stream_degraded_seconds: float = 5.0,
        stream_reprobe_seconds: float = 60.0,
    ):
        urls = [base_url] if isinstance(base_url, str) else list(base_url)
        if not urls:
            raise ValueError("HttpApiClient needs at least one endpoint")
        self._endpoints = [_Endpoint(u) for u in urls]
        # Which endpoint serves requests right now. Failover is sticky:
        # a rotation moves every subsequent request (and the watch
        # stream) to the new endpoint until IT fails in turn.
        self._active = 0
        self._endpoint_lock = threading.Lock()
        self.failovers = 0  # observability: endpoint rotations performed
        # The identity credential (serviceaccount-token analog). Falls
        # back to KFTPU_TOKEN so gang workers spawned with the launcher
        # env contract inherit their pod's credential without plumbing.
        self.token = token if token is not None else os.environ.get(
            "KFTPU_TOKEN"
        )
        # TLS: pin the platform CA (env fallback KFTPU_CA rides the same
        # launcher env contract as the token). Verification is against
        # the pinned CA only — never the system trust store. One context
        # serves every https endpoint: an HA pair shares the platform CA
        # (the standby boots over the same state dir's TLS material).
        ca = ca if ca is not None else os.environ.get("KFTPU_CA")
        self._ssl = None
        if any(ep.https for ep in self._endpoints):
            from kubeflow_tpu.web import tls as tlsmod

            if ca:
                self._ssl = tlsmod.client_context(ca)
            elif os.environ.get("KFTPU_SYSTEM_TRUST") == "1":
                # Publicly-signed deployments opt into the system trust
                # store explicitly.
                import ssl as _ssl

                self._ssl = _ssl.create_default_context()
            else:
                # The platform CA is self-signed: without the pin every
                # request would die later with an opaque
                # CERTIFICATE_VERIFY_FAILED. Fail actionably, now.
                raise ValueError(
                    f"https server {self._endpoints[0].url!r} needs the "
                    "platform CA pinned (ca=/--ca/KFTPU_CA; the launcher "
                    "prints the path at boot), or KFTPU_SYSTEM_TRUST=1 "
                    "for a publicly-signed endpoint"
                )
        plaintext = [ep.url for ep in self._endpoints if not ep.https]
        if plaintext and self.token:
            # A bearer token over cleartext is a leaked credential, not a
            # working config: refuse unless the caller explicitly opts
            # in (loopback-only test rigs; KFTPU_ALLOW_PLAINTEXT=1 for
            # spawned workers). Secure-by-default, like the serving
            # side — and EVERY endpoint must qualify, or a failover
            # would leak the token the primary protected.
            if allow_plaintext_token is None:
                allow_plaintext_token = os.environ.get(
                    "KFTPU_ALLOW_PLAINTEXT"
                ) == "1"
            if not allow_plaintext_token:
                raise ValueError(
                    f"refusing to send a bearer token over plaintext "
                    f"{plaintext[0]!r} — use https:// (pin the CA via "
                    f"ca=/KFTPU_CA) or pass allow_plaintext_token=True / "
                    f"KFTPU_ALLOW_PLAINTEXT=1 for a trusted loopback"
                )
        self.timeout = timeout
        self.watch_poll_timeout = watch_poll_timeout
        self.watch_retry = watch_retry
        self._watchers: list[tuple[str | None, WatchHandler]] = []
        self._watch_lock = threading.Lock()
        self._watch_thread: threading.Thread | None = None
        self._closed = threading.Event()
        # Persistent-connection pools (the client-go shared-transport
        # analog): requests ride keep-alive connections, so a client
        # pays O(1) TCP+TLS handshakes for its whole request train
        # instead of one per request. `handshakes` counts connections
        # dialed — the load test pins it flat while requests grow. The
        # pool is per-endpoint (each keep-alive connection belongs to
        # the facade that accepted it).
        self._pool_lock = threading.Lock()
        # Leader-election write fencing: when armed (set_lease_guard),
        # every write carries the guard and the server rejects it with
        # Conflict unless the lease still shows this holder+generation.
        self.lease_guard: tuple[str, str, str, int] | None = None
        # -- fault tolerance (the chaos-soak contract) ---------------------
        # Bounded retry-with-jitter for transient write failures. Safe
        # only because every retried write is guarded: updates carry a
        # resourceVersion precondition, creates recover AlreadyExists by
        # comparing the stored object, deletes treat NotFound as done —
        # so an ambiguous failure (connection died after send) can never
        # double-apply.
        self.write_retries = write_retries
        self.retry_base = retry_base
        self.retry_cap = retry_cap
        self.retries_total = 0  # write attempts beyond the first
        # Per-endpoint circuit breakers: repeated transport failures at
        # one endpoint shed load (fail fast) instead of stacking threads
        # behind a dead socket, then probe their way closed again.
        self.breaker_threshold = breaker_threshold
        self.breaker_cooldown = breaker_cooldown
        self._breakers: dict[str, CircuitBreaker] = {}
        self._breakers_lock = threading.Lock()
        # Streaming-watch health: consecutive stream failures past the
        # threshold shed the watch to long-poll DEGRADED mode for
        # `stream_degraded_seconds`, then re-probe the stream; a server
        # that affirmatively rejects the stream form (distinguishable
        # 400) is re-probed on the slower `stream_reprobe_seconds`
        # cadence instead of being written off for the process lifetime.
        self._stream_breaker = CircuitBreaker(
            threshold=stream_failure_threshold,
            cooldown=stream_degraded_seconds,
        )
        self.stream_reprobe_seconds = stream_reprobe_seconds
        self._stream_unsupported_until = 0.0

    def set_lease_guard(
        self, guard: tuple[str, str, str, int] | None
    ) -> None:
        """Arm (or disarm with None) the lease guard on all writes. Pass
        `LeaderElector.guard` after acquiring leadership — from then on a
        partition that deposes this leader turns its in-flight writes
        into Conflicts instead of corruption of the successor's term."""
        self.lease_guard = guard

    # How many idle connections to keep (a controller process typically
    # runs one watch stream + a few concurrent reconcile threads).
    POOL_SIZE = 4

    # -- endpoint selection (active-passive failover) ----------------------

    def _endpoint(self) -> _Endpoint:
        with self._endpoint_lock:
            return self._endpoints[self._active]

    @property
    def base_url(self) -> str:
        """The endpoint currently serving this client (back-compat: the
        historical single-URL attribute, now the ACTIVE endpoint)."""
        return self._endpoint().url

    @property
    def endpoints(self) -> tuple[str, ...]:
        return tuple(ep.url for ep in self._endpoints)

    @property
    def handshakes(self) -> int:
        """Connections dialed, summed over endpoints (the load test pins
        this flat while requests grow)."""
        return sum(ep.handshakes for ep in self._endpoints)

    # Back-compat introspection (tests dial raw sockets at the client's
    # target): the ACTIVE endpoint's location.
    @property
    def _conn_host(self) -> str:
        return self._endpoint().host

    @property
    def _conn_port(self) -> int:
        return self._endpoint().port

    def _set_active(self, ep: _Endpoint) -> None:
        """Make `ep` the endpoint subsequent requests go to first.
        Counted as a failover only when it actually changes — rotation
        is sticky, so a takeover costs one rotation, not one per call."""
        with self._endpoint_lock:
            idx = self._endpoints.index(ep)
            if idx != self._active:
                self._active = idx
                self.failovers += 1
                log.info("apiserver failover: now talking to %s", ep.url)

    def _new_conn(self, ep: _Endpoint):
        import http.client as _hc

        if ep.https:
            conn = _hc.HTTPSConnection(
                ep.host,
                ep.port,
                timeout=self.timeout,
                context=self._ssl,
            )
        else:
            conn = _hc.HTTPConnection(
                ep.host, ep.port, timeout=self.timeout
            )
        conn._kftpu_reused = False
        conn._kftpu_ep = ep
        with self._pool_lock:
            ep.handshakes += 1
        return conn

    # Discard pooled connections idle longer than this (below the
    # server's 75 s keep-alive reap, so the client almost never races a
    # server-side close — the stale-connection window that would
    # otherwise force ambiguous write retries).
    POOL_IDLE_MAX = 60.0

    def _get_conn(self, ep: _Endpoint | None = None):
        import time as _time

        ep = ep if ep is not None else self._endpoint()
        now = _time.monotonic()
        with self._pool_lock:
            while ep.pool:
                conn = ep.pool.pop()
                if now - getattr(conn, "_kftpu_idle_since", now) \
                        <= self.POOL_IDLE_MAX:
                    return conn
                conn.close()  # probably server-reaped already
        return self._new_conn(ep)

    def _put_conn(self, conn) -> None:
        import time as _time

        ep = getattr(conn, "_kftpu_ep", None) or self._endpoint()
        conn._kftpu_reused = True
        conn._kftpu_idle_since = _time.monotonic()
        # Restore the default op timeout (a stream may have raised it).
        if conn.sock is not None:
            conn.sock.settimeout(self.timeout)
        with self._pool_lock:
            if len(ep.pool) < self.POOL_SIZE:
                ep.pool.append(conn)
                return
        conn.close()

    def _attempt(self, ep: _Endpoint, method, path, data, headers):
        """One round trip against ONE endpoint. A dial failure (nothing
        sent yet) raises `_ConnectFailed` so the caller may rotate; any
        failure after bytes hit the wire keeps the historical ambiguity
        rules (reused-GET retries once on a fresh connection, everything
        else propagates)."""
        import http.client as _hc

        while True:
            conn = self._get_conn(ep)
            if conn.sock is None:
                # Dial explicitly, so a refused/unreachable endpoint is
                # distinguishable from a request that died mid-flight —
                # the distinction that makes endpoint rotation safe for
                # writes.
                try:
                    conn.connect()
                except OSError as e:
                    conn.close()
                    raise _ConnectFailed(e) from e
            try:
                conn.request(method, path, body=data, headers=headers)
                resp = conn.getresponse()
            except (_hc.HTTPException, OSError):
                reused = getattr(conn, "_kftpu_reused", False)
                conn.close()
                if reused and method == "GET":
                    continue  # stale keep-alive victim: one fresh retry
                raise
            return conn, resp

    def _request_raw(
        self, method: str, path: str, body: dict | None = None
    ):
        """One round trip on a pooled connection; returns (conn, resp)
        with the response UNREAD (callers stream or slurp).

        Endpoint walk: starting at the active endpoint, skip endpoints
        whose circuit is open (shed to the standby instead of failing
        fast into the caller) and rotate past endpoints that refuse the
        dial; the endpoint that answers becomes the active one. With a
        single endpoint this degenerates to exactly the historical
        behavior (breaker-open → Unavailable, dial failure → OSError).

        Retry policy (the urllib3 rule): only IDEMPOTENT-safe requests
        (GET) auto-retry when a REUSED connection dies — for a write,
        the failure is ambiguous (the server may have committed before
        the connection broke) and a blind replay could double-apply, so
        writes propagate the error and the caller's level-triggered
        retry re-reads state first. A CONNECT failure is the exception:
        nothing was sent, so trying the next endpoint is safe for every
        method. The stale-connection window writes would otherwise hit
        is mostly closed by POOL_IDLE_MAX reaping pooled connections
        before the server's keep-alive timeout can."""
        data = json.dumps(body).encode() if body is not None else None
        headers = {
            "Content-Type": "application/json",
            # An active span's trace id rides along, so a reconcile's
            # apiserver calls land in the same trace (`utils.tracing`).
            **self._auth_header(),
            **tracing.trace_header(),
        }
        guard = self.lease_guard
        if guard is not None and method in ("POST", "PUT", "DELETE", "PATCH"):
            headers["X-Kftpu-Lease-Guard"] = json.dumps(list(guard))
        eps = self._endpoints
        with self._endpoint_lock:
            start = self._active
        last_exc: Exception | None = None
        for k in range(len(eps)):
            ep = eps[(start + k) % len(eps)]
            breaker = self._breaker_for(ep, method, path)
            if not breaker.allow():
                # Open circuit: shed to the next endpoint; with nothing
                # left to try this surfaces below as Unavailable.
                last_exc = Unavailable(
                    f"circuit open for {method} "
                    f"{path.partition('?')[0]} at {ep.url} (failing "
                    "fast after repeated endpoint failures)"
                )
                continue
            try:
                conn, resp = self._attempt(ep, method, path, data, headers)
            except _ConnectFailed as e:
                breaker.failure()
                last_exc = e.cause
                continue  # rotate: the dial failed, nothing was sent
            except Exception:
                breaker.failure()
                raise  # ambiguous once bytes were sent: never rotate
            self._set_active(ep)
            return conn, resp
        assert last_exc is not None
        raise last_exc

    def _finish(self, conn, resp) -> bytes:
        """Slurp the body and recycle (or retire) the connection."""
        try:
            data = resp.read()
        except Exception:
            conn.close()
            raise
        if resp.will_close:
            conn.close()
        else:
            self._put_conn(conn)
        return data

    @staticmethod
    def _raise_for_status(status: int, detail: str):
        if status in (401, 403):
            raise Forbidden(detail)
        if status == 404:
            raise NotFound(detail)
        if status == 409:
            # The server folds AlreadyExists and Conflict onto 409;
            # disambiguate from the message.
            if "already exists" in detail:
                raise AlreadyExists(detail)
            raise Conflict(detail)
        if status == 410:
            raise Gone(detail)
        if status == 422:
            raise Invalid(detail)
        if status == 503:
            raise Unavailable(detail)
        raise ApiError(f"HTTP {status}: {detail}")

    def _breaker_for(
        self, ep: _Endpoint, method: str, path: str
    ) -> CircuitBreaker:
        """One breaker per ENDPOINT per endpoint class: method + the
        first two path segments ("/apis/<kind>"), query stripped — fine
        enough that a sick watch endpoint doesn't open the circuit for
        writes, coarse enough that per-object paths share state. Keyed
        by endpoint so a dead active's open circuits shed load to the
        standby instead of gating the whole client."""
        bare = path.partition("?")[0]
        key = f"{ep.url} {method} /" + "/".join(bare.split("/")[1:3])
        with self._breakers_lock:
            breaker = self._breakers.get(key)
            if breaker is None:
                breaker = self._breakers[key] = CircuitBreaker(
                    threshold=self.breaker_threshold,
                    cooldown=self.breaker_cooldown,
                )
            return breaker

    def breaker_state(self) -> dict[str, tuple[int, bool]]:
        """Observability: endpoint → (trips, currently_open)."""
        with self._breakers_lock:
            snapshot = dict(self._breakers)
        # Each breaker is read outside the registry lock: `open` takes
        # the breaker's own lock, and nesting that under `_breakers_lock`
        # adds a lock-order edge for a pure observability read.
        return {k: (b.trips, b.open) for k, b in snapshot.items()}

    def _call(self, method: str, path: str, body: dict | None = None) -> dict:
        # Transport-level failures (dial refusals, mid-flight deaths,
        # all-circuits-open) are accounted and raised inside
        # _request_raw's endpoint walk.
        conn, resp = self._request_raw(method, path, body)
        status = resp.status
        try:
            data = self._finish(conn, resp)
        except Exception:
            self._breaker_for(conn._kftpu_ep, method, path).failure()
            raise
        # 5xx counts against the endpoint; everything else — including
        # functional errors like 404/409/422 — proves it is answering.
        breaker = self._breaker_for(conn._kftpu_ep, method, path)
        if status >= 500:
            breaker.failure()
        else:
            breaker.success()
        if status >= 400:
            self._raise_for_status(status, data.decode(errors="replace"))
        return json.loads(data)

    def _write_with_retry(self, attempt, *, recover_committed=None):
        """Bounded retry with exponential backoff + full jitter for
        transient WRITE failures (`Unavailable`/transport errors — the
        chaos soak's 5xx bursts, resets, and crash-before-ack class).

        A transport failure is AMBIGUOUS: the server may have committed
        before the connection died. After any ambiguous failure,
        `recover_committed(exc)` is consulted when a later attempt fails
        with an already-happened-shaped error (AlreadyExists / NotFound
        / Conflict): it returns the recovered result, or None to
        re-raise — which is what keeps a retried write from ever
        double-applying."""
        import http.client as _hc

        delay = self.retry_base
        ambiguous = False
        attempts = 0
        while True:
            try:
                return attempt()
            except (Unavailable, _hc.HTTPException, OSError) as e:
                # 503 means the store refused before committing;
                # a dead connection means we simply don't know.
                ambiguous = ambiguous or not isinstance(e, Unavailable)
                attempts += 1
                if attempts > self.write_retries or self._closed.is_set():
                    raise
                self.retries_total += 1
                # Full jitter: decorrelates a fleet of clients retrying
                # into the same recovering endpoint.
                self._closed.wait(random.uniform(0, delay))
                delay = min(delay * 2, self.retry_cap)
            except (AlreadyExists, NotFound, Conflict) as e:
                if ambiguous and recover_committed is not None:
                    out = recover_committed(e)
                    if out is not None:
                        return out
                raise

    def get(
        self,
        kind: str,
        name: str,
        namespace: str = "default",
        version: str | None = None,
    ) -> Resource:
        query = f"?{urllib.parse.urlencode({'version': version})}" if version else ""
        return Resource.from_dict(
            self._call("GET", f"/apis/{kind}/{_ns_seg(namespace)}/{name}{query}")
        )

    def list(
        self,
        kind: str,
        namespace: str | None = None,
        label_selector: dict[str, str] | None = None,
        version: str | None = None,
    ) -> list[Resource]:
        params = {}
        if version:
            params["version"] = version
        if namespace is not None:
            params["namespace"] = _ns_seg(namespace)
        if label_selector:
            params["labelSelector"] = ",".join(
                f"{k}={v}" for k, v in label_selector.items()
            )
        query = f"?{urllib.parse.urlencode(params)}" if params else ""
        data = self._call("GET", f"/apis/{kind}{query}")
        return [Resource.from_dict(d) for d in data["items"]]

    def create(self, obj: Resource) -> Resource:
        def attempt() -> Resource:
            return Resource.from_dict(
                self._call("POST", f"/apis/{obj.kind}", obj.to_dict())
            )

        def recover(e: ApiError) -> Resource | None:
            # AlreadyExists after an ambiguous failure: OUR create may be
            # the one that landed. Claim it only if the stored object
            # contains what we sent (mutating admission may have ADDED
            # defaulted fields; spec-equality would disown our own
            # committed write) — a genuinely different pre-existing
            # object stays an error.
            if not isinstance(e, AlreadyExists):
                return None
            try:
                stored = self.get(
                    obj.kind, obj.metadata.name, obj.metadata.namespace
                )
            except ApiError:
                return None
            if (
                _subsumes(stored.spec, obj.spec)
                and stored.metadata.labels == obj.metadata.labels
            ):
                return stored
            return None

        return self._write_with_retry(attempt, recover_committed=recover)

    def update(self, obj: Resource) -> Resource:
        # Safe to retry: the body's resourceVersion precondition means a
        # first attempt that actually committed turns the replay into a
        # Conflict (the caller re-reads), never a silent double-apply.
        return self._write_with_retry(
            lambda: Resource.from_dict(
                self._call(
                    "PUT",
                    f"/apis/{obj.kind}/{_ns_seg(obj.metadata.namespace)}/"
                    f"{obj.metadata.name}",
                    obj.to_dict(),
                )
            )
        )

    def update_status(self, obj: Resource) -> Resource:
        return self._write_with_retry(
            lambda: Resource.from_dict(
                self._call(
                    "PUT",
                    f"/apis/{obj.kind}/{_ns_seg(obj.metadata.namespace)}/"
                    f"{obj.metadata.name}/status",
                    obj.to_dict(),
                )
            )
        )

    def delete(self, kind: str, name: str, namespace: str = "default") -> None:
        def recover(e: ApiError):
            # NotFound after an ambiguous failure: our delete landed (or
            # someone else's did — either way the object is gone, which
            # is all a delete promises).
            return {"deleted": True} if isinstance(e, NotFound) else None

        self._write_with_retry(
            lambda: self._call(
                "DELETE", f"/apis/{kind}/{_ns_seg(namespace)}/{name}"
            ),
            recover_committed=recover,
        )

    def pod_log(self, name: str, namespace: str = "default") -> str:
        """The pod's captured stdout (raw text; same pooled transport and
        error mapping as every other call)."""
        conn, resp = self._request_raw(
            "GET", f"/apis/Pod/{_ns_seg(namespace)}/{name}/log"
        )
        status = resp.status
        data = self._finish(conn, resp)
        if status >= 400:
            detail = data.decode(errors="replace")
            try:
                detail = json.loads(detail).get("log", detail)
            except ValueError:
                pass
            self._raise_for_status(status, detail)
        return data.decode(errors="replace")

    def _auth_header(self) -> dict[str, str]:
        return (
            {"Authorization": f"Bearer {self.token}"} if self.token else {}
        )

    def apply(self, obj: Resource) -> Resource:
        """Create-or-update, evaluated server-side (the store's compare is
        post-admission/post-conversion, so a remote reconciler's apply
        no-ops exactly when an in-process one would). Declaratively
        idempotent, so the transient-failure retry needs no recovery."""
        return self._write_with_retry(
            lambda: Resource.from_dict(
                self._call(
                    "POST", f"/apis/{obj.kind}?apply=true", obj.to_dict()
                )
            )
        )

    def record_event(
        self,
        about: Resource,
        reason: str,
        message: str,
        *,
        type_: str = "Normal",
    ) -> Resource:
        """Same Event shape FakeApiServer.record_event emits
        (`notebook_controller.go:87-103` event mirroring works unchanged
        from a remote controller). The content-derived name (see
        `fake_apiserver.event_name`) makes a retried emission collide
        with its first attempt instead of duplicating it."""
        from kubeflow_tpu.testing.fake_apiserver import (
            event_name,
            event_resource,
        )

        ev = event_resource(about, reason, message, type_=type_)
        try:
            return self.create(ev)
        except AlreadyExists:
            # The same logical event is already recorded (a retried
            # emission, or a repeat occurrence K8s would aggregate).
            return self.get(
                "Event", event_name(about, reason, message, type_),
                about.metadata.namespace,
            )

    # -- watch (informer client) ------------------------------------------

    def watch(self, handler: WatchHandler, kind: str | None = None) -> None:
        """Register a handler; the first registration starts the stream.
        Initial sync delivers every existing object of each concretely
        watched kind as a synthetic MODIFIED (list-then-watch), so a
        controller starting late still reconciles pre-existing objects."""
        with self._watch_lock:
            self._watchers.append((kind, handler))
            started = self._watch_thread is None
            if started:
                self._watch_thread = threading.Thread(
                    target=self._watch_loop,
                    name="apiclient-watch",
                    daemon=True,
                )
                self._watch_thread.start()
        if not started and kind is not None:
            # Late registration: the running stream's bookmark may already
            # be past this kind's existing objects, and the initial resync
            # never listed it. Deliver current state now — possibly
            # duplicating a concurrent stream delivery, which level-
            # triggered consumers tolerate by construction.
            try:
                data = self._call("GET", f"/apis/{kind}")
                for item in data["items"]:
                    self._dispatch("MODIFIED", Resource.from_dict(item))
            except Exception:
                log.debug(
                    "late-registration sync for %s failed", kind,
                    exc_info=True,
                )

    def close(self) -> None:
        self._closed.set()
        with self._pool_lock:
            conns = []
            for ep in self._endpoints:
                conns.extend(ep.pool)
                ep.pool = []
        for conn in conns:
            conn.close()

    def _dispatch(self, event: str, obj: Resource) -> None:
        for kind, handler in list(self._watchers):
            if kind is None or kind == obj.kind:
                try:
                    handler(event, obj)
                except Exception:
                    log.exception("watch handler failed for %s %s",
                                  event, obj.key)

    def _resync(self) -> int:
        """List every concretely watched kind, delivering synthetic
        MODIFIED events; returns the rv to watch from. The bookmark is the
        FIRST list's rv, so anything committed mid-resync is re-delivered
        by the subsequent watch — at-least-once, which level-triggered
        reconcilers tolerate by construction."""
        with self._watch_lock:
            kinds = {k for k, _ in self._watchers if k is not None}
        rv: int | None = None
        for kind in sorted(kinds):
            data = self._call("GET", f"/apis/{kind}")
            if rv is None:
                rv = data.get("resourceVersion", 0)
            for item in data["items"]:
                self._dispatch("MODIFIED", Resource.from_dict(item))
        return rv if rv is not None else 0

    def _stream_allowed(self) -> bool:
        """Whether this pass should attempt the streaming watch form.
        False while the server has affirmatively rejected it (until the
        periodic re-probe) or while the stream circuit is open (shed to
        long-poll degraded mode)."""
        if time.monotonic() < self._stream_unsupported_until:
            return False
        return self._stream_breaker.allow()

    @property
    def stream_degraded(self) -> bool:
        """Observability: True while the watch runs in long-poll
        degraded mode instead of streaming."""
        return (
            time.monotonic() < self._stream_unsupported_until
            or self._stream_breaker.open
        )

    def _watch_loop(self) -> None:
        rv = None
        # Prefer the chunked streaming watch (one held-open response,
        # event latency = delivery latency); fall back to long-polling
        # when the server rejects the stream form or the stream circuit
        # opens. NEITHER fallback is sticky: an affirmative rejection is
        # re-probed every stream_reprobe_seconds (the server may gain
        # the capability mid-life), and repeated stream failures shed to
        # long-poll only for the breaker's cooldown — the chaos soak's
        # truncated/slow streams degrade the transport, never disable
        # it.
        while not self._closed.is_set():
            try:
                if rv is None:
                    rv = self._resync()
                if self._stream_allowed():
                    try:
                        rv = self._stream_once(rv)
                        self._stream_breaker.success()
                        continue
                    except _StreamUnsupported as e:
                        log.info(
                            "server rejected streaming watch (%s); "
                            "long-polling, re-probe in %.0fs",
                            e, self.stream_reprobe_seconds,
                        )
                        self._stream_unsupported_until = (
                            time.monotonic() + self.stream_reprobe_seconds
                        )
                    except (Gone, PermissionError):
                        raise
                    except Exception:
                        if self._closed.is_set():
                            return
                        # Count against the stream circuit; fall through
                        # to one long-poll round so progress continues
                        # even while the stream endpoint is sick.
                        self._stream_breaker.failure()
                        log.debug(
                            "stream watch failed (%d consecutive); "
                            "long-poll round",
                            self._stream_breaker.failures, exc_info=True,
                        )
                params = urllib.parse.urlencode(
                    {
                        "watch": "true",
                        "resourceVersion": rv,
                        "timeoutSeconds": self.watch_poll_timeout,
                    }
                )
                data = self._call("GET", f"/apis/_?{params}")
            except Gone:
                rv = None  # journal horizon passed us — relist
                continue
            except PermissionError as e:
                if self._closed.is_set():
                    return
                # Not a network blip: a missing/revoked/under-privileged
                # token will never heal by hot-retrying. Surface loudly
                # and back off hard (the operator may re-grant RBAC, so
                # the stream stays up rather than dying silently).
                log.error("watch stream unauthorized (%s); backing off", e)
                self._closed.wait(max(self.watch_retry, 5.0))
                continue
            except Exception:
                if self._closed.is_set():
                    return
                log.debug("watch stream error; retrying", exc_info=True)
                self._closed.wait(self.watch_retry)
                continue
            rv = data["resourceVersion"]
            for ev in data["events"]:
                self._dispatch(ev["type"], Resource.from_dict(ev["object"]))

    def _stream_once(self, rv: int) -> int:
        """Consume one streaming watch response; returns the rv to resume
        from after the server ends the stream cleanly (its duration cap).
        Events dispatch as their lines arrive — no poll quantization."""
        params = urllib.parse.urlencode(
            {"watch": "true", "stream": "true", "resourceVersion": rv}
        )
        conn, resp = self._request_raw("GET", f"/apis/_?{params}")
        if resp.status == 400:
            detail = self._finish(conn, resp).decode(errors="replace")
            if _stream_rejected(detail):
                raise _StreamUnsupported(detail)
            # A stray 400 (fault injection, a confused intermediary, a
            # malformed bookmark) is NOT evidence the server lacks the
            # stream form — treating it as such permanently degraded the
            # transport (the round-5 apiserver_http.py:1032 bug).
            raise ApiError(f"watch stream HTTP 400: {detail}")
        if resp.status >= 400:
            status = resp.status
            detail = self._finish(conn, resp).decode(errors="replace")
            self._raise_for_status(status, detail)
        # Reads block until the next event/bookmark line; the server
        # bookmarks every STREAM_SLICE (5 s), so a healthy-but-quiet
        # stream produces a line well inside this read timeout — a
        # silent peer here is a dead one.
        if conn.sock is not None:
            conn.sock.settimeout(30.0)
        try:
            while not self._closed.is_set():
                line = resp.readline()
                if not line:
                    # Clean end of stream (terminal chunk consumed): the
                    # connection is reusable — the next stream/call rides
                    # the same handshake.
                    self._put_conn(conn)
                    return rv
                ev = json.loads(line)
                etype = ev["type"]
                if etype == "BOOKMARK":
                    rv = ev["resourceVersion"]
                elif etype == "ERROR":
                    if ev.get("status") == 410:
                        raise Gone(ev.get("message", "watch horizon"))
                    raise ApiError(
                        f"watch stream error {ev.get('status')}: "
                        f"{ev.get('message', '')}"
                    )
                else:
                    self._dispatch(etype, Resource.from_dict(ev["object"]))
                    rv = ev["rv"]
            conn.close()  # closed mid-stream: response state unusable
            return rv
        except BaseException:
            conn.close()
            raise


def _subsumes(stored, sent) -> bool:
    """Whether `stored` contains everything in `sent`: dicts may carry
    EXTRA keys (admission-defaulted fields), everything else must match
    exactly. The create-recovery ownership test — conservative enough
    that admission mutations which REWRITE sent values (or splice lists,
    e.g. PodDefault injection) fall back to surfacing AlreadyExists
    rather than mis-claiming a stranger's object."""
    if isinstance(sent, dict):
        if not isinstance(stored, dict):
            return False
        return all(
            k in stored and _subsumes(stored[k], v) for k, v in sent.items()
        )
    return stored == sent


def _stream_rejected(detail: str) -> bool:
    """Whether a 400 body is an AFFIRMATIVE streaming-watch rejection.

    A server that doesn't speak `stream=true` names the parameter in its
    complaint ("unknown/unsupported parameter `stream`"); an unrelated
    400 — an injected fault, a proxy in the path, a bad bookmark — does
    not. Only the former may put the client in long-poll fallback; the
    latter is a transient error like any other (the non-sticky contract
    tested by the chaos soak). Two conditions must hold: the stream
    token at a word start (so an intermediary's "upstream" never
    matches) AND rejection language (so "stream timeout"/"stream reset"
    transients never match)."""
    import re

    message = detail
    try:
        parsed = json.loads(detail)
        if isinstance(parsed, dict):
            message = parsed.get("log", detail)
    except ValueError:
        pass
    message = str(message)
    return (
        re.search(r"\bstream", message, re.IGNORECASE) is not None
        and re.search(
            r"unsupported|not supported|unknown|unrecognized|invalid"
            r"|parameter",
            message,
            re.IGNORECASE,
        )
        is not None
    )


class _StreamUnsupported(Exception):
    """Server affirmatively rejected `stream=true`: long-poll, re-probe
    periodically (`stream_reprobe_seconds`) — never sticky for life."""
