"""HTTP facade + client for the in-process API server.

The reference's sidecars and tests talk to the real K8s apiserver over
HTTP (`openmpi-controller/controller/util.py` uses the kubernetes client;
`testing/deploy_utils.py:31-71`). Our control plane stores resources in
`FakeApiServer`; this module serves that store over REST so *separate
processes* (sidecar CLI, e2e workers, probers) get the same boundary:

    GET    /apis/<kind>                      ?namespace=&labelSelector=k=v&version=
    GET    /apis/<kind>?watch=true           &resourceVersion=N&timeoutSeconds=S
    GET    /apis/<kind>/<ns>/<name>          ('_' namespace = cluster scope; ?version=)
    POST   /apis/<kind>                      (?apply=true → create-or-update)
    PUT    /apis/<kind>/<ns>/<name>[/status]
    DELETE /apis/<kind>/<ns>/<name>

Multi-version kinds: POST/PUT bodies may carry any served apiVersion
(storage normalizes to the hub version); GETs pass `?version=` to read at
a specific served version.

Watch semantics match the real apiserver's (the reference's controllers
are watch-driven across process boundaries — controller-runtime's
`SetupWithManager`, `notebook_controller.go:516`): a long-poll returns
events with rv > resourceVersion plus the rv to resume from; a bookmark
older than the journal horizon gets 410 Gone, and the client recovers the
way an informer does (re-list, deliver synthetic events, re-watch).

`HttpApiClient` mirrors the FakeApiServer method surface (get/list/create/
update/update_status/delete/apply/record_event/watch) so controller-side
code — including `controllers/runtime.Controller` — is client-agnostic:
the same reconciler binary runs in-process against the store or in a
separate process against this facade.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request

import logging
import threading

from kubeflow_tpu.api.objects import ObjectMeta, Resource, fresh_uid
from kubeflow_tpu.utils import tracing
from kubeflow_tpu.testing.fake_apiserver import (
    AlreadyExists,
    Conflict,
    FakeApiServer,
    Gone,
    Invalid,
    NotFound,
    WatchHandler,
)

log = logging.getLogger(__name__)
from kubeflow_tpu.web.wsgi import App, HttpError, Request, Response, json_response


def _ns_seg(namespace: str) -> str:
    return namespace or "_"


def _seg_ns(seg: str) -> str:
    return "" if seg == "_" else seg


class ApiServerApp(App):
    """REST facade. Unauthenticated — this is the in-cluster trust domain
    (the reference controllers talk to the apiserver with pod
    serviceaccounts; web-tier authn/authz stays in the web apps)."""

    def __init__(self, api: FakeApiServer, log_root: str | None = None):
        super().__init__("apiserver")
        self.api = api
        # Containment root for /log: only files under the runner's
        # capture dir are served. status is client-writable, so serving
        # status.logPath unconstrained would be an arbitrary-file-read
        # primitive. None disables log serving entirely.
        import pathlib

        self.log_root = (
            pathlib.Path(log_root).resolve() if log_root else None
        )
        self.add_route("/apis/<kind>", self.list_kind)
        self.add_route("/apis/<kind>", self.create, ("POST",))
        self.add_route("/apis/<kind>/<ns>/<name>", self.get)
        self.add_route("/apis/<kind>/<ns>/<name>", self.update, ("PUT",))
        self.add_route("/apis/<kind>/<ns>/<name>", self.delete, ("DELETE",))
        self.add_route(
            "/apis/<kind>/<ns>/<name>/status", self.update_status, ("PUT",)
        )
        # kubelet log-endpoint analog: serves the pod's captured stdout
        # (LocalPodRunner publishes status.logPath). Pod-only.
        self.add_route("/apis/Pod/<ns>/<name>/log", self.pod_log)
        # In-process trace collector drain (the platform's jaeger-query
        # stand-in): returns and clears all finished spans.
        self.add_route("/debug/traces", self.drain_traces)

    def drain_traces(self, req: Request) -> Response:
        from kubeflow_tpu.utils import tracing

        return json_response(
            {
                "spans": tracing.tracer.export(),
                "dropped": tracing.tracer.dropped,
            }
        )

    def list_kind(self, req: Request) -> Response:
        if req.query.get("watch") in ("true", "1"):
            return self._watch(req)
        selector = None
        if "labelSelector" in req.query:
            selector = dict(
                part.split("=", 1)
                for part in req.query["labelSelector"].split(",")
                if "=" in part
            )
        namespace = req.query.get("namespace")
        # The list's rv is the watch bookmark (informer list-then-watch).
        # Read it BEFORE listing: an object committed between the two
        # reads is then re-delivered by the watch (at-least-once), whereas
        # rv-after-list would place it behind the bookmark and lose it.
        rv = self.api.current_rv
        items = self.api.list(
            req.path_params["kind"],
            namespace=_seg_ns(namespace) if namespace is not None else None,
            label_selector=selector,
        )
        items = [self._at_version(r, req) for r in items]
        return json_response(
            {
                "items": [r.to_dict() for r in items],
                "resourceVersion": rv,
            }
        )

    def _watch(self, req: Request) -> Response:
        """Long-poll watch: block until events land past the bookmark (or
        timeoutSeconds), return them with the rv to resume from. `_` as
        the kind watches everything (the client multiplexes one stream
        across all its registered handlers)."""
        try:
            since = int(req.query.get("resourceVersion", "0"))
        except ValueError:
            raise HttpError(400, "resourceVersion must be an integer")
        timeout = min(float(req.query.get("timeoutSeconds", "10")), 60.0)
        kind = req.path_params["kind"]
        namespace = req.query.get("namespace")
        try:
            events, rv = self.api.wait_events(
                since,
                kind=None if kind == "_" else kind,
                namespace=_seg_ns(namespace) if namespace is not None else None,
                timeout=timeout,
            )
        except Gone as e:
            raise HttpError(410, str(e))
        return json_response(
            {
                "events": [
                    {"type": ev, "rv": ev_rv, "object": obj.to_dict()}
                    for ev_rv, ev, obj in events
                ],
                "resourceVersion": rv,
            }
        )

    def _at_version(self, obj: Resource, req: Request) -> Resource:
        version = req.query.get("version")
        if not version:
            return obj
        # Invalid propagates: wsgi maps it to 422 and HttpApiClient maps
        # 422 back to Invalid, so both clients surface the same error.
        return self.api.convert_to(obj, version)

    def get(self, req: Request) -> Response:
        obj = self.api.get(
            req.path_params["kind"],
            req.path_params["name"],
            _seg_ns(req.path_params["ns"]),
        )
        return json_response(self._at_version(obj, req).to_dict())

    def create(self, req: Request) -> Response:
        obj = Resource.from_dict(req.json())
        if obj.kind != req.path_params["kind"]:
            raise HttpError(400, "kind mismatch between path and body")
        if req.query.get("apply") in ("true", "1"):
            # Server-side apply: create-or-update with the store's own
            # no-op detection (post-admission, post-conversion compare) so
            # remote reconcilers don't re-trigger their own watches.
            return json_response(self.api.apply(obj).to_dict())
        return json_response(self.api.create(obj).to_dict(), status=201)

    def _body_matching_path(self, req: Request) -> Resource:
        """The path is authoritative: a body naming a different object than
        the REST path is a client bug, not a write to the named object."""
        obj = Resource.from_dict(req.json())
        if (
            obj.kind != req.path_params["kind"]
            or obj.metadata.name != req.path_params["name"]
            or (obj.metadata.namespace or "") != (_seg_ns(req.path_params["ns"]) or "")
        ):
            raise HttpError(400, "kind/namespace/name mismatch between path and body")
        return obj

    def update(self, req: Request) -> Response:
        return json_response(
            self.api.update(self._body_matching_path(req)).to_dict()
        )

    def update_status(self, req: Request) -> Response:
        return json_response(
            self.api.update_status(self._body_matching_path(req)).to_dict()
        )

    def delete(self, req: Request) -> Response:
        self.api.delete(
            req.path_params["kind"],
            req.path_params["name"],
            _seg_ns(req.path_params["ns"]),
        )
        return json_response({"deleted": True})

    def pod_log(self, req: Request) -> Response:
        import pathlib

        if self.log_root is None:
            raise HttpError(
                404, "log serving not configured (no capture directory)"
            )
        pod = self.api.get(
            "Pod", req.path_params["name"], _seg_ns(req.path_params["ns"])
        )
        log_path = pod.status.get("logPath")
        if not log_path:
            raise HttpError(
                404,
                f"pod {pod.metadata.name!r} has no captured logs (the "
                "local runtime publishes status.logPath when capture is "
                "on)",
            )
        path = pathlib.Path(log_path).resolve()
        # status is client-writable: refuse anything outside the capture
        # root (resolve() collapses ../ and symlinks first).
        if not path.is_relative_to(self.log_root):
            raise HttpError(
                404, f"log path for {pod.metadata.name!r} is outside the "
                "capture directory",
            )
        if not path.is_file():
            raise HttpError(404, f"log file {log_path!r} is gone")
        return Response(path.read_bytes(), content_type="text/plain")


class HttpApiClient:
    """Remote twin of FakeApiServer's CRUD + watch surface.

    `watch()` makes this a real informer client: one multiplexed
    long-poll stream feeds every registered handler, resuming from the
    last seen resourceVersion across reconnects and recovering from 410
    Gone via list-then-rewatch (synthetic MODIFIED events). A
    `controllers/runtime.Controller` built over this client is therefore
    event-driven across the process boundary — zero list polling."""

    def __init__(
        self,
        base_url: str,
        timeout: float = 10.0,
        watch_poll_timeout: float = 5.0,
        watch_retry: float = 0.5,
    ):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.watch_poll_timeout = watch_poll_timeout
        self.watch_retry = watch_retry
        self._watchers: list[tuple[str | None, WatchHandler]] = []
        self._watch_lock = threading.Lock()
        self._watch_thread: threading.Thread | None = None
        self._closed = threading.Event()

    def _call(self, method: str, path: str, body: dict | None = None) -> dict:
        req = urllib.request.Request(
            self.base_url + path,
            method=method,
            data=json.dumps(body).encode() if body is not None else None,
            # An active span's trace id rides along, so a reconcile's
            # apiserver calls land in the same trace (`utils.tracing`).
            headers={
                "Content-Type": "application/json",
                **tracing.trace_header(),
            },
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read())
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")
            if e.code == 404:
                raise NotFound(detail)
            if e.code == 409:
                # The server folds AlreadyExists and Conflict onto 409;
                # disambiguate from the message.
                if "already exists" in detail:
                    raise AlreadyExists(detail)
                raise Conflict(detail)
            if e.code == 410:
                raise Gone(detail)
            if e.code == 422:
                raise Invalid(detail)
            raise

    def get(
        self,
        kind: str,
        name: str,
        namespace: str = "default",
        version: str | None = None,
    ) -> Resource:
        query = f"?{urllib.parse.urlencode({'version': version})}" if version else ""
        return Resource.from_dict(
            self._call("GET", f"/apis/{kind}/{_ns_seg(namespace)}/{name}{query}")
        )

    def list(
        self,
        kind: str,
        namespace: str | None = None,
        label_selector: dict[str, str] | None = None,
        version: str | None = None,
    ) -> list[Resource]:
        params = {}
        if version:
            params["version"] = version
        if namespace is not None:
            params["namespace"] = _ns_seg(namespace)
        if label_selector:
            params["labelSelector"] = ",".join(
                f"{k}={v}" for k, v in label_selector.items()
            )
        query = f"?{urllib.parse.urlencode(params)}" if params else ""
        data = self._call("GET", f"/apis/{kind}{query}")
        return [Resource.from_dict(d) for d in data["items"]]

    def create(self, obj: Resource) -> Resource:
        return Resource.from_dict(
            self._call("POST", f"/apis/{obj.kind}", obj.to_dict())
        )

    def update(self, obj: Resource) -> Resource:
        return Resource.from_dict(
            self._call(
                "PUT",
                f"/apis/{obj.kind}/{_ns_seg(obj.metadata.namespace)}/"
                f"{obj.metadata.name}",
                obj.to_dict(),
            )
        )

    def update_status(self, obj: Resource) -> Resource:
        return Resource.from_dict(
            self._call(
                "PUT",
                f"/apis/{obj.kind}/{_ns_seg(obj.metadata.namespace)}/"
                f"{obj.metadata.name}/status",
                obj.to_dict(),
            )
        )

    def delete(self, kind: str, name: str, namespace: str = "default") -> None:
        self._call("DELETE", f"/apis/{kind}/{_ns_seg(namespace)}/{name}")

    def pod_log(self, name: str, namespace: str = "default") -> str:
        """The pod's captured stdout (raw text; same tracing header and
        error mapping as every other call)."""
        req = urllib.request.Request(
            f"{self.base_url}/apis/Pod/{_ns_seg(namespace)}/{name}/log",
            headers=tracing.trace_header(),
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return resp.read().decode(errors="replace")
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")
            try:
                detail = json.loads(detail).get("log", detail)
            except ValueError:
                pass
            if e.code == 404:
                raise NotFound(detail)
            raise

    def apply(self, obj: Resource) -> Resource:
        """Create-or-update, evaluated server-side (the store's compare is
        post-admission/post-conversion, so a remote reconciler's apply
        no-ops exactly when an in-process one would)."""
        return Resource.from_dict(
            self._call("POST", f"/apis/{obj.kind}?apply=true", obj.to_dict())
        )

    def record_event(
        self,
        about: Resource,
        reason: str,
        message: str,
        *,
        type_: str = "Normal",
    ) -> Resource:
        """Same Event shape FakeApiServer.record_event emits
        (`notebook_controller.go:87-103` event mirroring works unchanged
        from a remote controller)."""
        ev = Resource(
            kind="Event",
            metadata=ObjectMeta(
                name=f"{about.metadata.name}.{fresh_uid()[:8]}",
                namespace=about.metadata.namespace,
            ),
            spec={
                "involvedObject": {
                    "kind": about.kind,
                    "name": about.metadata.name,
                    "uid": about.metadata.uid,
                },
                "reason": reason,
                "message": message,
                "type": type_,
            },
            status={},
        )
        return self.create(ev)

    # -- watch (informer client) ------------------------------------------

    def watch(self, handler: WatchHandler, kind: str | None = None) -> None:
        """Register a handler; the first registration starts the stream.
        Initial sync delivers every existing object of each concretely
        watched kind as a synthetic MODIFIED (list-then-watch), so a
        controller starting late still reconciles pre-existing objects."""
        with self._watch_lock:
            self._watchers.append((kind, handler))
            started = self._watch_thread is None
            if started:
                self._watch_thread = threading.Thread(
                    target=self._watch_loop,
                    name="apiclient-watch",
                    daemon=True,
                )
                self._watch_thread.start()
        if not started and kind is not None:
            # Late registration: the running stream's bookmark may already
            # be past this kind's existing objects, and the initial resync
            # never listed it. Deliver current state now — possibly
            # duplicating a concurrent stream delivery, which level-
            # triggered consumers tolerate by construction.
            try:
                data = self._call("GET", f"/apis/{kind}")
                for item in data["items"]:
                    self._dispatch("MODIFIED", Resource.from_dict(item))
            except Exception:
                log.debug(
                    "late-registration sync for %s failed", kind,
                    exc_info=True,
                )

    def close(self) -> None:
        self._closed.set()

    def _dispatch(self, event: str, obj: Resource) -> None:
        for kind, handler in list(self._watchers):
            if kind is None or kind == obj.kind:
                try:
                    handler(event, obj)
                except Exception:
                    log.exception("watch handler failed for %s %s",
                                  event, obj.key)

    def _resync(self) -> int:
        """List every concretely watched kind, delivering synthetic
        MODIFIED events; returns the rv to watch from. The bookmark is the
        FIRST list's rv, so anything committed mid-resync is re-delivered
        by the subsequent watch — at-least-once, which level-triggered
        reconcilers tolerate by construction."""
        with self._watch_lock:
            kinds = {k for k, _ in self._watchers if k is not None}
        rv: int | None = None
        for kind in sorted(kinds):
            data = self._call("GET", f"/apis/{kind}")
            if rv is None:
                rv = data.get("resourceVersion", 0)
            for item in data["items"]:
                self._dispatch("MODIFIED", Resource.from_dict(item))
        return rv if rv is not None else 0

    def _watch_loop(self) -> None:
        rv = None
        while not self._closed.is_set():
            try:
                if rv is None:
                    rv = self._resync()
                params = urllib.parse.urlencode(
                    {
                        "watch": "true",
                        "resourceVersion": rv,
                        "timeoutSeconds": self.watch_poll_timeout,
                    }
                )
                data = self._call("GET", f"/apis/_?{params}")
            except Gone:
                rv = None  # journal horizon passed us — relist
                continue
            except Exception:
                if self._closed.is_set():
                    return
                log.debug("watch stream error; retrying", exc_info=True)
                self._closed.wait(self.watch_retry)
                continue
            rv = data["resourceVersion"]
            for ev in data["events"]:
                self._dispatch(ev["type"], Resource.from_dict(ev["object"]))
