"""HTTP facade + client for the in-process API server.

The reference's sidecars and tests talk to the real K8s apiserver over
HTTP (`openmpi-controller/controller/util.py` uses the kubernetes client;
`testing/deploy_utils.py:31-71`). Our control plane stores resources in
`FakeApiServer`; this module serves that store over REST so *separate
processes* (sidecar CLI, e2e workers, probers) get the same boundary:

    GET    /apis/<kind>                      ?namespace=&labelSelector=k=v&version=
    GET    /apis/<kind>?watch=true           &resourceVersion=N&timeoutSeconds=S
    GET    /apis/<kind>/<ns>/<name>          ('_' namespace = cluster scope; ?version=)
    POST   /apis/<kind>                      (?apply=true → create-or-update)
    PUT    /apis/<kind>/<ns>/<name>[/status]
    DELETE /apis/<kind>/<ns>/<name>

Multi-version kinds: POST/PUT bodies may carry any served apiVersion
(storage normalizes to the hub version); GETs pass `?version=` to read at
a specific served version.

Watch semantics match the real apiserver's (the reference's controllers
are watch-driven across process boundaries — controller-runtime's
`SetupWithManager`, `notebook_controller.go:516`): a long-poll returns
events with rv > resourceVersion plus the rv to resume from; a bookmark
older than the journal horizon gets 410 Gone, and the client recovers the
way an informer does (re-list, deliver synthetic events, re-watch).

`HttpApiClient` mirrors the FakeApiServer method surface (get/list/create/
update/update_status/delete/apply/record_event/watch) so controller-side
code — including `controllers/runtime.Controller` — is client-agnostic:
the same reconciler binary runs in-process against the store or in a
separate process against this facade.
"""

from __future__ import annotations

import json
import urllib.parse

import logging
import threading

import os

from kubeflow_tpu.api.objects import ObjectMeta, Resource, fresh_uid
from kubeflow_tpu.api.rbac import resource_for_kind, subject_access_review
from kubeflow_tpu.api.tokens import TokenRegistry
from kubeflow_tpu.utils import tracing
from kubeflow_tpu.testing.fake_apiserver import (
    AlreadyExists,
    ApiError,
    Conflict,
    FakeApiServer,
    Forbidden,
    Gone,
    Invalid,
    NotFound,
    Unavailable,
    WatchHandler,
)

log = logging.getLogger(__name__)
from kubeflow_tpu.web.wsgi import (
    App,
    HttpError,
    Request,
    Response,
    StreamResponse,
    json_response,
)


def _ns_seg(namespace: str) -> str:
    return namespace or "_"


def _seg_ns(seg: str) -> str:
    return "" if seg == "_" else seg


class ApiServerApp(App):
    """REST facade.

    With `tokens`, every request must carry `Authorization: Bearer
    <token>` naming a registered identity, and every operation is gated
    by a SubjectAccessReview over the stored RBAC objects — the trust
    model the reference runs under (controllers authenticate with pod
    serviceaccount tokens, `notebook_controller.go:516` manager config;
    web backends SAR every request, `crud_backend/authz.py:46-80`; even
    /metrics sits behind kube-rbac-proxy,
    `notebook-controller/config/default/manager_auth_proxy_patch.yaml`).
    Status is a distinct RBAC subresource (`<resource>/status`), so only
    the owning runtime identity can be granted status writes.

    Without `tokens` the facade is open — the in-process test seam only
    (the kube-apiserver insecure-localhost-port analog); the platform
    launcher and e2e harnesses always pass a registry."""

    def __init__(
        self,
        api: FakeApiServer,
        log_root: str | None = None,
        tokens: TokenRegistry | None = None,
    ):
        super().__init__("apiserver")
        self.api = api
        self.tokens = tokens
        if tokens is not None:
            self.before_request(self._authenticate)
        # Containment root for /log: only files under the runner's
        # capture dir are served. status is client-writable, so serving
        # status.logPath unconstrained would be an arbitrary-file-read
        # primitive. None disables log serving entirely.
        import pathlib

        self.log_root = (
            pathlib.Path(log_root).resolve() if log_root else None
        )
        self.add_route("/apis/<kind>", self.list_kind)
        self.add_route("/apis/<kind>", self.create, ("POST",))
        self.add_route("/apis/<kind>/<ns>/<name>", self.get)
        self.add_route("/apis/<kind>/<ns>/<name>", self.update, ("PUT",))
        self.add_route("/apis/<kind>/<ns>/<name>", self.delete, ("DELETE",))
        self.add_route(
            "/apis/<kind>/<ns>/<name>/status", self.update_status, ("PUT",)
        )
        # kubelet log-endpoint analog: serves the pod's captured stdout
        # (LocalPodRunner publishes status.logPath). Pod-only.
        self.add_route("/apis/Pod/<ns>/<name>/log", self.pod_log)
        # In-process trace collector drain (the platform's jaeger-query
        # stand-in): returns and clears all finished spans.
        self.add_route("/debug/traces", self.drain_traces)

    # -- authn/authz -------------------------------------------------------

    def _authenticate(self, req: Request) -> Response | None:
        """Before-request hook (secure mode): resolve the bearer token to
        an identity or 401. /healthz stays open for probes."""
        if req.path == "/healthz":
            return None
        header = req.headers.get("authorization", "")
        scheme, _, token = header.partition(" ")
        user = (
            self.tokens.authenticate(token.strip())
            if scheme.lower() == "bearer" and token.strip()
            else None
        )
        if user is None:
            from kubeflow_tpu.web.wsgi import error_response

            return error_response(
                401,
                "no valid bearer token (secure facade: every request "
                "needs 'Authorization: Bearer <token>')",
            )
        req.user = user
        return None

    def _authorize(
        self, req: Request, verb: str, resource: str, namespace: str
    ) -> None:
        """SAR gate for one operation; no-op in open mode. 403 carries the
        crud_backend-style readable denial (`authz.py:46-80`)."""
        if self.tokens is None:
            return
        if not subject_access_review(
            self.api, req.user, verb, resource, namespace
        ):
            scope = (
                f"in namespace {namespace!r}" if namespace else "cluster-wide"
            )
            raise HttpError(
                403,
                f"user {req.user!r} is not allowed to {verb} {resource} "
                f"{scope}",
            )

    def _lease_guard(self, req: Request):
        """Optional write fencing: a leader-elected client arms its
        lease guard and every write carries it in this header; the store
        verifies holder+generation atomically with the commit
        (`fake_apiserver._check_lease_guard`). Correctness fencing
        against deposed leaders, not an authz boundary — RBAC already
        gated the write above."""
        raw = req.headers.get("x-kftpu-lease-guard")
        if not raw:
            return None
        try:
            ns, name, holder, transitions = json.loads(raw)
            return (str(ns), str(name), str(holder), int(transitions))
        except (ValueError, TypeError) as e:
            raise HttpError(
                400, f"malformed X-Kftpu-Lease-Guard header: {e}"
            )

    def _may_watch(self, user: str, obj: Resource, cache: dict) -> bool:
        """Per-event watch filter for the multiplexed `_` stream: deliver
        only objects whose (kind, namespace) the identity may watch, so a
        least-privilege controller can hold one stream without cluster-wide
        read (the apiserver's per-resource watch authorization, folded
        into our single-stream transport)."""
        key = (obj.kind, obj.metadata.namespace or "")
        if key not in cache:
            cache[key] = subject_access_review(
                self.api, user, "watch", resource_for_kind(obj.kind), key[1]
            )
        return cache[key]

    def drain_traces(self, req: Request) -> Response:
        from kubeflow_tpu.utils import tracing

        # Draining is destructive (export clears the buffer): gate it
        # behind the write verb so a view-bound identity can't wipe the
        # shared tracer.
        self._authorize(req, "delete", "traces", "")
        return json_response(
            {
                "spans": tracing.tracer.export(),
                "dropped": tracing.tracer.dropped,
            }
        )

    def list_kind(self, req: Request) -> Response:
        if req.query.get("watch") in ("true", "1"):
            return self._watch(req)
        selector = None
        if "labelSelector" in req.query:
            selector = dict(
                part.split("=", 1)
                for part in req.query["labelSelector"].split(",")
                if "=" in part
            )
        namespace = req.query.get("namespace")
        self._authorize(
            req,
            "list",
            resource_for_kind(req.path_params["kind"]),
            _seg_ns(namespace) if namespace is not None else "",
        )
        # The list's rv is the watch bookmark (informer list-then-watch).
        # Read it BEFORE listing: an object committed between the two
        # reads is then re-delivered by the watch (at-least-once), whereas
        # rv-after-list would place it behind the bookmark and lose it.
        rv = self.api.current_rv
        items = self.api.list(
            req.path_params["kind"],
            namespace=_seg_ns(namespace) if namespace is not None else None,
            label_selector=selector,
        )
        items = [self._at_version(r, req) for r in items]
        return json_response(
            {
                "items": [r.to_dict() for r in items],
                "resourceVersion": rv,
            }
        )

    def _watch(self, req: Request) -> Response:
        """Watch transport, two forms.

        Long-poll (default): block until events land past the bookmark
        (or timeoutSeconds), return them with the rv to resume from.
        `_` as the kind watches everything (the client multiplexes one
        stream across all its registered handlers).

        Streaming (`stream=true`): ONE chunked HTTP response held open
        across events — each line is a JSON event, with BOOKMARK lines
        marking quiet progress (heartbeat + rv advance) and an ERROR
        line carrying the would-be HTTP status (410 journal horizon,
        503 fail-stop) before the stream ends. This is the client-go
        informer transport (`notebook_controller.go:516` watches ride
        one shared connection): event latency is delivery latency, not
        poll cadence, and a keep-alive client re-uses the connection's
        single TLS handshake for the whole stream."""
        try:
            since = int(req.query.get("resourceVersion", "0"))
        except ValueError:
            raise HttpError(400, "resourceVersion must be an integer")
        kind = req.path_params["kind"]
        namespace = req.query.get("namespace")
        if kind != "_":
            # Concrete-kind stream: authorize eagerly (403 beats silently
            # delivering nothing). The `_` stream filters per event below.
            self._authorize(
                req,
                "watch",
                resource_for_kind(kind),
                _seg_ns(namespace) if namespace is not None else "",
            )
        if req.query.get("stream") in ("true", "1"):
            return self._watch_stream(req, since, kind, namespace)
        timeout = min(float(req.query.get("timeoutSeconds", "10")), 60.0)
        try:
            events, rv = self.api.wait_events(
                since,
                kind=None if kind == "_" else kind,
                namespace=_seg_ns(namespace) if namespace is not None else None,
                timeout=timeout,
            )
        except Gone as e:
            raise HttpError(410, str(e))
        events = self._filter_watchable(req, kind, events)
        return json_response(
            {
                "events": [
                    {"type": ev, "rv": ev_rv, "object": obj.to_dict()}
                    for ev_rv, ev, obj in events
                ],
                "resourceVersion": rv,
            }
        )

    def _filter_watchable(self, req: Request, kind: str, events):
        """Per-event SAR filter for the multiplexed `_` stream."""
        if self.tokens is None or kind != "_":
            return events
        cache: dict = {}
        return [
            (ev_rv, ev, obj)
            for ev_rv, ev, obj in events
            if self._may_watch(req.user, obj, cache)
        ]

    # How long one streaming response lives before the server ends it
    # cleanly (the kube-apiserver min-request-timeout analog): bounds a
    # dead client's grip on its thread; a live client just re-opens on
    # its pooled (already-handshaken) connection.
    STREAM_DURATION = 240.0
    # Bookmark cadence: each quiet slice emits a BOOKMARK line, serving
    # as heartbeat (the peer detects a dead server in seconds) and rv
    # advance (a resume after disconnect skips the drained history).
    STREAM_SLICE = 5.0

    def _watch_stream(
        self, req: Request, since: int, kind: str, namespace: str | None
    ) -> StreamResponse:
        import json as _json

        duration = min(
            float(req.query.get("timeoutSeconds", self.STREAM_DURATION)),
            3600.0,
        )

        def line(payload: dict) -> bytes:
            return _json.dumps(payload, separators=(",", ":")).encode() + b"\n"

        def gen():
            # Exceptions here happen AFTER App.handle returned (the
            # handler thread is mid-chunked-response), so the error
            # mapping rides the stream as an ERROR line instead of an
            # HTTP status.
            import time as _time

            rv = since
            deadline = _time.monotonic() + duration
            while True:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    return  # clean end; client resumes from its rv
                try:
                    events, new_rv = self.api.wait_events(
                        rv,
                        kind=None if kind == "_" else kind,
                        namespace=(
                            _seg_ns(namespace) if namespace is not None
                            else None
                        ),
                        timeout=min(self.STREAM_SLICE, remaining),
                    )
                except Gone as e:
                    yield line(
                        {"type": "ERROR", "status": 410, "message": str(e)}
                    )
                    return
                except Exception as e:  # Unavailable, shutdown races
                    yield line(
                        {"type": "ERROR", "status": 503, "message": str(e)}
                    )
                    return
                for ev_rv, ev, obj in self._filter_watchable(
                    req, kind, events
                ):
                    yield line(
                        {"type": ev, "rv": ev_rv, "object": obj.to_dict()}
                    )
                rv = new_rv
                yield line({"type": "BOOKMARK", "resourceVersion": rv})

        return StreamResponse(gen(), content_type="application/json")

    def _at_version(self, obj: Resource, req: Request) -> Resource:
        version = req.query.get("version")
        if not version:
            return obj
        # Invalid propagates: wsgi maps it to 422 and HttpApiClient maps
        # 422 back to Invalid, so both clients surface the same error.
        return self.api.convert_to(obj, version)

    def get(self, req: Request) -> Response:
        self._authorize(
            req,
            "get",
            resource_for_kind(req.path_params["kind"]),
            _seg_ns(req.path_params["ns"]),
        )
        obj = self.api.get(
            req.path_params["kind"],
            req.path_params["name"],
            _seg_ns(req.path_params["ns"]),
        )
        return json_response(self._at_version(obj, req).to_dict())

    def create(self, req: Request) -> Response:
        obj = Resource.from_dict(req.json())
        if obj.kind != req.path_params["kind"]:
            raise HttpError(400, "kind mismatch between path and body")
        resource = resource_for_kind(obj.kind)
        namespace = obj.metadata.namespace or ""
        if self.tokens is not None and obj.status:
            # Status-subresource integrity on create: a body arriving with
            # status would otherwise persist it (the store honors it;
            # update() already doesn't), letting a create-only identity
            # forge e.g. phase=Succeeded. Like the real apiserver we drop
            # it — unless the identity holds the status grant anyway, so
            # runtimes that materialize already-Running objects (the
            # WorkloadMaterializer pattern) keep working remotely.
            if not subject_access_review(
                self.api, req.user, "update", resource + "/status", namespace
            ):
                obj.status = {}
        if req.query.get("apply") in ("true", "1"):
            # Server-side apply is create-or-update: the identity needs
            # both (the reference's SSA patch demands `patch`; our edit
            # role carries create+update+patch together).
            self._authorize(req, "create", resource, namespace)
            self._authorize(req, "update", resource, namespace)
            # Server-side apply: create-or-update with the store's own
            # no-op detection (post-admission, post-conversion compare) so
            # remote reconcilers don't re-trigger their own watches.
            return json_response(
                self.api.apply(
                    obj, lease_guard=self._lease_guard(req)
                ).to_dict()
            )
        self._authorize(req, "create", resource, namespace)
        return json_response(
            self.api.create(
                obj, lease_guard=self._lease_guard(req)
            ).to_dict(),
            status=201,
        )

    def _body_matching_path(self, req: Request) -> Resource:
        """The path is authoritative: a body naming a different object than
        the REST path is a client bug, not a write to the named object."""
        obj = Resource.from_dict(req.json())
        if (
            obj.kind != req.path_params["kind"]
            or obj.metadata.name != req.path_params["name"]
            or (obj.metadata.namespace or "") != (_seg_ns(req.path_params["ns"]) or "")
        ):
            raise HttpError(400, "kind/namespace/name mismatch between path and body")
        return obj

    def update(self, req: Request) -> Response:
        self._authorize(
            req,
            "update",
            resource_for_kind(req.path_params["kind"]),
            _seg_ns(req.path_params["ns"]),
        )
        return json_response(
            self.api.update(
                self._body_matching_path(req),
                lease_guard=self._lease_guard(req),
            ).to_dict()
        )

    def update_status(self, req: Request) -> Response:
        # Distinct subresource: granting `tpujobs` update does NOT grant
        # `tpujobs/status` — only the owning runtime identity's role
        # carries the status rule (the reference's controllers get
        # `.../status` verbs in their RBAC manifests; web apps never do).
        self._authorize(
            req,
            "update",
            resource_for_kind(req.path_params["kind"]) + "/status",
            _seg_ns(req.path_params["ns"]),
        )
        return json_response(
            self.api.update_status(
                self._body_matching_path(req),
                lease_guard=self._lease_guard(req),
            ).to_dict()
        )

    def delete(self, req: Request) -> Response:
        self._authorize(
            req,
            "delete",
            resource_for_kind(req.path_params["kind"]),
            _seg_ns(req.path_params["ns"]),
        )
        self.api.delete(
            req.path_params["kind"],
            req.path_params["name"],
            _seg_ns(req.path_params["ns"]),
            lease_guard=self._lease_guard(req),
        )
        return json_response({"deleted": True})

    def pod_log(self, req: Request) -> Response:
        import pathlib

        # The kubelet log endpoint's RBAC resource (`pods/log`, verb get).
        self._authorize(
            req, "get", "pods/log", _seg_ns(req.path_params["ns"])
        )
        if self.log_root is None:
            raise HttpError(
                404, "log serving not configured (no capture directory)"
            )
        pod = self.api.get(
            "Pod", req.path_params["name"], _seg_ns(req.path_params["ns"])
        )
        log_path = pod.status.get("logPath")
        if not log_path:
            raise HttpError(
                404,
                f"pod {pod.metadata.name!r} has no captured logs (the "
                "local runtime publishes status.logPath when capture is "
                "on)",
            )
        path = pathlib.Path(log_path).resolve()
        # status is client-writable: refuse anything outside the capture
        # root (resolve() collapses ../ and symlinks first).
        if not path.is_relative_to(self.log_root):
            raise HttpError(
                404, f"log path for {pod.metadata.name!r} is outside the "
                "capture directory",
            )
        if not path.is_file():
            raise HttpError(404, f"log file {log_path!r} is gone")
        return Response(path.read_bytes(), content_type="text/plain")


class HttpApiClient:
    """Remote twin of FakeApiServer's CRUD + watch surface.

    `watch()` makes this a real informer client: one multiplexed
    long-poll stream feeds every registered handler, resuming from the
    last seen resourceVersion across reconnects and recovering from 410
    Gone via list-then-rewatch (synthetic MODIFIED events). A
    `controllers/runtime.Controller` built over this client is therefore
    event-driven across the process boundary — zero list polling."""

    def __init__(
        self,
        base_url: str,
        timeout: float = 10.0,
        watch_poll_timeout: float = 5.0,
        watch_retry: float = 0.5,
        token: str | None = None,
        ca: str | None = None,
        allow_plaintext_token: bool | None = None,
    ):
        self.base_url = base_url.rstrip("/")
        # The identity credential (serviceaccount-token analog). Falls
        # back to KFTPU_TOKEN so gang workers spawned with the launcher
        # env contract inherit their pod's credential without plumbing.
        self.token = token if token is not None else os.environ.get(
            "KFTPU_TOKEN"
        )
        # TLS: pin the platform CA (env fallback KFTPU_CA rides the same
        # launcher env contract as the token). Verification is against
        # the pinned CA only — never the system trust store.
        ca = ca if ca is not None else os.environ.get("KFTPU_CA")
        self._ssl = None
        if self.base_url.startswith("https:"):
            from kubeflow_tpu.web import tls as tlsmod

            if ca:
                self._ssl = tlsmod.client_context(ca)
            elif os.environ.get("KFTPU_SYSTEM_TRUST") == "1":
                # Publicly-signed deployments opt into the system trust
                # store explicitly.
                import ssl as _ssl

                self._ssl = _ssl.create_default_context()
            else:
                # The platform CA is self-signed: without the pin every
                # request would die later with an opaque
                # CERTIFICATE_VERIFY_FAILED. Fail actionably, now.
                raise ValueError(
                    f"https server {self.base_url!r} needs the platform "
                    "CA pinned (ca=/--ca/KFTPU_CA; the launcher prints "
                    "the path at boot), or KFTPU_SYSTEM_TRUST=1 for a "
                    "publicly-signed endpoint"
                )
        elif self.token:
            # A bearer token over cleartext is a leaked credential, not a
            # working config: refuse unless the caller explicitly opts
            # in (loopback-only test rigs; KFTPU_ALLOW_PLAINTEXT=1 for
            # spawned workers). Secure-by-default, like the serving side.
            if allow_plaintext_token is None:
                allow_plaintext_token = os.environ.get(
                    "KFTPU_ALLOW_PLAINTEXT"
                ) == "1"
            if not allow_plaintext_token:
                raise ValueError(
                    f"refusing to send a bearer token over plaintext "
                    f"{self.base_url!r} — use https:// (pin the CA via "
                    f"ca=/KFTPU_CA) or pass allow_plaintext_token=True / "
                    f"KFTPU_ALLOW_PLAINTEXT=1 for a trusted loopback"
                )
        self.timeout = timeout
        self.watch_poll_timeout = watch_poll_timeout
        self.watch_retry = watch_retry
        self._watchers: list[tuple[str | None, WatchHandler]] = []
        self._watch_lock = threading.Lock()
        self._watch_thread: threading.Thread | None = None
        self._closed = threading.Event()
        # Persistent-connection pool (the client-go shared-transport
        # analog): requests ride keep-alive connections, so a client
        # pays O(1) TCP+TLS handshakes for its whole request train
        # instead of one per request. `handshakes` counts connections
        # dialed — the load test pins it flat while requests grow.
        parts = urllib.parse.urlsplit(self.base_url)
        self._conn_host = parts.hostname or "127.0.0.1"
        self._conn_port = parts.port or (
            443 if parts.scheme == "https" else 80
        )
        self._conn_https = parts.scheme == "https"
        self._pool: list = []
        self._pool_lock = threading.Lock()
        self.handshakes = 0
        # Leader-election write fencing: when armed (set_lease_guard),
        # every write carries the guard and the server rejects it with
        # Conflict unless the lease still shows this holder+generation.
        self.lease_guard: tuple[str, str, str, int] | None = None

    def set_lease_guard(
        self, guard: tuple[str, str, str, int] | None
    ) -> None:
        """Arm (or disarm with None) the lease guard on all writes. Pass
        `LeaderElector.guard` after acquiring leadership — from then on a
        partition that deposes this leader turns its in-flight writes
        into Conflicts instead of corruption of the successor's term."""
        self.lease_guard = guard

    # How many idle connections to keep (a controller process typically
    # runs one watch stream + a few concurrent reconcile threads).
    POOL_SIZE = 4

    def _new_conn(self):
        import http.client as _hc

        if self._conn_https:
            conn = _hc.HTTPSConnection(
                self._conn_host,
                self._conn_port,
                timeout=self.timeout,
                context=self._ssl,
            )
        else:
            conn = _hc.HTTPConnection(
                self._conn_host, self._conn_port, timeout=self.timeout
            )
        conn._kftpu_reused = False
        with self._pool_lock:
            self.handshakes += 1
        return conn

    # Discard pooled connections idle longer than this (below the
    # server's 75 s keep-alive reap, so the client almost never races a
    # server-side close — the stale-connection window that would
    # otherwise force ambiguous write retries).
    POOL_IDLE_MAX = 60.0

    def _get_conn(self):
        import time as _time

        now = _time.monotonic()
        with self._pool_lock:
            while self._pool:
                conn = self._pool.pop()
                if now - getattr(conn, "_kftpu_idle_since", now) \
                        <= self.POOL_IDLE_MAX:
                    return conn
                conn.close()  # probably server-reaped already
        return self._new_conn()

    def _put_conn(self, conn) -> None:
        import time as _time

        conn._kftpu_reused = True
        conn._kftpu_idle_since = _time.monotonic()
        # Restore the default op timeout (a stream may have raised it).
        if conn.sock is not None:
            conn.sock.settimeout(self.timeout)
        with self._pool_lock:
            if len(self._pool) < self.POOL_SIZE:
                self._pool.append(conn)
                return
        conn.close()

    def _request_raw(
        self, method: str, path: str, body: dict | None = None
    ):
        """One round trip on a pooled connection; returns (conn, resp)
        with the response UNREAD (callers stream or slurp).

        Retry policy (the urllib3 rule): only IDEMPOTENT-safe requests
        (GET) auto-retry when a REUSED connection dies — for a write,
        the failure is ambiguous (the server may have committed before
        the connection broke) and a blind replay could double-apply, so
        writes propagate the error and the caller's level-triggered
        retry re-reads state first. The stale-connection window writes
        would otherwise hit is mostly closed by POOL_IDLE_MAX reaping
        pooled connections before the server's keep-alive timeout can.
        A fresh-connection failure is real and always propagates."""
        import http.client as _hc

        data = json.dumps(body).encode() if body is not None else None
        headers = {
            "Content-Type": "application/json",
            # An active span's trace id rides along, so a reconcile's
            # apiserver calls land in the same trace (`utils.tracing`).
            **self._auth_header(),
            **tracing.trace_header(),
        }
        guard = self.lease_guard
        if guard is not None and method in ("POST", "PUT", "DELETE", "PATCH"):
            headers["X-Kftpu-Lease-Guard"] = json.dumps(list(guard))
        while True:
            conn = self._get_conn()
            try:
                conn.request(method, path, body=data, headers=headers)
                resp = conn.getresponse()
            except (_hc.HTTPException, OSError):
                reused = getattr(conn, "_kftpu_reused", False)
                conn.close()
                if reused and method == "GET":
                    continue  # stale keep-alive victim: one fresh retry
                raise
            return conn, resp

    def _finish(self, conn, resp) -> bytes:
        """Slurp the body and recycle (or retire) the connection."""
        try:
            data = resp.read()
        except Exception:
            conn.close()
            raise
        if resp.will_close:
            conn.close()
        else:
            self._put_conn(conn)
        return data

    @staticmethod
    def _raise_for_status(status: int, detail: str):
        if status in (401, 403):
            raise Forbidden(detail)
        if status == 404:
            raise NotFound(detail)
        if status == 409:
            # The server folds AlreadyExists and Conflict onto 409;
            # disambiguate from the message.
            if "already exists" in detail:
                raise AlreadyExists(detail)
            raise Conflict(detail)
        if status == 410:
            raise Gone(detail)
        if status == 422:
            raise Invalid(detail)
        if status == 503:
            raise Unavailable(detail)
        raise ApiError(f"HTTP {status}: {detail}")

    def _call(self, method: str, path: str, body: dict | None = None) -> dict:
        conn, resp = self._request_raw(method, path, body)
        status = resp.status
        data = self._finish(conn, resp)
        if status >= 400:
            self._raise_for_status(status, data.decode(errors="replace"))
        return json.loads(data)

    def get(
        self,
        kind: str,
        name: str,
        namespace: str = "default",
        version: str | None = None,
    ) -> Resource:
        query = f"?{urllib.parse.urlencode({'version': version})}" if version else ""
        return Resource.from_dict(
            self._call("GET", f"/apis/{kind}/{_ns_seg(namespace)}/{name}{query}")
        )

    def list(
        self,
        kind: str,
        namespace: str | None = None,
        label_selector: dict[str, str] | None = None,
        version: str | None = None,
    ) -> list[Resource]:
        params = {}
        if version:
            params["version"] = version
        if namespace is not None:
            params["namespace"] = _ns_seg(namespace)
        if label_selector:
            params["labelSelector"] = ",".join(
                f"{k}={v}" for k, v in label_selector.items()
            )
        query = f"?{urllib.parse.urlencode(params)}" if params else ""
        data = self._call("GET", f"/apis/{kind}{query}")
        return [Resource.from_dict(d) for d in data["items"]]

    def create(self, obj: Resource) -> Resource:
        return Resource.from_dict(
            self._call("POST", f"/apis/{obj.kind}", obj.to_dict())
        )

    def update(self, obj: Resource) -> Resource:
        return Resource.from_dict(
            self._call(
                "PUT",
                f"/apis/{obj.kind}/{_ns_seg(obj.metadata.namespace)}/"
                f"{obj.metadata.name}",
                obj.to_dict(),
            )
        )

    def update_status(self, obj: Resource) -> Resource:
        return Resource.from_dict(
            self._call(
                "PUT",
                f"/apis/{obj.kind}/{_ns_seg(obj.metadata.namespace)}/"
                f"{obj.metadata.name}/status",
                obj.to_dict(),
            )
        )

    def delete(self, kind: str, name: str, namespace: str = "default") -> None:
        self._call("DELETE", f"/apis/{kind}/{_ns_seg(namespace)}/{name}")

    def pod_log(self, name: str, namespace: str = "default") -> str:
        """The pod's captured stdout (raw text; same pooled transport and
        error mapping as every other call)."""
        conn, resp = self._request_raw(
            "GET", f"/apis/Pod/{_ns_seg(namespace)}/{name}/log"
        )
        status = resp.status
        data = self._finish(conn, resp)
        if status >= 400:
            detail = data.decode(errors="replace")
            try:
                detail = json.loads(detail).get("log", detail)
            except ValueError:
                pass
            self._raise_for_status(status, detail)
        return data.decode(errors="replace")

    def _auth_header(self) -> dict[str, str]:
        return (
            {"Authorization": f"Bearer {self.token}"} if self.token else {}
        )

    def apply(self, obj: Resource) -> Resource:
        """Create-or-update, evaluated server-side (the store's compare is
        post-admission/post-conversion, so a remote reconciler's apply
        no-ops exactly when an in-process one would)."""
        return Resource.from_dict(
            self._call("POST", f"/apis/{obj.kind}?apply=true", obj.to_dict())
        )

    def record_event(
        self,
        about: Resource,
        reason: str,
        message: str,
        *,
        type_: str = "Normal",
    ) -> Resource:
        """Same Event shape FakeApiServer.record_event emits
        (`notebook_controller.go:87-103` event mirroring works unchanged
        from a remote controller)."""
        ev = Resource(
            kind="Event",
            metadata=ObjectMeta(
                name=f"{about.metadata.name}.{fresh_uid()[:8]}",
                namespace=about.metadata.namespace,
            ),
            spec={
                "involvedObject": {
                    "kind": about.kind,
                    "name": about.metadata.name,
                    "uid": about.metadata.uid,
                },
                "reason": reason,
                "message": message,
                "type": type_,
            },
            status={},
        )
        return self.create(ev)

    # -- watch (informer client) ------------------------------------------

    def watch(self, handler: WatchHandler, kind: str | None = None) -> None:
        """Register a handler; the first registration starts the stream.
        Initial sync delivers every existing object of each concretely
        watched kind as a synthetic MODIFIED (list-then-watch), so a
        controller starting late still reconciles pre-existing objects."""
        with self._watch_lock:
            self._watchers.append((kind, handler))
            started = self._watch_thread is None
            if started:
                self._watch_thread = threading.Thread(
                    target=self._watch_loop,
                    name="apiclient-watch",
                    daemon=True,
                )
                self._watch_thread.start()
        if not started and kind is not None:
            # Late registration: the running stream's bookmark may already
            # be past this kind's existing objects, and the initial resync
            # never listed it. Deliver current state now — possibly
            # duplicating a concurrent stream delivery, which level-
            # triggered consumers tolerate by construction.
            try:
                data = self._call("GET", f"/apis/{kind}")
                for item in data["items"]:
                    self._dispatch("MODIFIED", Resource.from_dict(item))
            except Exception:
                log.debug(
                    "late-registration sync for %s failed", kind,
                    exc_info=True,
                )

    def close(self) -> None:
        self._closed.set()
        with self._pool_lock:
            pool, self._pool = self._pool, []
        for conn in pool:
            conn.close()

    def _dispatch(self, event: str, obj: Resource) -> None:
        for kind, handler in list(self._watchers):
            if kind is None or kind == obj.kind:
                try:
                    handler(event, obj)
                except Exception:
                    log.exception("watch handler failed for %s %s",
                                  event, obj.key)

    def _resync(self) -> int:
        """List every concretely watched kind, delivering synthetic
        MODIFIED events; returns the rv to watch from. The bookmark is the
        FIRST list's rv, so anything committed mid-resync is re-delivered
        by the subsequent watch — at-least-once, which level-triggered
        reconcilers tolerate by construction."""
        with self._watch_lock:
            kinds = {k for k, _ in self._watchers if k is not None}
        rv: int | None = None
        for kind in sorted(kinds):
            data = self._call("GET", f"/apis/{kind}")
            if rv is None:
                rv = data.get("resourceVersion", 0)
            for item in data["items"]:
                self._dispatch("MODIFIED", Resource.from_dict(item))
        return rv if rv is not None else 0

    def _watch_loop(self) -> None:
        rv = None
        # Prefer the chunked streaming watch (one held-open response,
        # event latency = delivery latency); fall back to long-polling
        # against servers that don't speak it. The fallback is sticky
        # per process — a server that 400s the stream form once won't
        # grow the capability mid-life.
        streaming = True
        while not self._closed.is_set():
            try:
                if rv is None:
                    rv = self._resync()
                if streaming:
                    try:
                        rv = self._stream_once(rv)
                        continue
                    except _StreamUnsupported:
                        streaming = False
                params = urllib.parse.urlencode(
                    {
                        "watch": "true",
                        "resourceVersion": rv,
                        "timeoutSeconds": self.watch_poll_timeout,
                    }
                )
                data = self._call("GET", f"/apis/_?{params}")
            except Gone:
                rv = None  # journal horizon passed us — relist
                continue
            except PermissionError as e:
                if self._closed.is_set():
                    return
                # Not a network blip: a missing/revoked/under-privileged
                # token will never heal by hot-retrying. Surface loudly
                # and back off hard (the operator may re-grant RBAC, so
                # the stream stays up rather than dying silently).
                log.error("watch stream unauthorized (%s); backing off", e)
                self._closed.wait(max(self.watch_retry, 5.0))
                continue
            except Exception:
                if self._closed.is_set():
                    return
                log.debug("watch stream error; retrying", exc_info=True)
                self._closed.wait(self.watch_retry)
                continue
            rv = data["resourceVersion"]
            for ev in data["events"]:
                self._dispatch(ev["type"], Resource.from_dict(ev["object"]))

    def _stream_once(self, rv: int) -> int:
        """Consume one streaming watch response; returns the rv to resume
        from after the server ends the stream cleanly (its duration cap).
        Events dispatch as their lines arrive — no poll quantization."""
        params = urllib.parse.urlencode(
            {"watch": "true", "stream": "true", "resourceVersion": rv}
        )
        conn, resp = self._request_raw("GET", f"/apis/_?{params}")
        if resp.status == 400:
            self._finish(conn, resp)
            raise _StreamUnsupported()
        if resp.status >= 400:
            status = resp.status
            detail = self._finish(conn, resp).decode(errors="replace")
            self._raise_for_status(status, detail)
        # Reads block until the next event/bookmark line; the server
        # bookmarks every STREAM_SLICE (5 s), so a healthy-but-quiet
        # stream produces a line well inside this read timeout — a
        # silent peer here is a dead one.
        if conn.sock is not None:
            conn.sock.settimeout(30.0)
        try:
            while not self._closed.is_set():
                line = resp.readline()
                if not line:
                    # Clean end of stream (terminal chunk consumed): the
                    # connection is reusable — the next stream/call rides
                    # the same handshake.
                    self._put_conn(conn)
                    return rv
                ev = json.loads(line)
                etype = ev["type"]
                if etype == "BOOKMARK":
                    rv = ev["resourceVersion"]
                elif etype == "ERROR":
                    if ev.get("status") == 410:
                        raise Gone(ev.get("message", "watch horizon"))
                    raise ApiError(
                        f"watch stream error {ev.get('status')}: "
                        f"{ev.get('message', '')}"
                    )
                else:
                    self._dispatch(etype, Resource.from_dict(ev["object"]))
                    rv = ev["rv"]
            conn.close()  # closed mid-stream: response state unusable
            return rv
        except BaseException:
            conn.close()
            raise


class _StreamUnsupported(Exception):
    """Server rejected `stream=true` (400): stick to long-polling."""
