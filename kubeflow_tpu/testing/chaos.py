"""Control-plane fault injection: a seeded chaos proxy.

`ChaosProxy` is a TCP proxy that sits between `HttpApiClient` and the
apiserver facade (either backend behind `ApiServerApp`) and injects
faults from a `FaultSchedule` — a finite, seeded plan, so any soak run
is reproducible from one integer. This is the Jepsen-style posture
(PAPERS.md: fault injection as a routine test input, crash-only
software): failure is not an accident the suite hopes to avoid but a
scheduled input the control plane must converge through.

Fault classes (the failure modes a controller actually meets between
itself and a real apiserver):

- ``error_5xx``         synthesized 503 burst — the apiserver is
                        briefly unavailable; the request never reached
                        it (retry is safe).
- ``reset_mid_response``the response dies partway — the request WAS
                        processed; only the answer is lost (ambiguous
                        for writes).
- ``stale_gone``        synthesized 410 on a watch — the journal
                        horizon passed the client's bookmark; it must
                        relist.
- ``slow_stream``       the streaming watch crawls (per-chunk delay)
                        before recovering — degraded network.
- ``truncate_stream``   the streaming watch is severed mid-body with no
                        terminal chunk — a dead LB / half-open TCP.
- ``delay_write``       a write is held before forwarding — reordering
                        pressure against optimistic concurrency.
- ``crash_before_ack``  a write is forwarded and COMMITTED upstream but
                        the connection dies before the ack — the
                        classic duplicate-side-effect trap.

Opt-in (``HA_FAULT_CLASSES``; needs a process to kill, so the driver
supplies the executor — ChaosProxy's ``kill_active`` callback, or the
failover soak consuming the schedule directly):

- ``apiserver_kill``    SIGKILL the ACTIVE apiserver facade mid-load;
                        the standby takes over (testing/failover.py)
                        and every client fails over on its endpoint
                        list — whole-control-plane death, the canonical
                        TPU-pod-scale failure (arXiv:2011.03641).

The schedule is a *plan*, not a rate: a `FaultSchedule(seed)` yields an
identical fault sequence every run (the soak asserts this), each entry
is consumed by the first eligible request that arrives, and `coverage()`
reports how many of each class actually fired — a soak that quietly
exercised nothing fails its own coverage gate.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import random
import socket
import struct
import threading
import time

log = logging.getLogger(__name__)

FAULT_CLASSES = (
    "error_5xx",
    "reset_mid_response",
    "stale_gone",
    "slow_stream",
    "truncate_stream",
    "delay_write",
    "crash_before_ack",
)

# Whole-control-plane death (arXiv:2011.03641's canonical failure mode):
# SIGKILL the ACTIVE apiserver facade mid-load and let the standby take
# over (testing/failover.py). Not in FAULT_CLASSES — it needs a process
# to kill, so only drivers that can supply one (ChaosProxy's
# `kill_active` callback, or the failover soak consuming the schedule
# directly) opt in via FaultSchedule(classes=HA_FAULT_CLASSES); the
# plain wire-proxy soak keeps its historical 7-class plan.
APISERVER_KILL = "apiserver_kill"
HA_FAULT_CLASSES = FAULT_CLASSES + (APISERVER_KILL,)

_WRITE_METHODS = ("POST", "PUT", "DELETE", "PATCH")


@dataclasses.dataclass(frozen=True)
class Fault:
    """One planned injection. `param` is class-specific (burst length,
    byte budget, delay seconds, body fraction); `gap` is how many
    eligible requests pass unfaulted afterwards, so the system gets
    breathing room to make progress between injections."""

    cls: str
    param: float
    gap: int


def _eligible(cls: str, method: str, path: str, query: str) -> bool:
    """Which requests a fault class may bind to. Streams and watches are
    identified by their query params (the facade's watch contract)."""
    watch = "watch=true" in query or "watch=1" in query
    stream = "stream=true" in query or "stream=1" in query
    if cls in ("slow_stream", "truncate_stream"):
        return stream
    if cls == "stale_gone":
        return watch
    if cls in ("delay_write", "crash_before_ack"):
        return method in _WRITE_METHODS
    if cls == "reset_mid_response":
        # Mid-body resets of a *stream* are truncate_stream's job.
        return not stream
    return True  # error_5xx / apiserver_kill: anything


class FaultSchedule:
    """A finite, seeded fault plan plus its runtime consumption state.

    Two schedules built from the same seed have identical `plan`s — the
    reproducibility contract the soak pins. The first round contains one
    entry of EVERY class (shuffled) so even a short soak can reach 100%
    class coverage; subsequent rounds are uniformly shuffled.
    """

    def __init__(
        self,
        seed: int,
        *,
        faults_per_class: int = 2,
        max_gap: int = 3,
        classes: tuple[str, ...] = FAULT_CLASSES,
    ):
        self.seed = seed
        self.classes = tuple(classes)
        rng = random.Random(seed)

        def mk(cls: str) -> Fault:
            if cls == "error_5xx":
                param = float(rng.randint(1, 3))  # burst length
            elif cls == "reset_mid_response":
                param = rng.uniform(0.2, 0.8)  # body fraction forwarded
            elif cls == "slow_stream":
                param = rng.uniform(0.02, 0.08)  # per-burst delay (s)
            elif cls == "truncate_stream":
                param = float(rng.randint(80, 400))  # bytes before cut
            elif cls == "delay_write":
                param = rng.uniform(0.05, 0.25)  # hold time (s)
            else:  # stale_gone, crash_before_ack, apiserver_kill
                param = 0.0
            return Fault(cls, param, rng.randint(1, max_gap))

        first = (
            [mk(c) for c in self.classes] if faults_per_class >= 1 else []
        )
        rng.shuffle(first)
        rest = [
            mk(c)
            for _ in range(max(0, faults_per_class - 1))
            for c in self.classes
        ]
        rng.shuffle(rest)
        self.plan: tuple[Fault, ...] = tuple(first + rest)
        self._pending: list[Fault] = list(self.plan)
        self._cooldown = 0
        self._inflight = 0
        self._injected: dict[str, int] = {c: 0 for c in self.classes}
        self._lock = threading.Lock()

    @classmethod
    def from_plan(cls, plan) -> "FaultSchedule":
        """A schedule with an explicit plan (targeted tests that need
        exactly one known fault, not a seeded mix)."""
        sched = cls(0, faults_per_class=0)
        sched.plan = tuple(plan)
        sched._pending = list(sched.plan)
        return sched

    def __repr__(self) -> str:  # shows up in assertion messages
        return (
            f"FaultSchedule(seed={self.seed}, planned={len(self.plan)}, "
            f"pending={len(self._pending)}, coverage={self.coverage()})"
        )

    def next_fault(self, method: str, path: str, query: str) -> Fault | None:
        """The fault (if any) to attempt on this request: the first
        pending plan entry the request is eligible for, rate-limited by
        the previous entry's gap. Thread-safe; consumption order across
        concurrent requests may vary, the plan itself never does.

        Consumption is NOT coverage: the proxy calls `mark_injected`
        only once the fault's effect actually lands, and `requeue` when
        it could not (a stream that ended before the truncation budget,
        an upstream that died first) — so `coverage()` never reports
        robustness the run didn't test."""
        with self._lock:
            if not self._pending:
                return None
            if self._cooldown > 0:
                self._cooldown -= 1
                return None
            for i, fault in enumerate(self._pending):
                if _eligible(fault.cls, method, path, query):
                    del self._pending[i]
                    self._cooldown = fault.gap
                    self._inflight += 1
                    return fault
            return None

    def mark_injected(self, fault: Fault) -> None:
        """The fault's effect happened on the wire."""
        with self._lock:
            # .get: from_plan() may stage classes outside this
            # schedule's seeded set (targeted tests).
            self._injected[fault.cls] = self._injected.get(fault.cls, 0) + 1
            self._inflight -= 1

    def requeue(self, fault: Fault) -> None:
        """The fault bound to a request it could not actually affect —
        put it back at the head so a later eligible request retries it."""
        with self._lock:
            self._pending.insert(0, fault)
            self._inflight -= 1

    def coverage(self) -> dict[str, int]:
        """Injections actually performed, per class. The soak's coverage
        gate: every class must be > 0 or the run proved nothing about
        that failure mode."""
        with self._lock:
            return dict(self._injected)

    @property
    def exhausted(self) -> bool:
        """Every plan entry has taken effect (none pending, none still
        bound to an in-flight request)."""
        with self._lock:
            return not self._pending and self._inflight == 0

    @property
    def remaining(self) -> int:
        with self._lock:
            return len(self._pending)


# ---------------------------------------------------------------------------
# Training fault plans (docs/resilience.md)
#
# The control-plane proxy above injects faults on the wire; training
# faults are injected against a real `fit()` run instead — the process,
# its checkpoints, and its data. Same discipline: a finite SEEDED plan,
# consumed by a driver that runs subprocess incarnations, with coverage
# accounting so a soak that quietly exercised nothing fails its gate.
# ---------------------------------------------------------------------------

TRAIN_FAULT_CLASSES = (
    # process faults — one crash boundary each
    "kill",                  # SIGKILL between steps: no warning, no save
    "sigterm",               # SIGTERM mid-step: fit must exit Preempted
                             # at the boundary after an emergency save
    # storage faults — applied between incarnations, against the newest
    # checkpoint (each exercises a distinct verification path)
    "truncate_checkpoint",   # torn write: a committed file loses its tail
    "corrupt_checkpoint",    # bit rot: same size, flipped bytes
    "corrupt_manifest",      # the verifier's own record is garbage
    # data faults — identical in the baseline run (part of the data)
    "loss_spike",            # a poison batch the AnomalyGuard must skip
)

# Elastic-resize fault classes (ISSUE 9): the soak variant where
# preemption is ABSORBED instead of fatal. A `preempt_shrink` is a real
# SIGTERM self-delivered at the scheduled position WITH a staged
# shrink-to-fit target (the scheduler's resize proposal): fit() must
# reshape the mesh at the boundary and keep training — the process
# never dies, so steps-lost-per-kill is ~0 instead of a save-interval's
# worth. `grow_back` is the unprompted return to full dp when capacity
# comes back. Built with `TrainFaultSchedule(..., elastic=True)`, which
# swaps the crash/storage classes for resize cycles (the no-death
# story) while keeping the loss spikes (the guard must compose with
# resize).
ELASTIC_FAULT_CLASSES = (
    "preempt_shrink",
    "grow_back",
    "loss_spike",
)

_PROCESS_CLASSES = ("kill", "sigterm")
_STORAGE_CLASSES = ("truncate_checkpoint", "corrupt_checkpoint", "corrupt_manifest")


@dataclasses.dataclass(frozen=True)
class TrainFault:
    """One planned training fault. `at_step` is the 0-based batch
    position it binds to (process/data faults; 0 for storage faults);
    `after_crash` is the 0-based crash-boundary index a storage fault is
    applied at, and `offset` which checkpoint it targets (0 = newest,
    1 = second-newest, ...) — faults stacked on one boundary get
    distinct offsets so each one's verification path is actually
    exercised by the newest-first fallback walk, not masked by a
    sibling fault on the same step. `dp` is the resize target of an
    elastic fault (preempt_shrink/grow_back; 0 otherwise)."""

    cls: str
    at_step: int = 0
    after_crash: int = 0
    offset: int = 0
    dp: int = 0


class TrainFaultSchedule:
    """A finite, seeded fault plan for a kill-and-resume soak.

    Pure function of (seed, total_steps, save_interval,
    faults_per_class): two schedules from the same arguments have
    identical plans — the reproducibility contract the soak pins, same
    as `FaultSchedule`. The plan always covers EVERY class:

    - `faults_per_class` kills and sigterms, placed at ascending step
      positions spaced >= 3*save_interval + 2 apart (and at least that
      far in), so every incarnation both finds >= 3 prior checkpoints
      (max_to_keep's worth) to fall back through and makes save
      progress before dying;
    - `faults_per_class` of each storage class, distributed round-robin
      over the crash boundaries with per-boundary distinct `offset`s
      (newest, second-newest, ...), so stacked faults damage DIFFERENT
      steps and the fallback walk meets every one;
    - `faults_per_class` loss spikes at positions the guard's EWMA has
      warmed up for, disjoint from the crash steps.

    ``elastic=True`` builds the RESIZE soak's plan instead (ISSUE 9):
    `faults_per_class` shrink->grow cycles — each a `preempt_shrink`
    (real SIGTERM + staged target ``dp_shrunk``) later undone by a
    `grow_back` to ``dp_full`` — plus the same loss spikes; the crash
    and storage classes are absent because the whole point is that the
    process never dies and the checkpoint directory is never the
    recovery path. Coverage accounting runs over
    `ELASTIC_FAULT_CLASSES`.
    """

    def __init__(
        self,
        seed: int,
        total_steps: int,
        *,
        save_interval: int,
        faults_per_class: int = 1,
        guard_warmup: int = 3,
        elastic: bool = False,
        dp_full: int = 2,
        dp_shrunk: int = 1,
    ):
        self.seed = seed
        self.total_steps = total_steps
        self.save_interval = save_interval
        self.elastic = elastic
        self._injected: dict[str, int] = {
            c: 0
            for c in (
                ELASTIC_FAULT_CLASSES if elastic else TRAIN_FAULT_CLASSES
            )
        }
        self._lock = threading.Lock()
        rng = random.Random(seed)

        if elastic:
            self._init_elastic(
                rng, total_steps, faults_per_class, guard_warmup,
                dp_full, dp_shrunk,
            )
            return

        self.resize_faults: tuple[TrainFault, ...] = ()
        k = faults_per_class
        spacing = 3 * save_interval + 2
        first = spacing
        last = total_steps - 2
        n_crashes = 2 * k
        if first + (n_crashes - 1) * spacing > last:
            raise ValueError(
                f"total_steps={total_steps} too small for {n_crashes} "
                f"crashes spaced {spacing} (save_interval={save_interval})"
            )
        # Ascending crash positions with guaranteed spacing: distribute
        # the slack between the minimum-spacing slots.
        slack = last - (first + (n_crashes - 1) * spacing)
        offsets = sorted(rng.randint(0, slack) for _ in range(n_crashes))
        steps = [first + i * spacing + offsets[i] for i in range(n_crashes)]
        kinds = [_PROCESS_CLASSES[i % 2] for i in range(n_crashes)]
        rng.shuffle(kinds)
        self.crash_faults: tuple[TrainFault, ...] = tuple(
            TrainFault(cls, at_step=s) for cls, s in zip(kinds, steps)
        )

        storage = [cls for cls in _STORAGE_CLASSES for _ in range(k)]
        rng.shuffle(storage)
        per_boundary: dict[int, int] = {}
        storage_faults = []
        for i, cls in enumerate(storage):
            boundary = i % n_crashes
            offset = per_boundary.get(boundary, 0)
            per_boundary[boundary] = offset + 1
            storage_faults.append(
                TrainFault(cls, after_crash=boundary, offset=offset)
            )
        self.storage_faults: tuple[TrainFault, ...] = tuple(storage_faults)

        crash_steps = {f.at_step for f in self.crash_faults}
        candidates = [
            s for s in range(max(guard_warmup + 2, 3), total_steps - 1)
            if s not in crash_steps
        ]
        spikes = sorted(rng.sample(candidates, k))
        self.spike_faults: tuple[TrainFault, ...] = tuple(
            TrainFault("loss_spike", at_step=s) for s in spikes
        )

        self.plan: tuple[TrainFault, ...] = (
            self.crash_faults + self.storage_faults + self.spike_faults
        )

    def _init_elastic(
        self, rng, total_steps: int, k: int, guard_warmup: int,
        dp_full: int, dp_shrunk: int,
    ) -> None:
        """The resize-soak plan: k shrink->grow cycles at ascending,
        spaced positions, plus the usual seeded loss spikes."""
        if dp_shrunk >= dp_full or dp_shrunk < 1:
            raise ValueError(
                f"elastic schedule needs 1 <= dp_shrunk < dp_full, got "
                f"{dp_shrunk} / {dp_full}"
            )
        self.crash_faults = ()
        self.storage_faults = ()
        spacing = max(3, self.save_interval)
        first = max(guard_warmup + 2, spacing)
        last = total_steps - 2
        n_events = 2 * k
        if first + (n_events - 1) * spacing > last:
            raise ValueError(
                f"total_steps={total_steps} too small for {k} "
                f"shrink->grow cycles spaced {spacing}"
            )
        slack = last - (first + (n_events - 1) * spacing)
        offsets = sorted(rng.randint(0, slack) for _ in range(n_events))
        steps = [first + i * spacing + offsets[i] for i in range(n_events)]
        self.resize_faults = tuple(
            TrainFault(
                "preempt_shrink" if i % 2 == 0 else "grow_back",
                at_step=s,
                dp=dp_shrunk if i % 2 == 0 else dp_full,
            )
            for i, s in enumerate(steps)
        )
        resize_steps = {f.at_step for f in self.resize_faults}
        candidates = [
            s for s in range(max(guard_warmup + 2, 3), total_steps - 1)
            if s not in resize_steps
        ]
        spikes = sorted(rng.sample(candidates, k))
        self.spike_faults = tuple(
            TrainFault("loss_spike", at_step=s) for s in spikes
        )
        self.plan = self.resize_faults + self.spike_faults

    @property
    def resize_plan(self) -> tuple[dict, ...]:
        """The resize cycles as the worker's staged-proposal env
        payload (JSON-ready)."""
        return tuple(
            {"at_step": f.at_step, "dp": f.dp, "cls": f.cls}
            for f in self.resize_faults
        )

    @property
    def spike_steps(self) -> tuple[int, ...]:
        return tuple(f.at_step for f in self.spike_faults)

    def storage_after(self, crash_idx: int) -> tuple[TrainFault, ...]:
        """Storage faults the driver applies after crash boundary
        `crash_idx` (the newest checkpoint is the target)."""
        return tuple(
            f for f in self.storage_faults if f.after_crash == crash_idx
        )

    def mark_injected(self, fault: TrainFault) -> None:
        """The fault's effect verifiably happened (the driver observed
        the kill/exit code, mutated a real file, or counted the guard
        skip)."""
        with self._lock:
            self._injected[fault.cls] += 1

    def coverage(self) -> dict[str, int]:
        with self._lock:
            return dict(self._injected)

    def __repr__(self) -> str:
        return (
            f"TrainFaultSchedule(seed={self.seed}, "
            f"planned={len(self.plan)}, coverage={self.coverage()})"
        )


# ---------------------------------------------------------------------------
# Serving data-plane fault plans (docs/serving.md)
#
# The serving chaos variant kills REPLICAS, not the control plane: a
# worker dies mid-request under thousands of concurrent clients and the
# router's ack contract (acked == completed + failed, failed == 0 for
# idempotent traffic) is the gate. Same discipline as every other plan
# here: finite, seeded, coverage-accounted.
# ---------------------------------------------------------------------------

REPLICA_KILL = "replica_kill"
SERVING_FAULT_CLASSES = (REPLICA_KILL,)


@dataclasses.dataclass(frozen=True)
class ReplicaKill:
    """One planned replica death. `at_fraction` is the point in the
    offered load (completed-requests fraction, 0..1) the kill fires at;
    `victim` indexes into the READY set at fire time (mod its length),
    so the plan stays meaningful however many replicas are still up."""

    cls: str
    at_fraction: float
    victim: int


class ReplicaKillSchedule:
    """A finite, seeded replica-kill plan for the serving chaos bench.

    Pure function of (seed, kills, replicas): two schedules from the
    same arguments have identical plans — the reproducibility contract
    shared with `FaultSchedule`/`TrainFaultSchedule`. Kills land at
    ascending load fractions inside `window` (default mid-run, so every
    kill hits a fleet with requests in flight AND leaves load behind it
    to prove recovery), each targeting a seeded victim index.

    `due(fraction)` is the driver's poll: it pops at most one kill whose
    trigger fraction has passed. Consumption is not coverage —
    `mark_injected` records only kills whose effect landed (the driver
    observed the process die / the queue close), so `coverage()` never
    reports robustness the run didn't test."""

    def __init__(
        self,
        seed: int,
        *,
        kills: int = 1,
        replicas: int = 3,
        window: tuple[float, float] = (0.2, 0.7),
    ):
        self.seed = seed
        rng = random.Random(seed)
        lo, hi = window
        span = (hi - lo) / max(1, kills)
        plan = []
        for i in range(kills):
            at = lo + span * (i + rng.uniform(0.25, 0.75))
            plan.append(
                ReplicaKill(REPLICA_KILL, at, rng.randrange(replicas))
            )
        self.plan: tuple[ReplicaKill, ...] = tuple(plan)
        self._pending: list[ReplicaKill] = list(self.plan)
        self._injected: dict[str, int] = {c: 0 for c in SERVING_FAULT_CLASSES}
        self._lock = threading.Lock()

    @classmethod
    def from_plan(cls, plan) -> "ReplicaKillSchedule":
        """A schedule with an explicit plan (targeted tests that need a
        kill at an exact point, not a seeded mix)."""
        sched = cls(0, kills=0)
        sched.plan = tuple(plan)
        sched._pending = list(sched.plan)
        return sched

    def due(self, fraction: float) -> ReplicaKill | None:
        """The kill (if any) whose trigger point has passed. At most one
        per call so the driver applies each death and lets the router
        react before the next. Thread-safe."""
        with self._lock:
            if self._pending and fraction >= self._pending[0].at_fraction:
                return self._pending.pop(0)
            return None

    def mark_injected(self, kill: ReplicaKill) -> None:
        """The kill verifiably landed (process dead / queue closed)."""
        with self._lock:
            self._injected[kill.cls] = self._injected.get(kill.cls, 0) + 1

    def coverage(self) -> dict[str, int]:
        with self._lock:
            return dict(self._injected)

    @property
    def exhausted(self) -> bool:
        with self._lock:
            return not self._pending

    def __repr__(self) -> str:
        return (
            f"ReplicaKillSchedule(seed={self.seed}, "
            f"planned={len(self.plan)}, coverage={self.coverage()})"
        )


def apply_checkpoint_fault(ckpt_dir, cls: str, offset: int = 0) -> str | None:
    """Mutate the checkpoint `offset` steps back from the newest under
    `ckpt_dir` (0 = newest) per the storage fault class. Returns a
    description of what was damaged, or None when there was nothing to
    damage at that offset (the driver must treat that as a scheduling
    bug — storage faults are planned after >= max_to_keep saves)."""
    from pathlib import Path

    from kubeflow_tpu.train.checkpoint import MANIFEST_NAME

    root = Path(ckpt_dir)
    steps = sorted(
        (int(p.name), p) for p in root.iterdir()
        if p.is_dir() and p.name.isdigit()
    )
    if len(steps) <= offset:
        return None
    step, step_dir = steps[-1 - offset]
    if cls == "corrupt_manifest":
        target = step_dir / MANIFEST_NAME
        # Unparsable JSON: the verifier must treat it as corruption, not
        # crash on it.
        target.write_bytes(b'{"files": {broken')
        return f"corrupt_manifest step={step}"
    files = sorted(
        (p for p in step_dir.rglob("*")
         if p.is_file() and p.name != MANIFEST_NAME),
        key=lambda p: p.stat().st_size,
    )
    if not files:
        return None
    target = files[-1]  # the largest payload file: real tensor bytes
    data = target.read_bytes()
    if cls == "truncate_checkpoint":
        target.write_bytes(data[: max(1, len(data) // 2)])
        return f"truncate_checkpoint step={step} file={target.name}"
    if cls == "corrupt_checkpoint":
        mid = len(data) // 2
        flipped = bytes(b ^ 0xFF for b in data[mid:mid + 16])
        target.write_bytes(data[:mid] + flipped + data[mid + 16:])
        return f"corrupt_checkpoint step={step} file={target.name}"
    raise ValueError(f"unknown storage fault class {cls!r}")


class ResumableWrapper:
    """Base for fault-injecting wrappers over a resumable data iterable:
    forwards the whole resumable-data protocol (docs/resilience.md) so a
    wrapped stream checkpoints/restores/perturbs exactly like the bare
    one, and exposes `position` — the upcoming batch's 0-based index —
    in either state dialect (the synthetic streams count "position",
    RecordDataset counts "batches_delivered")."""

    def __init__(self, data):
        self._data = data

    @property
    def position(self) -> int:
        state = self._data.state_dict()
        if "position" in state:
            return int(state["position"])
        return int(state["batches_delivered"])

    def state_dict(self) -> dict:
        return self._data.state_dict()

    def load_state_dict(self, state: dict) -> None:
        self._data.load_state_dict(state)

    def rebind(self, mesh) -> "ResumableWrapper":
        """The wrapper re-bound to a resized mesh (elastic resize):
        rebinds the WRAPPED iterable and keeps this wrapper's own fault
        state — scheduled positions are mesh-independent, so faults
        staged past the resize still fire exactly once."""
        import copy

        clone = copy.copy(self)
        clone._data = self._data.rebind(mesh)
        return clone

    def __getattr__(self, name):
        # `perturb` is OPTIONAL in the protocol: expose it only when
        # the wrapped data actually has one, so capability probes
        # (e.g. fit()'s rollback precondition, which must refuse
        # non-perturbable data rather than run futile identical
        # retries) see the truth through the wrapper.
        if name == "perturb" and "_data" in self.__dict__:
            return getattr(self._data, "perturb")
        raise AttributeError(name)

    def __iter__(self):
        it = iter(self._data)
        while True:
            pos = self.position
            try:
                batch = next(it)
            except StopIteration:
                # PEP 479: a StopIteration escaping a generator body
                # becomes RuntimeError — end cleanly instead, so finite
                # wrapped streams (e.g. bounded-epoch RecordDatasets)
                # still signal exhaustion to the training loop.
                return
            yield self.transform(pos, batch)

    def transform(self, pos: int, batch):
        """Override: the (possibly faulted) batch for position `pos`."""
        return batch


class SpikedData(ResumableWrapper):
    """Deterministic loss-spike injector over a resumable data iterable.

    At each position in `positions`, the yielded batch's float fields
    are scaled by `scale` — a poison batch whose loss/grad-norm the
    AnomalyGuard must reject. The spike is a pure function of the
    position, so a resumed (or baseline) run sees the identical poison
    at the identical step — the spikes are part of the data, which is
    what lets the soak assert exact final-state parity against an
    uninterrupted run."""

    def __init__(self, data, positions, scale: float = 1e4):
        super().__init__(data)
        self.positions = frozenset(int(p) for p in positions)
        self.scale = scale

    def transform(self, pos: int, batch):
        if pos not in self.positions:
            return batch
        return {
            k: v * self.scale if v.dtype.kind == "f" else v
            for k, v in batch.items()
        }


def _abort(sock: socket.socket) -> None:
    """Hard-close: RST instead of FIN (SO_LINGER 0), so the peer sees a
    connection *failure*, not a clean end-of-stream."""
    try:
        sock.setsockopt(
            socket.SOL_SOCKET, socket.SO_LINGER, struct.pack("ii", 1, 0)
        )
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


def _synth_response(status: int, reason: str, payload: dict) -> bytes:
    """A synthesized HTTP/1.1 response in the facade's error envelope
    (`web.wsgi.error_response`), so injected statuses are
    indistinguishable from server-emitted ones at the client."""
    body = json.dumps(
        {"success": False, "status": status, **payload}
    ).encode()
    head = (
        f"HTTP/1.1 {status} {reason}\r\n"
        f"Content-Type: application/json\r\n"
        f"Content-Length: {len(body)}\r\n\r\n"
    ).encode()
    return head + body


class ChaosProxy:
    """Seeded fault-injecting TCP proxy in front of the apiserver facade.

    One listener; each accepted client connection gets a thread and one
    upstream connection (keep-alive preserved end-to-end when no fault
    intervenes). Requests are parsed just enough to classify them
    (method, path, query, Content-Length body) and to frame upstream
    responses (Content-Length vs chunked) so the proxy can relay
    streaming watches chunk-by-chunk — the surface the stream faults
    need.
    """

    def __init__(
        self,
        upstream_host: str,
        upstream_port: int,
        schedule: FaultSchedule,
        host: str = "127.0.0.1",
        port: int = 0,
        kill_active=None,
    ):
        self.upstream = (upstream_host, upstream_port)
        self.schedule = schedule
        # apiserver_kill executor: a driver-supplied callable that
        # SIGKILLs the active facade (and typically restarts the deposed
        # one as a fresh standby). Return a falsy value when no kill
        # happened (the entry requeues), True when the NEW active serves
        # on the same upstream address, or the new active's
        # (host, port) — the proxy retargets, so an active-passive pair
        # on per-replica ports stays reachable through one proxied
        # address across takeovers. Without a callback, apiserver_kill
        # entries requeue forever — so only schedules built with
        # HA_FAULT_CLASSES should meet a proxy without one, and only in
        # tests asserting that.
        self.kill_active = kill_active
        self.host = host
        self._want_port = port
        self._listener: socket.socket | None = None
        self._closed = threading.Event()
        self._conns: set[socket.socket] = set()
        self._conns_lock = threading.Lock()
        # Remaining synthesized 503s of an active error_5xx burst.
        self._burst = 0
        self._burst_lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ChaosProxy":
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind((self.host, self._want_port))
        listener.listen(64)
        listener.settimeout(1.0)
        self._listener = listener
        threading.Thread(
            target=self._accept_loop, name="chaos-proxy", daemon=True
        ).start()
        return self

    @property
    def port(self) -> int:
        assert self._listener is not None, "start() first"
        return self._listener.getsockname()[1]

    @property
    def base_url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        self._closed.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        with self._conns_lock:
            conns, self._conns = set(self._conns), set()
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    # -- request parsing ---------------------------------------------------

    def _read_request(self, sock: socket.socket):
        """One full client request (clients send Content-Length-framed
        JSON bodies only). Returns (method, target, raw_head, body) or
        None on clean EOF."""
        buf = b""
        while b"\r\n\r\n" not in buf:
            try:
                data = sock.recv(65536)
            except OSError:
                return None
            if not data:
                return None
            buf += data
        head, _, tail = buf.partition(b"\r\n\r\n")
        lines = head.decode("latin-1").split("\r\n")
        method, target = lines[0].split(" ", 2)[:2]
        length = 0
        for line in lines[1:]:
            name, _, value = line.partition(":")
            if name.strip().lower() == "content-length":
                try:
                    length = int(value.strip())
                except ValueError:
                    length = 0
        body = tail
        while len(body) < length:
            data = sock.recv(65536)
            if not data:
                return None
            body += data
        return method, target, head + b"\r\n\r\n", body

    # -- response relay ----------------------------------------------------

    def _read_response_head(self, upstream: socket.socket):
        """Status line + headers + any body bytes already received.
        Returns (status, headers_lower, raw_head, extra) or None."""
        buf = b""
        while b"\r\n\r\n" not in buf:
            data = upstream.recv(65536)
            if not data:
                return None
            buf += data
        head, _, extra = buf.partition(b"\r\n\r\n")
        lines = head.decode("latin-1").split("\r\n")
        status = int(lines[0].split(" ", 2)[1])
        headers: dict[str, str] = {}
        for line in lines[1:]:
            name, _, value = line.partition(":")
            headers[name.strip().lower()] = value.strip()
        return status, headers, head + b"\r\n\r\n", extra

    def _read_exact(self, upstream: socket.socket, buf: bytes, n: int):
        while len(buf) < n:
            data = upstream.recv(65536)
            if not data:
                break
            buf += data
        return buf

    def _relay_fixed(
        self, client, upstream, raw_head, extra, length, fault
    ) -> bool:
        """Relay a Content-Length response; returns False when the
        connection pair must be dropped."""
        try:
            body = self._read_exact(upstream, extra, length)[:length]
        except OSError:
            if fault is not None:
                self.schedule.requeue(fault)
            raise
        if fault is not None and fault.cls == "reset_mid_response":
            cut = max(1, int(len(body) * fault.param)) if body else 0
            try:
                client.sendall(raw_head + body[:cut])
            except OSError:
                pass
            # Either way the client experienced a severed response.
            self.schedule.mark_injected(fault)
            _abort(client)
            return False
        if fault is not None and fault.cls == "crash_before_ack":
            # The upstream response is fully read — the write COMMITTED.
            # The client never hears the ack.
            self.schedule.mark_injected(fault)
            _abort(client)
            return False
        if fault is not None:
            # A stream-class fault bound to a request whose response
            # turned out non-chunked (e.g. the stream request drew a
            # plain-framed error): it never took effect — retry later.
            self.schedule.requeue(fault)
        client.sendall(raw_head + body)
        return True

    def _relay_chunked(self, client, upstream, raw_head, extra, fault) -> bool:
        """Relay a chunked (streaming watch) response burst-by-burst,
        watching for the terminal 0-chunk so keep-alive survives a
        cleanly-ended stream. Returns False when the pair must drop.
        A bound stream fault is marked injected only when its effect
        actually lands (the sever happened / at least one burst was
        delayed) and requeued when the stream ends first — coverage
        must never claim an injection the wire never carried."""
        try:
            client.sendall(raw_head)
        except OSError:
            if fault is not None:
                self.schedule.requeue(fault)
            raise
        relayed = 0
        slow_bursts = 8 if (fault and fault.cls == "slow_stream") else 0
        slowed = False
        tail = b""
        buf = extra

        def settle(applied: bool) -> None:
            if fault is None:
                return
            if applied:
                self.schedule.mark_injected(fault)
            else:
                self.schedule.requeue(fault)

        while True:
            if buf:
                if fault is not None and fault.cls == "truncate_stream":
                    if relayed + len(buf) >= fault.param:
                        keep = max(0, int(fault.param) - relayed)
                        try:
                            client.sendall(buf[:keep])
                        except OSError:
                            pass
                        # Sever with no terminal chunk: the client's
                        # chunked reader must treat this as a transport
                        # failure, never a clean end.
                        settle(True)
                        _abort(client)
                        return False
                if slow_bursts > 0:
                    time.sleep(fault.param)
                    slow_bursts -= 1
                    slowed = True
                try:
                    client.sendall(buf)
                except OSError:
                    settle(slowed)
                    return False
                relayed += len(buf)
                tail = (tail + buf)[-8:]
                buf = b""
                if tail.endswith(b"0\r\n\r\n"):
                    # Terminal chunk: response complete. A slow fault
                    # that delayed at least one burst took effect; a
                    # truncate fault whose byte budget never arrived
                    # did not.
                    settle(slowed if fault is not None
                           and fault.cls == "slow_stream" else False)
                    return True
            try:
                buf = upstream.recv(65536)
            except OSError:
                buf = b""
            if not buf:
                settle(slowed)
                return False  # upstream died mid-stream: drop the pair

    # -- per-connection loop -----------------------------------------------

    def _serve_conn(self, client: socket.socket) -> None:
        upstream: socket.socket | None = None
        client.settimeout(300.0)
        try:
            while not self._closed.is_set():
                req = self._read_request(client)
                if req is None:
                    return
                method, target, raw_head, body = req
                path, _, query = target.partition("?")

                with self._burst_lock:
                    in_burst = self._burst > 0
                    if in_burst:
                        self._burst -= 1
                if in_burst:
                    # Burst continuation: not a plan entry, no coverage
                    # accounting of its own.
                    fault = Fault("error_5xx", 0.0, 0)
                else:
                    fault = self.schedule.next_fault(method, path, query)

                if fault is not None and fault.cls == "error_5xx":
                    if not in_burst and fault.param > 1:
                        with self._burst_lock:
                            self._burst += int(fault.param) - 1
                    client.sendall(
                        _synth_response(
                            503,
                            "Service Unavailable",
                            {"log": "chaos: injected apiserver outage"},
                        )
                    )
                    if not in_burst:
                        self.schedule.mark_injected(fault)
                    continue
                if fault is not None and fault.cls == "stale_gone":
                    client.sendall(
                        _synth_response(
                            410,
                            "Gone",
                            {
                                "log": (
                                    "chaos: resourceVersion expired — "
                                    "relist"
                                )
                            },
                        )
                    )
                    self.schedule.mark_injected(fault)
                    continue
                if fault is not None and fault.cls == APISERVER_KILL:
                    # Whole-facade death: the driver's callback SIGKILLs
                    # the active. The in-flight request dies with it (an
                    # aborted connection, exactly what a real kill does
                    # to this client), and every other client discovers
                    # the death through its own transport errors.
                    killed = (
                        self.kill_active()
                        if self.kill_active is not None
                        else None
                    )
                    if killed:
                        if isinstance(killed, tuple):
                            # The new active serves elsewhere (per-
                            # replica ports): retarget, so the NEXT
                            # connection through this proxy reaches it.
                            self.upstream = killed
                        self.schedule.mark_injected(fault)
                        _abort(client)
                        return
                    self.schedule.requeue(fault)
                    fault = None
                if fault is not None and fault.cls == "delay_write":
                    # The hold itself is the effect; the write then
                    # proceeds normally.
                    time.sleep(fault.param)
                    self.schedule.mark_injected(fault)
                    fault = None

                if upstream is None:
                    try:
                        upstream = socket.create_connection(
                            self.upstream, timeout=300.0
                        )
                    except OSError:
                        if fault is not None:
                            self.schedule.requeue(fault)
                        _abort(client)
                        return
                    with self._conns_lock:
                        self._conns.add(upstream)
                try:
                    upstream.sendall(raw_head + body)
                    resp = self._read_response_head(upstream)
                except OSError:
                    resp = None
                if resp is None:
                    # Upstream gone mid-request: the bound fault never
                    # took effect — retry it later. Surface a transport
                    # failure to the client and retire both ends.
                    if fault is not None:
                        self.schedule.requeue(fault)
                    _abort(client)
                    return
                status, headers, resp_head, extra = resp
                try:
                    if headers.get("transfer-encoding", "").lower() == \
                            "chunked":
                        ok = self._relay_chunked(
                            client, upstream, resp_head, extra, fault
                        )
                    else:
                        length = int(headers.get("content-length", 0) or 0)
                        ok = self._relay_fixed(
                            client, upstream, resp_head, extra, length,
                            fault,
                        )
                except OSError:
                    ok = False
                if not ok:
                    return
                if headers.get("connection", "").lower() == "close":
                    return
        except Exception:
            log.debug("chaos proxy connection error", exc_info=True)
        finally:
            for sock in (client, upstream):
                if sock is None:
                    continue
                with self._conns_lock:
                    self._conns.discard(sock)
                try:
                    sock.close()
                except OSError:
                    pass


# ---------------------------------------------------------------------------
# RL study fault plans (docs/rl.md)
#
# The RL soak runs a StudyJob of short actor–learner trials and kills
# each layer of the coupled system in a different trial: an ACTOR's
# serving replica mid-study (the fleet must heal and the loop keep
# acting), the LEARNER mid-fit (SIGKILL; the resumed incarnation must
# continue the same replay position), and a whole TRIAL before it
# trains (the study controller must reschedule it). Same discipline as
# every plan above: finite, seeded, coverage gated on worker-reported
# evidence — never on the driver's intent.
# ---------------------------------------------------------------------------

ACTOR_KILL = "actor_kill"
LEARNER_KILL = "learner_kill"
TRIAL_KILL = "trial_kill"
RL_FAULT_CLASSES = (ACTOR_KILL, LEARNER_KILL, TRIAL_KILL)


@dataclasses.dataclass(frozen=True)
class RLFault:
    """One planned RL fault. `trial` is the study trial index it binds
    to; `at_fraction` the point in that trial's learner progress
    (steps-done fraction, 0..1) it fires at. trial_kill fires before
    meaningful training (the reschedule story), learner_kill mid-fit
    (the resume story), actor_kill mid-fit (the heal story)."""

    cls: str
    trial: int
    at_fraction: float


class RLFaultSchedule:
    """A finite, seeded fault plan for the RL study soak.

    Pure function of (seed, trials): the soak DRIVER and every TRIAL
    WORKER construct the identical schedule from the env-carried seed,
    so a worker self-derives its own faults from its trial index (read
    off its job's trial label) — no fault channel between processes,
    which is exactly why a kill can't be lost in transit.

    Every class lands on a DISTINCT trial (requires trials >= 3) so one
    trial's recovery can't mask another class going uninjected.
    `mark_injected` is driven by worker-reported evidence only (the
    observation rows carry what actually happened), so `coverage()`
    never reports robustness the run didn't test.
    """

    def __init__(self, seed: int, *, trials: int):
        if trials < len(RL_FAULT_CLASSES):
            raise ValueError(
                f"RL soak needs >= {len(RL_FAULT_CLASSES)} trials for "
                f"distinct per-class victims, got {trials}"
            )
        self.seed = seed
        self.trials = trials
        # A STRING seed: Random(str) seeds via sha512 — stable across
        # processes, which the driver/worker shared-plan contract needs
        # (tuple/other hashables seed via hash(), randomized per
        # process by PYTHONHASHSEED).
        rng = random.Random(f"rl-{seed}")
        victims = rng.sample(range(trials), len(RL_FAULT_CLASSES))
        windows = {
            # Early: the trial dies before training matters.
            TRIAL_KILL: (0.0, 0.1),
            # Mid-fit, past warmup, with room left to prove recovery.
            LEARNER_KILL: (0.35, 0.65),
            ACTOR_KILL: (0.3, 0.6),
        }
        plan = []
        for cls, trial in zip(RL_FAULT_CLASSES, victims):
            lo, hi = windows[cls]
            plan.append(RLFault(cls, trial, rng.uniform(lo, hi)))
        self.plan: tuple[RLFault, ...] = tuple(
            sorted(plan, key=lambda f: f.trial)
        )
        self._injected: dict[str, int] = {c: 0 for c in RL_FAULT_CLASSES}
        self._lock = threading.Lock()

    def for_trial(self, trial: int) -> tuple[RLFault, ...]:
        """The faults bound to one trial (what a worker self-delivers)."""
        return tuple(f for f in self.plan if f.trial == trial)

    def mark_injected(self, cls: str) -> None:
        """Worker-reported evidence says this class's effect landed."""
        with self._lock:
            self._injected[cls] = self._injected.get(cls, 0) + 1

    def coverage(self) -> dict[str, int]:
        with self._lock:
            return dict(self._injected)

    def __repr__(self) -> str:
        return (
            f"RLFaultSchedule(seed={self.seed}, trials={self.trials}, "
            f"planned={len(self.plan)}, coverage={self.coverage()})"
        )
