"""E2E harness utilities — the `testing/` toolbox analog.

Parity map (SURVEY.md §2 #26, §4):
- `run_with_retry`      → `testing/run_with_retry.py` flake harness
- `wait_for` /
  `wait_for_deployments`→ `testing/wait_for_deployment.py`,
                          `wait_for_kubeflow.py`
- `kf_is_ready`         → `testing/kfctl/kf_is_ready_test.py:101-115`
                          (the core deployment-set assertion)
- `junit_xml`           → the junit-to-GCS Gubernator contract every
                          Argo step honored (`testing/README.md:22-35`)
- `NotebookLoadTest`    → `notebook-controller/loadtest/start_notebooks.py`
- `DeployProber`        → `testing/test_deploy_app.py:38-53` continuous
                          click-to-deploy prober with Prometheus gauges
"""

from __future__ import annotations

import dataclasses
import logging
import time
import xml.sax.saxutils as saxutils
from typing import Callable, Iterable

from kubeflow_tpu.api.objects import new_resource
from kubeflow_tpu.deploy.bundles import CORE_DEPLOYMENTS
from kubeflow_tpu.testing.fake_apiserver import FakeApiServer, NotFound
from kubeflow_tpu.utils.metrics import MetricsRegistry

log = logging.getLogger(__name__)


def run_with_retry(
    fn: Callable[[], object],
    *,
    retries: int = 3,
    delay_seconds: float = 1.0,
    backoff: float = 2.0,
    exceptions: tuple[type[BaseException], ...] = (Exception,),
    sleep: Callable[[float], None] = time.sleep,
):
    """Run `fn`, retrying listed exceptions up to `retries` extra times
    with exponential backoff. The last failure propagates."""
    attempt = 0
    while True:
        try:
            return fn()
        except exceptions:
            attempt += 1
            if attempt > retries:
                raise
            wait = delay_seconds * backoff ** (attempt - 1)
            log.warning("attempt %d failed; retrying in %.1fs", attempt, wait)
            sleep(wait)


def wait_for(
    predicate: Callable[[], bool],
    *,
    timeout_seconds: float = 300.0,
    poll_seconds: float = 1.0,
    desc: str = "condition",
    clock: Callable[[], float] = time.monotonic,
    sleep: Callable[[float], None] = time.sleep,
) -> None:
    """Poll until `predicate()` is truthy; TimeoutError otherwise."""
    deadline = clock() + timeout_seconds
    while not predicate():
        if clock() >= deadline:
            raise TimeoutError(f"timed out waiting for {desc}")
        sleep(poll_seconds)


def missing_deployments(
    api: FakeApiServer,
    names: Iterable[str] = CORE_DEPLOYMENTS,
    namespace: str = "kubeflow",
) -> list[str]:
    present = {d.metadata.name for d in api.list("Deployment", namespace)}
    return [n for n in names if n not in present]


def wait_for_deployments(
    api: FakeApiServer,
    names: Iterable[str],
    namespace: str = "kubeflow",
    **wait_kwargs,
) -> None:
    names = list(names)
    wait_for(
        lambda: not missing_deployments(api, names, namespace),
        desc=f"deployments {names}",
        **wait_kwargs,
    )


def kf_is_ready(api: FakeApiServer) -> list[str]:
    """The `kf_is_ready_test` assertion: returns what's missing from the
    core component set (empty = ready)."""
    problems = [
        f"deployment/{n}" for n in missing_deployments(api)
    ]
    crds = {c.metadata.name for c in api.list("CustomResourceDefinition", "")}
    for plural in (
        "tpujobs", "studies", "workflows", "notebooks", "profiles",
        "tensorboards", "poddefaults",
    ):
        if f"{plural}.kubeflow-tpu.org" not in crds:
            problems.append(f"crd/{plural}")
    return problems


# -- junit ------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TestResult:
    name: str
    seconds: float = 0.0
    failure: str | None = None


def junit_xml(suite: str, results: Iterable[TestResult]) -> str:
    results = list(results)
    failures = sum(1 for r in results if r.failure is not None)
    lines = [
        '<?xml version="1.0" encoding="utf-8"?>',
        f'<testsuite name={saxutils.quoteattr(suite)} '
        f'tests="{len(results)}" failures="{failures}">',
    ]
    for r in results:
        open_tag = (
            f"  <testcase name={saxutils.quoteattr(r.name)} "
            f'time="{r.seconds:.3f}"'
        )
        if r.failure is None:
            lines.append(open_tag + " />")
        else:
            lines.append(open_tag + ">")
            lines.append(
                f"    <failure>{saxutils.escape(r.failure)}</failure>"
            )
            lines.append("  </testcase>")
    lines.append("</testsuite>")
    return "\n".join(lines) + "\n"


# -- load tests -------------------------------------------------------------


class NotebookLoadTest:
    """Spawn N Notebook CRs and wait for their StatefulSets — the
    controller load test (`loadtest/start_notebooks.py:1-30`)."""

    def __init__(self, api: FakeApiServer, namespace: str = "loadtest"):
        self.api = api
        self.namespace = namespace

    def spawn(self, count: int, *, image: str = "kubeflow-tpu/jax-notebook:0.6-cpu"):
        for i in range(count):
            self.api.create(
                new_resource(
                    "Notebook",
                    f"load-{i}",
                    self.namespace,
                    spec={
                        "template": {
                            "spec": {
                                "containers": [
                                    {"name": "notebook", "image": image}
                                ]
                            }
                        }
                    },
                )
            )

    def ready_count(self) -> int:
        names = {
            n.metadata.name for n in self.api.list("Notebook", self.namespace)
        }
        return sum(
            1
            for s in self.api.list("StatefulSet", self.namespace)
            if s.metadata.name in names
        )

    def cleanup(self) -> None:
        for n in self.api.list("Notebook", self.namespace):
            try:
                self.api.delete("Notebook", n.metadata.name, self.namespace)
            except NotFound:
                pass


class DeployProber:
    """Continuous deploy prober (`test_deploy_app.py`): drive the deploy
    service end-to-end and export `deployment_service_status` (1 ok) +
    latency + failure counters."""

    def __init__(
        self,
        client,  # TestClient or HTTP client with post/get -> Response
        *,
        metrics: MetricsRegistry | None = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        timeout_seconds: float = 120.0,
    ):
        self.client = client
        self.metrics = metrics or MetricsRegistry()
        self.status_gauge = self.metrics.gauge(
            "deployment_service_status", "1 if the last probe deployed OK"
        )
        self.latency = self.metrics.gauge(
            "deployment_latency_seconds", "last end-to-end deploy time"
        )
        self.failures = self.metrics.counter(
            "deployment_probe_failures_total", "failed deploy probes"
        )
        self.clock = clock
        self.sleep = sleep
        self.timeout_seconds = timeout_seconds

    def probe_once(self, spec_dict: dict) -> bool:
        """spec_dict: a PlatformSpec dict (`kfctl` request body)."""
        t0 = self.clock()
        ok = False
        try:
            name = spec_dict["metadata"]["name"]
            resp = self.client.post("/kfctl/apps/v1/create", spec_dict)
            if resp.status in (200, 201, 202):
                deadline = self.clock() + self.timeout_seconds
                while self.clock() < deadline:
                    status = self.client.get(f"/kfctl/apps/v1/status/{name}")
                    phase = (
                        status.json().get("status", {}).get("phase")
                        if status.status == 200
                        else None
                    )
                    if phase == "Ready":
                        ok = True
                        break
                    if phase == "Failed":
                        break
                    self.sleep(1.0)
        except Exception as e:  # the prober itself must not die
            log.warning("deploy probe error: %s", e)
        self.latency.set(self.clock() - t0)
        self.status_gauge.set(1.0 if ok else 0.0)
        if not ok:
            self.failures.inc()
        return ok
