"""Active-passive apiserver failover over one durable state directory.

The reference never built this: GKE's managed control plane plus etcd
quorum gave it an HA apiserver for free (`kf_is_ready_test.py:101-115`
simply assumes one is there to ask). A platform that REPLACES the
apiserver must replace that property. The shape here is the classic
active-passive pair over shared durable storage:

- N facade processes boot over the same `persist_dir`, but only the one
  holding the **apiserver lease** opens the store and serves; the rest
  park in the standby acquire loop (`controllers/leader.py` — the exact
  elector the controllers use, pointed at a different lease store).
- The lease cannot live INSIDE the store it gates (the store is closed
  until the lease is won), so `FileLeaseStore` keeps it as a file
  BESIDE the store directory, with the same CAS surface the elector
  expects: get/create/update with resourceVersion preconditions,
  serialized under an OS file lock.
- On takeover the new active replays the WAL (`FakeApiServer._restore_locked`:
  snapshot + journal tail, torn-tail repair, watch journal re-seeded at
  the durable resourceVersion so pre-failover bookmarks get an honest
  410 → relist), then `checkpoint()`s — which, via `PyWal.snapshot`'s
  truncate-by-replacement, moves `wal.log` onto a **new inode**. A
  deposed active still holding the old fd appends into an orphaned file
  no restart will ever replay: late writes are *physically* fenced.
- Belt to that suspender: the active's WAL is wrapped in `FencedWal`,
  which re-reads the lease before every append/snapshot. The instant
  the term moves, the next durable write raises `WalFenced`, the store
  fail-stops (`fake_apiserver._fail_stop_locked` — in-memory divergence becomes
  unobservable, every op 503s), clients rotate to the new active via
  their endpoint list, and the deposed process exits. An acked write is
  therefore either in the WAL the successor replayed, or was never
  acked at all — the zero-acked-writes-lost contract the failover e2e
  pins with a WAL diff.

Timing inherits the elector's contract: `renew_deadline <
lease_duration` means a partitioned active stops acking (fail-stop on
its next durable write, or steps down) before the standby's takeover
clock can have expired, so the fencing races the chaos suite throws at
it (SIGSTOP, SIGKILL mid-load) resolve to Conflict/503, never to two
actives acking into one log.
"""

from __future__ import annotations

import json
import logging
import os
import threading

from kubeflow_tpu.api.objects import Resource, new_resource
from kubeflow_tpu.testing.fake_apiserver import (
    AlreadyExists,
    Conflict,
    NotFound,
)

log = logging.getLogger(__name__)

LEASE_KIND = "Lease"


class WalFenced(Exception):
    """A durable write was attempted after this process's term ended.
    Deliberately NOT an ApiError: `FakeApiServer._persist_locked` maps unknown
    exceptions to fail-stop (every subsequent op raises Unavailable),
    which is exactly the posture a deposed active must take."""


class FileLeaseStore:
    """Lease CRUD over files in a shared directory — the minimal CAS
    surface `controllers/leader.LeaderElector` needs (get/create/update
    with resourceVersion preconditions), for the one lease that cannot
    live inside the store it gates. One JSON file per lease name; every
    mutation happens under an `flock` on a sibling lock file and lands
    via write-tmp/fsync/rename, so concurrent candidates on the same
    host (the active-passive deployment unit) serialize exactly like
    store writers under the commit lock."""

    def __init__(self, directory: str):
        self._dir = str(directory)
        os.makedirs(self._dir, mode=0o700, exist_ok=True)
        self._lock_path = os.path.join(self._dir, ".lock")
        self._local = threading.Lock()  # flock is per-fd: serialize threads

    def _path(self, name: str) -> str:
        if "/" in name or name.startswith("."):
            raise ValueError(f"invalid lease name {name!r}")
        return os.path.join(self._dir, f"{name}.json")

    class _Flock:
        def __init__(self, path: str):
            self._path = path
            self._fd: int | None = None

        def __enter__(self):
            import fcntl

            self._fd = os.open(self._path, os.O_RDWR | os.O_CREAT, 0o600)
            fcntl.flock(self._fd, fcntl.LOCK_EX)
            return self

        def __exit__(self, *exc):
            import fcntl

            fcntl.flock(self._fd, fcntl.LOCK_UN)
            os.close(self._fd)

    def _locked(self):
        return self._Flock(self._lock_path)

    def _read(self, name: str) -> dict | None:
        try:
            with open(self._path(name), encoding="utf-8") as f:
                return json.load(f)
        except FileNotFoundError:
            return None
        except ValueError:
            # A torn lease write is unreachable (tmp+rename), but a
            # garbage file must read as "no holder", not crash the
            # election loop.
            return None

    def _write(self, name: str, record: dict) -> None:
        path = self._path(name)
        tmp = path + ".tmp"
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        try:
            data = json.dumps(record, separators=(",", ":")).encode()
            while data:
                data = data[os.write(fd, data):]
            os.fsync(fd)
        finally:
            os.close(fd)
        os.rename(tmp, path)
        dir_fd = os.open(self._dir, os.O_RDONLY | os.O_DIRECTORY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)

    def _to_resource(self, name: str, record: dict) -> Resource:
        lease = new_resource(
            LEASE_KIND, name, "", spec=dict(record.get("spec", {}))
        )
        lease.metadata.resource_version = int(record.get("rv", 0))
        return lease

    # -- the elector's CAS surface ----------------------------------------

    def get(self, kind: str, name: str, namespace: str = "") -> Resource:
        assert kind == LEASE_KIND, kind
        record = self._read(name)
        if record is None:
            raise NotFound(f"Lease {name!r} not found")
        return self._to_resource(name, record)

    def create(self, obj: Resource) -> Resource:
        assert obj.kind == LEASE_KIND, obj.kind
        name = obj.metadata.name
        with self._local, self._locked():
            if self._read(name) is not None:
                raise AlreadyExists(f"Lease {name!r} already exists")
            record = {"rv": 1, "spec": dict(obj.spec)}
            self._write(name, record)
        return self._to_resource(name, record)

    def update(self, obj: Resource) -> Resource:
        assert obj.kind == LEASE_KIND, obj.kind
        name = obj.metadata.name
        with self._local, self._locked():
            record = self._read(name)
            if record is None:
                raise NotFound(f"Lease {name!r} not found")
            if (
                obj.metadata.resource_version
                and obj.metadata.resource_version != int(record["rv"])
            ):
                raise Conflict(
                    f"Lease {name!r}: stale resourceVersion "
                    f"{obj.metadata.resource_version} != {record['rv']}"
                )
            record = {"rv": int(record["rv"]) + 1, "spec": dict(obj.spec)}
            self._write(name, record)
        return self._to_resource(name, record)

    # -- the fence's read surface -----------------------------------------

    def read_spec(self, name: str) -> dict | None:
        """The lease's spec right now, or None — lock-free (the file is
        replaced atomically), cheap enough to run on every WAL append."""
        record = self._read(name)
        return dict(record.get("spec", {})) if record else None


class FencedWal:
    """Term fencing at the durability boundary: every append/snapshot
    verifies the lease still names this process's (holder, transitions)
    — BEFORE the write (don't touch a successor's log if we already
    know the term moved) and again AFTER it, before the caller can ack.
    The post-write check is the one that carries the zero-acked-loss
    contract: verify→write alone has a TOCTOU hole (verify passes, the
    process is descheduled, the standby wins the lease AND replays the
    log, then the old append lands — acked but never replayed). The
    successor always CAS-moves the lease before it reads the log, so
    re-reading the lease after our write is durable and raising
    `WalFenced` turns that lost update into an UNACKED one: the client
    sees the error and retries against the successor through the normal
    duplicate-free paths. (A fenced-after-write record may still be
    replayed if it beat the successor's read — harmless, that is
    exactly the crash_before_ack ambiguity clients already absorb.)
    The moment either check fires the store fail-stops and clients
    rotate. Reads and close stay open: a deposed process may still
    drain diagnostics. Residual: a stop-the-world pause between the
    post-check and `snapshot`'s rename could still publish a stale
    snapshot; that window is two instructions wide and covered by the
    elector's timing contract (renew_deadline < lease_duration — a
    process stalled that long has already stopped renewing)."""

    def __init__(self, inner, verify):
        self._inner = inner
        self._verify = verify

    def append(self, line: str) -> None:
        self._verify()
        self._inner.append(line)
        self._verify()  # the ack barrier (see class docstring)

    def snapshot(self, text: str) -> None:
        self._verify()
        self._inner.snapshot(text)
        self._verify()

    def read_snapshot(self) -> str:
        return self._inner.read_snapshot()

    def read_journal(self) -> str:
        return self._inner.read_journal()

    def close(self) -> None:
        self._inner.close()


def term_fence(
    leases: FileLeaseStore, name: str, holder: str, transitions: int
):
    """A `wal_wrap` for `FakeApiServer`: wraps the opened WAL in a
    `FencedWal` bound to one term. Pass right after winning the lease:

        api = FakeApiServer(
            persist_dir=...,
            wal_wrap=term_fence(leases, "apiserver", elector.identity,
                                elector.transitions),
        )
    """

    def verify() -> None:
        spec = leases.read_spec(name)
        current = (
            (spec.get("holderIdentity"), int(spec.get("leaseTransitions", 0)))
            if spec is not None
            else None
        )
        if current != (holder, int(transitions)):
            raise WalFenced(
                f"lease {name!r} is {current} but this store serves term "
                f"({holder!r}, {transitions}) — a deposed active must not "
                "write into its successor's log"
            )

    return lambda wal: FencedWal(wal, verify)


def open_active_store(
    persist_dir: str,
    leases: FileLeaseStore,
    lease_name: str,
    holder: str,
    transitions: int,
    **api_kwargs,
):
    """The takeover sequence, in order: open the durable store fenced to
    this term (construction replays snapshot + WAL and re-seeds the
    watch floor at the durable rv), then checkpoint — folding the
    replayed tail into a fresh snapshot and, via truncate-by-replacement,
    rotating `wal.log` onto a new inode so the deposed predecessor's fd
    is orphaned. Returns the serving-ready store."""
    from kubeflow_tpu.testing.fake_apiserver import FakeApiServer

    # The inode fence is a PyWal behavior: native/src/wal.cc still
    # truncates wal.log IN PLACE (O_TRUNC on the same inode), which
    # would leave a deposed predecessor's fd pointed at the LIVE log.
    # Until the native WAL ports truncate-by-replacement (ROADMAP open
    # item #1), HA stores pin the Python backend rather than silently
    # weakening the fence on hosts where the native tier builds.
    api_kwargs.setdefault("wal_backend", "python")
    api = FakeApiServer(
        persist_dir=persist_dir,
        wal_wrap=term_fence(leases, lease_name, holder, transitions),
        **api_kwargs,
    )
    api.checkpoint()
    return api
