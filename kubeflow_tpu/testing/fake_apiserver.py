"""An in-process API server with K8s storage semantics.

Implements the parts of the K8s resource model that controller correctness
depends on (the reference leaned on envtest for exactly this,
`profile-controller/controllers/suite_test.go:29-54`):

- optimistic concurrency (resourceVersion conflict on stale writes)
- spec/status as separate update surfaces
- label selectors on list
- watch events (ADDED/MODIFIED/DELETED) delivered to subscribers
- finalizers: delete marks deletionTimestamp; removal happens when the
  last finalizer is cleared
- owner references: cascading delete of dependents
- multi-version kinds: writes at any served apiVersion are converted to
  the kind's storage (hub) version before storing; readers may request a
  served version (the reference's Notebook CRD carries three versions
  plus conversion, `notebook-controller/api/*/notebook_types.go`)

Thread-safe. Watch delivery is ASYNCHRONOUS on a dedicated dispatcher
thread, off the store lock — a slow handler delays delivery, never
writers; `flush()` is the barrier deterministic tests drain on (the
controller runtime's run_until_idle calls it automatically).

Storage is copy-on-write (docs/perf.md): each commit deep-copies the
incoming object ONCE, freezes it, and shares that immutable snapshot
with the object map, the per-(kind, namespace) index, the journal, the
dispatch queue, every watch handler, and get/list/create/update return
values — fan-out costs zero copies per watcher. Consumers treat
results as read-only; `.thaw()` yields a private mutable copy, and
mutating a frozen snapshot raises FrozenResourceError instead of
corrupting other consumers.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Iterable

from kubeflow_tpu.api import versioning
from kubeflow_tpu.api.objects import ObjectMeta, Resource, fresh_uid, now

WatchHandler = Callable[[str, Resource], None]  # (event_type, obj)

log = logging.getLogger(__name__)


class ApiError(Exception):
    pass


class NotFound(ApiError):
    pass


class AlreadyExists(ApiError):
    pass


class Conflict(ApiError):
    pass


class Invalid(ApiError):
    pass


class Forbidden(ApiError, PermissionError):
    """A 401/403 from the secure facade. ApiError so per-object error
    handling (e.g. the CLI's multi-doc apply) reports it and continues;
    PermissionError so callers can treat auth failures as a class."""


class Gone(ApiError):
    """The requested resourceVersion predates the journal's oldest entry
    (the real apiserver's HTTP 410 on an expired watch bookmark). Clients
    recover the way informers do: re-list, then watch from the list's
    resourceVersion."""


class Unavailable(ApiError):
    """The store fail-stopped: a WAL write failed, so in-memory state may
    have run ahead of the durable log. Serving on would expose writes a
    restart silently loses (and a later snapshot would wrongly
    legitimize the divergence), so every operation refuses until the
    process restarts over the intact log — etcd's own posture when its
    backend errors. Maps to HTTP 503."""


def _matches(labels: dict[str, str], selector: dict[str, str]) -> bool:
    return all(labels.get(k) == v for k, v in selector.items())


class KindIndex:
    """Per-(kind, namespace) index over frozen Resource snapshots —
    kind -> namespace -> name -> Resource — shared by BOTH store
    backends (FakeApiServer's object index and NativeApiServer's
    snapshot mirror), so list ordering, selector filtering, and
    empty-bucket pruning can never drift between them (the
    select_journal_events unification, applied to reads). NOT
    synchronized: callers hold their store's lock."""

    def __init__(self):
        self._by_kind: dict[str, dict[str, dict[str, Resource]]] = {}

    def put(self, obj: Resource) -> None:
        self._by_kind.setdefault(obj.kind, {}).setdefault(
            obj.metadata.namespace, {}
        )[obj.metadata.name] = obj

    def pop(self, kind: str, namespace: str, name: str) -> None:
        by_ns = self._by_kind.get(kind)
        if by_ns is None:
            return
        names = by_ns.get(namespace)
        if names is not None:
            names.pop(name, None)
            if not names:
                del by_ns[namespace]
        if not by_ns:
            del self._by_kind[kind]

    def get(
        self, kind: str, namespace: str, name: str
    ) -> Resource | None:
        return (
            self._by_kind.get(kind, {}).get(namespace, {}).get(name)
        )

    def list(
        self,
        kind: str,
        namespace: str | None = None,
        label_selector: dict[str, str] | None = None,
    ) -> list[Resource]:
        """Frozen shared snapshots, (namespace, name)-ordered:
        O(result), not O(store)."""
        by_ns = self._by_kind.get(kind, {})
        if namespace is not None:
            spaces = [namespace] if namespace in by_ns else []
        else:
            spaces = sorted(by_ns)
        out = []
        for ns in spaces:
            names = by_ns[ns]
            for name in sorted(names):
                obj = names[name]
                if label_selector and not _matches(
                    obj.metadata.labels, label_selector
                ):
                    continue
                out.append(obj)
        return out

    def kinds(self) -> list[str]:
        """Kinds with live objects (empty buckets are pruned on pop)."""
        return sorted(self._by_kind)


def event_name(
    about: Resource, reason: str, message: str, type_: str = "Normal"
) -> str:
    """Content-derived Event name, shared by every event emitter (both
    stores and the HTTP client). The same logical occurrence always maps
    to the same name, so a RETRIED emission — a controller replaying a
    write whose ack was lost — collides with its first attempt
    (AlreadyExists, absorbed by the emitters) instead of duplicating it.
    Repeat occurrences with identical text collapse the same way, which
    is K8s's own event-aggregation posture."""
    import hashlib

    digest = hashlib.sha1(
        "\x00".join(
            (
                about.kind,
                about.metadata.namespace or "",
                about.metadata.name,
                str(about.metadata.uid),
                reason,
                message,
                type_,
            )
        ).encode()
    ).hexdigest()[:10]
    return f"{about.metadata.name}.{digest}"


def event_resource(
    about: Resource, reason: str, message: str, *, type_: str = "Normal"
) -> Resource:
    """The K8s-style Event object every emitter records (the reference
    mirrors these onto CR statuses, `notebook_controller.go:87-103`)."""
    return Resource(
        kind="Event",
        metadata=ObjectMeta(
            name=event_name(about, reason, message, type_),
            namespace=about.metadata.namespace,
        ),
        spec={
            "involvedObject": {
                "kind": about.kind,
                "name": about.metadata.name,
                "uid": about.metadata.uid,
            },
            "reason": reason,
            "message": message,
            "type": type_,
        },
        status={},
    )


def select_journal_events(
    journal,
    floor: int,
    current_rv: int,
    resource_version: int,
    kind: str | None,
    namespace: str | None,
):
    """The journal read contract, shared by BOTH store backends (the
    caller holds its store's lock): entries with rv > resource_version,
    filtered by kind/namespace, plus the rv to resume from; Gone when
    the bookmark predates the floor or the journal's trimmed horizon.
    One implementation so the 410 math can never drift between the
    Python and native apiservers.

    The journal is rv-ordered (every commit appends with a strictly
    increasing rv), so the resume point is a binary search, not a scan;
    and entries are frozen shared snapshots (docs/perf.md), so serving
    a bookmark costs zero copies."""
    import bisect
    from operator import itemgetter

    if resource_version < floor:
        raise Gone(
            f"resourceVersion {resource_version} predates this "
            f"server's history (floor {floor}) — relist"
        )
    if journal and resource_version < journal[0][0] - 1:
        raise Gone(
            f"resourceVersion {resource_version} is too old "
            f"(journal begins at {journal[0][0]})"
        )
    start = bisect.bisect_right(
        journal, resource_version, key=itemgetter(0)
    )
    out = [
        (rv, event, obj)
        for rv, event, obj in journal[start:]
        if (kind is None or obj.kind == kind)
        and (namespace is None or obj.metadata.namespace == namespace)
    ]
    return out, current_rv


def wait_journal_events(
    cv,
    events_since,
    resource_version: int,
    kind: str | None,
    namespace: str | None,
    timeout: float,
):
    """The long-poll half of the journal contract, shared by both
    backends: block on `cv` until events land past the bookmark or the
    timeout passes (empty batch + current rv). `events_since` must be
    callable under `cv`'s lock."""
    deadline = time.monotonic() + timeout
    with cv:
        while True:
            events, rv = events_since(
                resource_version, kind=kind, namespace=namespace
            )
            if events:
                return events, rv
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return [], rv
            cv.wait(remaining)


def check_lease_guard(get_lease_spec, guard, kind: str) -> None:
    """Write fencing, shared by BOTH store backends (the caller holds
    its store's commit lock, so the check is atomic with the write): a
    guarded write lands only while its lease still shows the presented
    holder AND generation. A deposed leader resurfacing after a
    partition — even one whose final write was already in flight — gets
    a Conflict instead of mutating state its successor owns. Lease
    writes themselves are exempt (the election protocol is
    self-arbitrating via rv CAS and must stay able to transfer
    ownership). `get_lease_spec(ns, name)` returns the lease's spec
    dict, or None when it does not exist."""
    if guard is None or kind == "Lease":
        return
    ns, name, holder, transitions = guard
    spec = get_lease_spec(ns, name)
    if (
        spec is None
        or spec.get("holderIdentity") != holder
        or int(spec.get("leaseTransitions", 0)) != int(transitions)
    ):
        current = (
            f"held by {spec.get('holderIdentity')!r} generation "
            f"{spec.get('leaseTransitions')}"
            if spec is not None
            else "gone"
        )
        raise Conflict(
            f"fenced: lease {ns or '_'}/{name} is {current}; writer "
            f"presented {holder!r} generation {transitions} — a "
            f"deposed leader must not write into its successor's term"
        )


class FakeApiServer:
    def __init__(
        self,
        *,
        journal_size: int = 10_000,
        persist_dir: str | None = None,
        snapshot_every: int = 1_000,
        wal_backend: str = "auto",
        wal_wrap=None,
    ):
        self._objects: dict[tuple[str, str, str], Resource] = {}
        # Per-(kind, namespace) index over the same frozen snapshots,
        # so list()/filtering touch only the kind+namespace asked for
        # instead of scanning the whole store (docs/perf.md).
        self._index = KindIndex()
        self._rv = 0
        # Events at or below the floor are unknowable (pre-restart, or
        # trimmed): watch bookmarks under it get Gone → relist.
        self._floor = 0
        self._lock = threading.RLock()
        self._watchers: list[tuple[str | None, WatchHandler]] = []
        self._admission: list[tuple[str | None, Callable[[Resource], Resource]]] = []
        # Resumable event journal: (resourceVersion, event, object), rv-
        # ordered. This is what the real apiserver keeps in etcd's event
        # history and serves on `GET ...?watch=true&resourceVersion=N`;
        # bounded, with Gone (410) past the horizon.
        self._journal: list[tuple[int, str, Resource]] = []
        self._journal_size = journal_size
        self._journal_cv = threading.Condition(self._lock)
        # In-process handler dispatch runs on a dedicated thread, OFF the
        # store lock: a slow/blocking handler delays event delivery, not
        # writers (the apiserver's watch cache serves watchers the same
        # way — writers never wait for consumers). The journal append
        # stays under the lock so journal order is rv order; the queue
        # preserves that order for handlers (single consumer).
        self._dispatch_cv = threading.Condition()
        self._dispatch_q: list[tuple[str, Resource]] = []
        self._dispatch_enqueued = 0
        self._dispatch_done = 0
        self._dispatcher: threading.Thread | None = None
        # Durable store (WAL+snapshot; `testing/persist.py`). The
        # reference gets this from etcd (`suite_test.go:29-54`); here the
        # server is durable exactly when a persist_dir is given: every
        # committed write is fsync'd to the WAL before its watch event is
        # emitted, and a restart over the same directory restores state.
        # Index of stored WebhookConfiguration keys: the zero-webhook
        # common case must cost writes nothing (a full-store list per
        # create would be O(N log N) under the lock).
        self._webhook_keys: set[tuple[str, str, str]] = set()
        self._wal = None
        self._snapshot_every = max(1, snapshot_every)
        self._appends_since_snapshot = 0
        # Set on the first WAL/snapshot IO failure; every public op then
        # raises Unavailable (see _fail_stop_locked).
        self._broken: BaseException | None = None
        if persist_dir is not None:
            from kubeflow_tpu.testing import persist

            self._wal = persist.open_wal(persist_dir, backend=wal_backend)
            if wal_wrap is not None:
                # Active-passive term fencing (`testing/failover.py`):
                # the wrapper verifies this process still owns the
                # apiserver lease before every durable write, so a
                # deposed active fail-stops instead of acking writes its
                # successor will never replay.
                self._wal = wal_wrap(self._wal)
            # Construction runs before any thread shares this server,
            # but _restore can checkpoint a torn tail and
            # _checkpoint_locked's contract is caller-holds-lock — hold
            # it for real (RLock, uncontended) instead of by argument.
            with self._lock:
                self._restore_locked()

    # -- storage (copy-on-write commit point) -----------------------------

    def _store_obj(self, stored: Resource) -> Resource:
        """THE commit point: freeze the (already private) copy and
        install it in the object map + per-(kind, namespace) index.
        Everything downstream — journal, dispatch, watchers, get/list —
        shares this frozen snapshot; nothing copies it again."""
        stored.freeze()
        key = stored.key
        self._objects[key] = stored
        self._index.put(stored)
        if stored.kind == self.WEBHOOK_KIND:
            self._webhook_keys.add(key)
        return stored

    def _unstore(self, key: tuple[str, str, str]) -> Resource:
        obj = self._objects.pop(key)
        self._index.pop(*key)
        self._webhook_keys.discard(key)
        return obj

    # -- persistence ------------------------------------------------------

    def _restore_locked(self) -> None:
        """Load snapshot + replay WAL (construction time; the caller
        holds the lock for _checkpoint_locked's torn-tail repair).
        Replay stops at the first undecodable line — a torn tail from a
        crash mid-append loses only the un-acked record. Records at or
        below the snapshot's rv are skipped (a crash between snapshot
        rename and WAL truncate legally leaves them behind)."""
        import json as _json

        from kubeflow_tpu.testing.persist import FORMAT

        snap_text = self._wal.read_snapshot()
        if snap_text:
            try:
                snap = _json.loads(snap_text)
            except ValueError as e:
                raise Invalid(f"corrupt snapshot: {e}") from e
            if snap.get("format") != FORMAT:
                raise Invalid(
                    f"snapshot format {snap.get('format')!r} is not "
                    f"{FORMAT} — refusing to guess at a migration"
                )
            for d in snap.get("objects", []):
                self._store_obj(Resource.from_dict(d))
            self._rv = int(snap.get("rv", 0))
        torn = False
        for line in self._wal.read_journal().splitlines():
            try:
                rec = _json.loads(line)
                rv = int(rec["rv"])
                event = rec["event"]
                obj = Resource.from_dict(rec["object"])
            except (ValueError, KeyError, TypeError):
                log.warning("WAL replay stopped at torn/corrupt record")
                torn = True
                break
            if rv <= self._rv:
                continue  # pre-snapshot leftover
            if event == "DELETED":
                if obj.key in self._objects:
                    self._unstore(obj.key)
            else:
                self._store_obj(obj)
            self._rv = rv
        if torn:
            # REPAIR the log now: the WAL reopens in append mode, so the
            # next acked write would otherwise glue onto the partial
            # line and be silently dropped by the NEXT restart's replay
            # (an acked, fsync'd write lost). Folding state into a fresh
            # snapshot truncates the torn tail away.
            self._checkpoint_locked()
        # Watchers resuming from before the restart can't be served from
        # the (empty) in-memory journal: 410 Gone → they relist.
        self._floor = self._rv

    def _fail_stop_locked(self, cause: BaseException) -> None:
        """Durable-write failure (disk full, IO error): the in-memory
        mutation that triggered it has NOT reached the journal or any
        watcher yet, but it is in self._objects — so rather than audit a
        rollback at every mutation site, stop serving entirely. The
        divergent state is then unobservable (all ops raise) and can
        never be checkpointed (the WAL handle is dropped, so close()/
        checkpoint() no-op instead of snapshotting un-logged writes)."""
        self._broken = cause
        wal, self._wal = self._wal, None
        if wal is not None:
            try:
                wal.close()
            except Exception:
                pass
        log.error("store fail-stopped after persistence failure: %s", cause)
        raise Unavailable(
            f"store fail-stopped after a persistence failure: {cause}"
        ) from cause

    def _check_available(self) -> None:
        if self._broken is not None:
            raise Unavailable(
                f"store fail-stopped after a persistence failure: "
                f"{self._broken}"
            )

    def _check_lease_guard(self, guard, kind: str) -> None:
        """Shared fencing contract — see module-level check_lease_guard
        (caller holds the lock, so check+commit is atomic)."""

        def lookup(ns: str, name: str):
            lease = self._objects.get(("Lease", ns, name))
            return lease.spec if lease is not None else None

        check_lease_guard(lookup, guard, kind)

    def _persist_locked(self, event: str, obj: Resource) -> None:
        """WAL-append one committed write (caller holds the lock). Runs
        BEFORE the in-memory journal append / watch delivery: an event a
        watcher saw must never be missing after a crash."""
        import json as _json

        try:
            self._wal.append(
                _json.dumps(
                    {
                        "rv": obj.metadata.resource_version,
                        "event": event,
                        "object": obj.to_dict(),
                    },
                    separators=(",", ":"),
                )
            )
            self._appends_since_snapshot += 1
            if self._appends_since_snapshot >= self._snapshot_every:
                self._checkpoint_locked()
        except ApiError:
            raise
        except Exception as e:
            self._fail_stop_locked(e)

    def _checkpoint_locked(self) -> None:
        import json as _json

        from kubeflow_tpu.testing.persist import FORMAT

        try:
            self._wal.snapshot(
                _json.dumps(
                    {
                        "format": FORMAT,
                        "rv": self._rv,
                        "objects": [
                            o.to_dict()
                            for _, o in sorted(self._objects.items())
                        ],
                    },
                    separators=(",", ":"),
                )
            )
        except Exception as e:
            self._fail_stop_locked(e)
        self._appends_since_snapshot = 0

    def checkpoint(self) -> None:
        """Fold the WAL into a fresh snapshot now (graceful shutdown, or
        bounding recovery time). No-op without persistence."""
        with self._lock:
            if self._wal is not None:
                self._checkpoint_locked()

    def close(self) -> None:
        """Checkpoint (if durable) and release the WAL handles."""
        with self._lock:
            if self._wal is not None:
                self._checkpoint_locked()
                self._wal.close()
                self._wal = None

    # -- admission --------------------------------------------------------

    def register_admission(
        self, mutator: Callable[[Resource], Resource], kind: str | None = None
    ) -> None:
        """Mutating-admission hook applied on create AND update (real
        mutating webhooks fire on both; the reference's boundary is
        `admission-webhook/main.go:447`). Mutators must be idempotent —
        updates re-run them over an already-mutated object. In-process
        hooks run INSIDE the store lock (quota's check-then-insert needs
        the atomicity); third-party mutators belong in a
        WebhookConfiguration callout instead (see _webhook_admit)."""
        with self._lock:
            self._admission.append((kind, mutator))

    def _admit(self, obj: Resource) -> Resource:
        for kind, mutator in list(self._admission):
            if kind is None or kind == obj.kind:
                obj = mutator(obj.deepcopy())
        return obj

    # -- webhook admission (the out-of-process extension point) ------------
    #
    # The reference's admission boundary is a STANDALONE TLS server the
    # apiserver calls out to (`admission-webhook/main.go:443` raw TLS,
    # `:447` mutatePods, `:597` main), registered via a webhook
    # configuration with timeout + failure semantics. Here that boundary
    # is a `WebhookConfiguration` CR:
    #
    #   spec:
    #     url: https://127.0.0.1:9443/mutate   (https only)
    #     caBundle: <inline PEM>               (pins the callee)
    #     kinds: ["Pod"]
    #     namespaces: ["team-a"]               (optional; [] = all — the
    #                                           namespaceSelector analog)
    #     selector: {matchLabels: {...}}       (optional objectSelector)
    #     timeoutSeconds: 5
    #     failurePolicy: Fail | Ignore         (default Fail)
    #
    # Callouts run OUTSIDE the store lock (an HTTP round trip must never
    # stall every writer) and BEFORE the in-process hooks — the K8s
    # ordering (mutating webhooks, then validating admission), which
    # also means quota meters the POST-mutation object and keeps its
    # in-lock check-then-insert atomicity untouched.

    WEBHOOK_KIND = "WebhookConfiguration"

    def _validate_webhook_config(self, obj: Resource) -> None:
        spec = obj.spec
        url = spec.get("url", "")
        if not url.startswith("https://"):
            raise Invalid(
                f"WebhookConfiguration {obj.metadata.name!r}: url must be "
                f"https:// (the admission callee carries object payloads; "
                f"got {url!r})"
            )
        policy = spec.get("failurePolicy", "Fail")
        if policy not in ("Fail", "Ignore"):
            raise Invalid(
                f"WebhookConfiguration {obj.metadata.name!r}: "
                f"failurePolicy must be Fail or Ignore, got {policy!r}"
            )
        kinds = spec.get("kinds")
        if not isinstance(kinds, list) or not kinds:
            raise Invalid(
                f"WebhookConfiguration {obj.metadata.name!r}: spec.kinds "
                "must be a non-empty list of kind names"
            )
        if self.WEBHOOK_KIND in kinds:
            raise Invalid(
                f"WebhookConfiguration {obj.metadata.name!r}: a webhook "
                "cannot admit WebhookConfigurations (self-bricking loop)"
            )
        timeout = spec.get("timeoutSeconds", 5)
        if not isinstance(timeout, (int, float)) or isinstance(
            timeout, bool
        ) or not timeout > 0:
            # Config-time 422, not a per-write "webhook failure" later.
            raise Invalid(
                f"WebhookConfiguration {obj.metadata.name!r}: "
                f"timeoutSeconds must be a positive number, got "
                f"{timeout!r}"
            )
        from kubeflow_tpu.web.tls import is_pem_data

        ca = spec.get("caBundle", "")
        if ca and not is_pem_data(ca):
            # Inline PEM only (the K8s caBundle form). A filesystem path
            # here would make the APISERVER open an arbitrary local file
            # chosen by whoever may create webhookconfigurations, and
            # would silently break for remote clients whose path doesn't
            # exist server-side. make_webhook_config inlines a readable
            # local path client-side for the legacy convenience.
            raise Invalid(
                f"WebhookConfiguration {obj.metadata.name!r}: caBundle "
                "must be inline PEM data (paths are resolved client-side)"
            )

    def _call_webhook(
        self, cfg: Resource, obj: Resource, operation: str
    ) -> Resource:
        import json as _json
        import urllib.request

        from kubeflow_tpu.web import tls as tlsmod

        spec = cfg.spec
        timeout = min(float(spec.get("timeoutSeconds", 5)), 30.0)
        ctx = None
        if spec.get("caBundle"):
            ctx = tlsmod.client_context(spec["caBundle"])
        req = urllib.request.Request(
            spec["url"],
            method="POST",
            data=_json.dumps(
                {"object": obj.to_dict(), "operation": operation}
            ).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=timeout, context=ctx) as r:
            resp = _json.loads(r.read())
        if not resp.get("allowed", False):
            # A DENIAL is an admission decision, not a webhook failure:
            # it rejects under BOTH failure policies.
            raise Invalid(
                f"admission webhook {cfg.metadata.name!r} denied "
                f"{obj.kind} {obj.metadata.namespace}/"
                f"{obj.metadata.name}: {resp.get('message', 'denied')}"
            )
        if "object" not in resp:
            return obj
        mutated = Resource.from_dict(resp["object"])
        # A mutator only gets to change spec/labels/annotations — never
        # identity or concurrency fields. A swapped kind would bypass
        # the per-kind validation that ran before the callout; a dropped
        # resourceVersion would disable the stale-write Conflict check;
        # a changed name/namespace would write a different store key
        # than the client asked for. (K8s enforces the same immutable
        # fields on webhook patches.)
        before = (
            obj.kind, obj.metadata.name, obj.metadata.namespace,
            obj.metadata.uid, obj.metadata.resource_version,
            obj.api_version, obj.status,
        )
        after = (
            mutated.kind, mutated.metadata.name,
            mutated.metadata.namespace, mutated.metadata.uid,
            mutated.metadata.resource_version, mutated.api_version,
            # status too: the facade strips status from clients without
            # the <resource>/status grant BEFORE admission runs — a
            # webhook forging phase=Succeeded would bypass that guard.
            mutated.status,
        )
        if before != after:
            raise Invalid(
                f"admission webhook {cfg.metadata.name!r} altered "
                f"immutable fields of {obj.kind} "
                f"{obj.metadata.namespace}/{obj.metadata.name} "
                f"({before} -> {after}) — mutation rejected"
            )
        return mutated

    def _webhook_admit(self, obj: Resource, operation: str) -> Resource:
        """Run matching webhook callouts over `obj` (lock NOT held
        during the HTTP round trips)."""
        if obj.kind == self.WEBHOOK_KIND:
            self._validate_webhook_config(obj)
            return obj
        if not self._webhook_keys:
            return obj  # the common case costs one set check

        def _matches_cfg(spec: dict) -> bool:
            if obj.kind not in (spec.get("kinds") or []):
                return False
            namespaces = spec.get("namespaces") or []
            if namespaces and obj.metadata.namespace not in namespaces:
                return False  # the namespaceSelector analog
            selector = (spec.get("selector") or {}).get("matchLabels") or {}
            return _matches(obj.metadata.labels, selector)  # objectSelector

        with self._lock:
            # Frozen snapshots — the callout only reads cfg.spec.
            configs = [
                self._objects[k]
                for k in sorted(self._webhook_keys)
                if k in self._objects
                and _matches_cfg(self._objects[k].spec)
            ]
        for cfg in configs:  # key-sorted: deterministic order
            try:
                obj = self._call_webhook(cfg, obj, operation)
            except Invalid:
                raise  # an explicit denial, under either policy
            except Exception as e:
                if cfg.spec.get("failurePolicy", "Fail") == "Ignore":
                    log.warning(
                        "admission webhook %s unreachable (%s); "
                        "failurePolicy=Ignore — admitting unmodified",
                        cfg.metadata.name, e,
                    )
                    continue
                raise Invalid(
                    f"admission webhook {cfg.metadata.name!r} failed "
                    f"({e}) and failurePolicy=Fail — rejecting "
                    f"{obj.kind} {obj.metadata.name!r}"
                ) from e
        return obj

    # -- watch ------------------------------------------------------------

    def watch(self, handler: WatchHandler, kind: str | None = None) -> None:
        """Subscribe to events; kind=None receives everything. The first
        subscription starts the dispatcher thread (stores nobody watches
        never pay for one). Handlers receive the SHARED frozen snapshot
        (read-only; `.thaw()` for a private mutable copy)."""
        with self._lock:
            self._watchers.append((kind, handler))
        with self._dispatch_cv:
            if self._dispatcher is None:
                self._dispatcher = threading.Thread(
                    target=self._dispatch_loop,
                    name="apiserver-dispatch",
                    daemon=True,
                )
                self._dispatcher.start()

    def _emit(self, event: str, obj: Resource) -> None:
        # Authoritative fail-stop check, under the lock (every caller
        # holds it): a writer that slipped past an unlocked precheck
        # while another thread fail-stopped must not see its event
        # journaled/delivered with persistence silently gone.
        self._check_available()
        # The copy-on-write contract: callers emit the frozen committed
        # snapshot, which the journal, the dispatch queue, and every
        # handler then SHARE — zero copies from here on (docs/perf.md).
        assert obj.frozen, "emit requires the frozen committed snapshot"
        # Durability first: the WAL append (fsync'd) happens before any
        # watcher can observe the event, so an acked write survives a
        # crash that follows it.
        if self._wal is not None:
            self._persist_locked(event, obj)
        # Journal under the lock (all callers hold it) so journal order is
        # resourceVersion order — a watcher resuming from rv N can never
        # miss an event that commits with rv > N after N was served.
        with self._journal_cv:
            # rv-sortedness is load-bearing: the bisect resume in
            # select_journal_events is undefined on unsorted data. Any
            # emit site that would append out of order (the old
            # finalize-then-cascade shape) must fail HERE, not as a
            # silently dropped resume event at some watcher later.
            assert (
                not self._journal
                or obj.metadata.resource_version > self._journal[-1][0]
            ), "journal emit out of rv order"
            self._journal.append(
                (obj.metadata.resource_version, event, obj)
            )
            if len(self._journal) > self._journal_size:
                del self._journal[: -self._journal_size]
            self._journal_cv.notify_all()
        if not self._watchers:
            return  # nobody to deliver to (late watchers get no replay)
        with self._dispatch_cv:
            self._dispatch_q.append((event, obj))
            self._dispatch_enqueued += 1
            self._dispatch_cv.notify_all()

    def _dispatch_loop(self) -> None:
        while True:
            with self._dispatch_cv:
                while not self._dispatch_q:
                    self._dispatch_cv.wait()
                event, obj = self._dispatch_q.pop(0)
            with self._lock:
                watchers = list(self._watchers)
            # Every handler gets THE SAME frozen snapshot: a handler
            # that mutates raises FrozenResourceError (and .thaw() is
            # its private-copy escape hatch) instead of corrupting its
            # peers — the old per-handler defensive copy's isolation,
            # now at zero copies per delivery.
            for kind, handler in watchers:
                if kind is None or kind == obj.kind:
                    try:
                        handler(event, obj)
                    except Exception:
                        log.exception(
                            "watch handler failed for %s %s", event, obj.key
                        )
            with self._dispatch_cv:
                self._dispatch_done += 1
                self._dispatch_cv.notify_all()

    def flush(self, timeout: float = 30.0) -> None:
        """Block until every event emitted so far has been delivered to
        all in-process handlers — the barrier deterministic test drivers
        (run_until_idle) sit on now that dispatch is asynchronous."""
        deadline = time.monotonic() + timeout
        with self._dispatch_cv:
            while self._dispatch_done < self._dispatch_enqueued:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"event dispatch did not drain "
                        f"({self._dispatch_done}/{self._dispatch_enqueued})"
                    )
                self._dispatch_cv.wait(remaining)

    @property
    def current_rv(self) -> int:
        with self._lock:
            return self._rv

    def events_since(
        self,
        resource_version: int,
        kind: str | None = None,
        namespace: str | None = None,
    ) -> tuple[list[tuple[int, str, Resource]], int]:
        """Journal entries with rv > resource_version, filtered; plus the
        server's current rv (the resume point even when nothing matched
        the filter). Raises Gone when the bookmark predates the journal."""
        with self._lock:
            self._check_available()
            return select_journal_events(
                self._journal, self._floor, self._rv,
                resource_version, kind, namespace,
            )

    def wait_events(
        self,
        resource_version: int,
        kind: str | None = None,
        namespace: str | None = None,
        timeout: float = 10.0,
    ) -> tuple[list[tuple[int, str, Resource]], int]:
        """Long-poll form of events_since — the shared
        wait_journal_events loop (one implementation across backends)."""
        return wait_journal_events(
            self._journal_cv, self.events_since,
            resource_version, kind, namespace, timeout,
        )

    # -- CRUD -------------------------------------------------------------

    def _normalize_version(self, obj: Resource) -> Resource:
        """Convert a write at any served version to storage form; an
        unserved version of a registered kind is a client error."""
        try:
            return versioning.registry.normalize(obj)
        except versioning.ConversionError as e:
            raise Invalid(str(e)) from e

    def convert_to(self, obj: Resource, version: str) -> Resource:
        """Read-side conversion: a stored (hub-version) object rendered at
        another served version."""
        try:
            return versioning.registry.convert(obj, version)
        except versioning.ConversionError as e:
            raise Invalid(str(e)) from e

    def create(
        self, obj: Resource, *, lease_guard=None
    ) -> Resource:
        self._check_available()
        obj = self._normalize_version(obj)
        # Webhook callouts OUTSIDE the lock (an HTTP round trip must not
        # stall writers), before in-process hooks (the K8s mutating →
        # validating order, so quota meters the post-mutation object).
        obj = self._webhook_admit(obj, "CREATE")
        with self._lock:
            self._check_lease_guard(lease_guard, obj.kind)
            # Admission INSIDE the critical section: validating hooks
            # (quota) read current state, and check-then-insert must be
            # atomic or two concurrent creates can both pass a cap.
            # Hooks may re-enter the store (RLock).
            obj = self._admit(obj)
            key = obj.key
            if key in self._objects:
                raise AlreadyExists(f"{key} already exists")
            # THE one copy per commit (docs/perf.md): everything from
            # here — store, index, journal, dispatch, return value —
            # shares the frozen `stored` snapshot.
            stored = obj.deepcopy()
            self._rv += 1
            stored.metadata.uid = fresh_uid()
            stored.metadata.resource_version = self._rv
            stored.metadata.generation = 1
            stored.metadata.creation_timestamp = now()
            self._store_obj(stored)
            self._emit("ADDED", stored)
        return stored

    def get(self, kind: str, name: str, namespace: str = "default") -> Resource:
        with self._lock:
            self._check_available()
            obj = self._objects.get((kind, namespace, name))
            if obj is None:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            return obj  # frozen shared snapshot; .thaw() to mutate

    def kinds(self) -> list[str]:
        """Distinct kinds with live objects (quota's count/<resource>
        inverse needs the real kind strings — resource_for_kind is lossy
        for CamelCase, so there is no static inverse)."""
        with self._lock:
            self._check_available()
            return sorted({k[0] for k in self._objects})

    def list(
        self,
        kind: str,
        namespace: str | None = None,
        label_selector: dict[str, str] | None = None,
    ) -> list[Resource]:
        """Frozen shared snapshots, (namespace, name)-ordered. Served
        from the shared per-(kind, namespace) index: O(result), not
        O(store)."""
        with self._lock:
            self._check_available()
            return self._index.list(kind, namespace, label_selector)

    def _update(
        self, obj: Resource, *, status_only: bool, lease_guard=None
    ) -> Resource:
        with self._lock:
            self._check_available()
            self._check_lease_guard(lease_guard, obj.kind)
            key = obj.key
            current = self._objects.get(key)
            if current is None:
                raise NotFound(f"{key} not found")
            if (
                obj.metadata.resource_version
                and obj.metadata.resource_version
                != current.metadata.resource_version
            ):
                raise Conflict(
                    f"{key}: stale resourceVersion "
                    f"{obj.metadata.resource_version} != "
                    f"{current.metadata.resource_version}"
                )
            # THE one copy per commit: `current` stays the previous
            # frozen snapshot (journal entries and readers may still
            # hold it); `stored` becomes the new one.
            stored = current.deepcopy()
            if status_only:
                stored.status = Resource.from_dict(obj.to_dict()).status
            else:
                incoming = Resource.from_dict(obj.to_dict())
                if incoming.spec != stored.spec:
                    stored.metadata.generation += 1
                stored.spec = incoming.spec
                stored.metadata.labels = incoming.metadata.labels
                stored.metadata.annotations = incoming.metadata.annotations
                stored.metadata.finalizers = incoming.metadata.finalizers
                stored.metadata.owner_references = (
                    incoming.metadata.owner_references
                )
            self._rv += 1
            stored.metadata.resource_version = self._rv
            self._store_obj(stored)
            if not self._maybe_finalize(stored):
                self._emit("MODIFIED", stored)
        return stored

    def update(self, obj: Resource, *, lease_guard=None) -> Resource:
        # Fast-fail precheck (authoritative re-check is in _emit, under
        # the lock): a fail-stopped store must not keep firing webhook
        # HTTP callouts for writes that can never commit.
        self._check_available()
        # Same two-phase admission as create: webhooks off-lock first.
        obj = self._webhook_admit(self._normalize_version(obj), "UPDATE")
        with self._lock:  # in-process admission atomic with the write
            return self._update(
                self._admit(obj), status_only=False,
                lease_guard=lease_guard,
            )

    def update_status(self, obj: Resource, *, lease_guard=None) -> Resource:
        return self._update(obj, status_only=True, lease_guard=lease_guard)

    def delete(
        self,
        kind: str,
        name: str,
        namespace: str = "default",
        *,
        lease_guard=None,
    ) -> None:
        with self._lock:
            self._check_available()
            self._check_lease_guard(lease_guard, kind)
            key = (kind, namespace, name)
            obj = self._objects.get(key)
            if obj is None:
                raise NotFound(f"{key} not found")
            if obj.metadata.finalizers:
                if obj.metadata.deletion_timestamp is None:
                    # Marking deletion is a commit of its own: copy once
                    # (prior snapshot stays shared with the journal).
                    stored = obj.thaw()
                    stored.metadata.deletion_timestamp = now()
                    self._rv += 1
                    stored.metadata.resource_version = self._rv
                    self._store_obj(stored)
                    self._emit("MODIFIED", stored)
                return
            self._remove_locked(key)

    def _maybe_finalize(self, stored: Resource) -> bool:
        """Remove an object whose deletion was pending and whose last
        finalizer was just cleared (emitting its DELETED). Returns True
        if removed. The DELETED is journaled BEFORE the cascade runs:
        cascaded children get fresh (higher) rvs, so emitting the parent
        first is what keeps the journal rv-sorted — the invariant the
        bisect resume in select_journal_events depends on."""
        if (
            stored.metadata.deletion_timestamp is not None
            and not stored.metadata.finalizers
        ):
            self._emit("DELETED", stored)
            self._remove_locked(stored.key, emit_delete=False)
            return True
        return False

    def _remove_locked(self, key: tuple, *, emit_delete: bool = True) -> None:
        obj = self._unstore(key)
        if emit_delete:
            # Deletion is a state transition of its own: give the DELETED
            # event a fresh rv so a watcher resuming from the object's
            # last-seen version still observes the removal. The stamp
            # goes on a private copy — the popped snapshot is still
            # shared with the journal/readers at its old rv.
            obj = obj.thaw()
            self._rv += 1
            obj.metadata.resource_version = self._rv
            obj.freeze()
            self._emit("DELETED", obj)
        self._cascade(obj)
        if obj.kind == "Namespace":
            self._drain_namespace(obj.metadata.name)

    def _drain_namespace(self, namespace: str) -> None:
        """Real apiserver semantics: deleting a Namespace deletes every
        namespaced object inside it (not just owner-ref dependents)."""
        for kind, ns, name in [
            k for k in self._objects if k[1] == namespace
        ]:
            try:
                self.delete(kind, name, ns)
            except NotFound:
                pass

    def _cascade(self, owner: Resource) -> None:
        """Delete dependents whose controller ownerReference matches."""
        uid = owner.metadata.uid
        dependents = [
            o.key
            for o in list(self._objects.values())
            if any(
                ref.get("uid") == uid
                for ref in o.metadata.owner_references
            )
        ]
        for key in dependents:
            if key in self._objects:
                kind, ns, name = key
                try:
                    self.delete(kind, name, ns)
                except NotFound:
                    pass

    # -- conveniences ------------------------------------------------------

    def apply(self, obj: Resource, *, lease_guard=None) -> Resource:
        """Create-or-update by (kind, ns, name) — the reconcilehelper
        pattern (`components/common/reconcilehelper/util.go:18-105`):
        no-op when the desired fields already match, so level-triggered
        reconcilers don't re-trigger their own watches."""
        try:
            current = self.get(obj.kind, obj.metadata.name, obj.metadata.namespace)
        except NotFound:
            return self.create(obj, lease_guard=lease_guard)
        # Compare post-conversion, post-admission desired state against
        # stored state — otherwise an apply() of a spoke-version or
        # pre-admission spec would never no-op (or strip injected
        # fields). Webhook mutations are part of "post-admission" too,
        # so webhook-injected fields don't defeat the no-op detection
        # (webhooks, like hooks, must be idempotent).
        obj = self._admit(
            self._webhook_admit(self._normalize_version(obj), "UPDATE")
        )
        if (
            current.spec == obj.spec
            and current.metadata.labels == obj.metadata.labels
            and current.metadata.annotations == obj.metadata.annotations
        ):
            return current
        merged = obj.deepcopy()
        merged.metadata.resource_version = current.metadata.resource_version
        merged.metadata.uid = current.metadata.uid
        # Internal update path: webhooks already ran on this object for
        # the comparison above — self.update() would pay every callout's
        # HTTPS round trip a second time. In-process hooks re-run under
        # the lock (quota's atomicity; they're cheap and idempotent).
        with self._lock:
            return self._update(
                self._admit(merged), status_only=False,
                lease_guard=lease_guard,
            )

    def record_event(
        self,
        about: Resource,
        reason: str,
        message: str,
        *,
        type_: str = "Normal",
    ) -> Resource:
        """Emit a K8s-style Event object (the reference mirrors these onto
        CR statuses, `notebook_controller.go:87-103`). Content-derived
        name: replayed/repeat emissions land on the existing Event
        instead of multiplying (see `event_name`)."""
        ev = event_resource(about, reason, message, type_=type_)
        try:
            return self.create(ev)
        except AlreadyExists:
            return self.get(
                "Event", ev.metadata.name, about.metadata.namespace
            )
