"""Compiled-program accounting: collective and schedule introspection.

On a CPU mesh a silently re-replicated sharding still converges, so
finite loss/grads alone can't prove a program runs the intended
communication pattern. These helpers inspect the COMPILED
(post-SPMD-partitioner) HLO text and the traced jaxpr instead — shared
by the driver's `dryrun_multichip`, `bench.py --workload pipeline`, and
the collective-accounting regression tests, so all three count the same
things the same way.
"""

from __future__ import annotations

import re
from typing import Iterable

# The collective families the platform's programs are audited against.
# dynamic-slice rides along because the CPU backend emits the unfused
# all-reduce + dynamic-slice form of reduce-scatter.
COLLECTIVE_OPS: tuple[str, ...] = (
    "all-gather",
    "reduce-scatter",
    "all-reduce",
    "collective-permute",
    "all-to-all",
    "dynamic-slice",
)


def compiled_hlo(jitted, *args) -> str:
    """Post-partitioner HLO text for a jitted callable at `args`."""
    return jitted.lower(*args).compile().as_text()


def collective_counts(hlo: str) -> dict[str, int]:
    """Occurrences of each collective family in HLO text."""
    return {op: len(re.findall(rf"\b{op}", hlo)) for op in COLLECTIVE_OPS}


def assert_collectives(
    name: str,
    hlo: str,
    expect: Iterable[str] = (),
    forbid: Iterable[str] = (),
    quiet: bool = False,
) -> dict[str, int]:
    """Assert expected collectives are present — and the wrong ones
    absent — in compiled HLO; returns the counts. Prints the one-line
    summary the driver's dryrun artifact parses."""
    counts = collective_counts(hlo)
    for op in expect:
        assert counts[op] > 0, (
            f"{name}: expected {op!r} in compiled HLO but found none "
            f"(counts: {counts}) — the sharding silently degenerated"
        )
    for op in forbid:
        assert counts[op] == 0, (
            f"{name}: forbidden {op!r} appears {counts[op]}x in "
            f"compiled HLO (counts: {counts}) — the program is "
            f"materializing what it should stream"
        )
    if not quiet:
        print(
            f"{name} collectives: "
            + " ".join(f"{op}={counts[op]}" for op in COLLECTIVE_OPS)
        )
    return counts


_SHAPE = re.compile(r"\w+\[([0-9,]*)\]")


def allreduce_element_counts(hlo: str) -> list[int]:
    """Element count of every all-reduced buffer in HLO text (each
    component of a tuple-shaped all-reduce counts separately). This is
    how the pipeline layer's wire contract is audited: a training step
    whose cross-pp traffic is scalars plus replicated-weight gradients
    shows nothing here near activation size, while an all-reduce of a
    `[M, mb, ...]` activation buffer sticks out by orders of
    magnitude."""
    out = []
    for m in re.finditer(r"=\s*([^=\n]*?)\s+all-reduce(?:-start)?\(", hlo):
        for dims in _SHAPE.findall(m.group(1)):
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            out.append(n)
    return out


def replica_group_shapes(hlo: str) -> set[str]:
    """'contiguous' and/or 'strided' group patterns present in the
    HLO's replica_groups — contiguous groups are within-slice (ICI)
    partitions, strided groups cross slices (DCN). Handles both the
    explicit v1 form ({{0,1},{2,3}}) and the iota v2 form
    ([G,S]<=[8] / [G,S]<=[2,4]T(1,0) — a transpose means the minor
    axis strides across the device order)."""
    shapes = set()
    for m in re.finditer(r"replica_groups=\{(\{[^=]*?\})\}", hlo):
        for grp in re.findall(r"\{([\d,]+)\}", m.group(1)):
            ids = [int(x) for x in grp.split(",")]
            if len(ids) < 2:
                continue
            strides = {b - a for a, b in zip(ids, ids[1:])}
            shapes.add("contiguous" if strides == {1} else "strided")
    for m in re.finditer(
        r"replica_groups=\[(\d+),(\d+)\]<=\[[\d,]+\](T\([\d,]+\))?",
        hlo,
    ):
        n_groups, group_size, transpose = (
            int(m.group(1)), int(m.group(2)), m.group(3),
        )
        if n_groups <= 1 or group_size <= 1:
            continue  # one global group / singleton groups: neither
        shapes.add("strided" if transpose else "contiguous")
    return shapes


def scan_lengths(fn, *args) -> set[int]:
    """Trip counts of every `lax.scan`/`fori_loop` in `fn`'s jaxpr
    (recursively, so scans inside shard_map/checkpoint/vmap bodies are
    seen). The pipeline bench uses this to read the schedule's MEASURED
    tick count out of the traced program rather than trusting the model
    formula it is compared against."""
    import jax

    lengths: set[int] = set()

    def walk(jaxpr) -> None:
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "scan":
                lengths.add(int(eqn.params["length"]))
            elif eqn.primitive.name == "while":
                # fori_loop with static bounds carries them as consts in
                # the cond jaxpr only when not lowered to scan; nothing
                # to read generically — scan is the differentiable form
                # the pipeline uses.
                pass
            for sub in jax.core.jaxprs_in_params(eqn.params):
                walk(sub)

    closed = jax.make_jaxpr(fn)(*args)
    walk(closed.jaxpr)
    return lengths
