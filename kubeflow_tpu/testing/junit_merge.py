"""Merge per-shard junit XML into one suite — the artifact-collection
step of a fanned-out CI run.

The reference copies every step's junit XML from the shared NFS volume to
GCS for Gubernator (`testing/README.md:22-35`, `kfctl_go_test.jsonnet`'s
artifact steps); the collector here is that join, run as the final DAG
step over `STEP_ARTIFACTS`:

    python -m kubeflow_tpu.testing.junit_merge <dir> [-o merged.xml]

Exits non-zero when any merged suite contains failures/errors, so the
collect step's pod phase reflects the fan's overall verdict.
"""

from __future__ import annotations

import argparse
import pathlib
import sys
import xml.etree.ElementTree as ET


def merge(
    junit_dir: str | pathlib.Path, output: str | pathlib.Path | None = None
) -> tuple[int, int, int]:
    """Merge `junit_*.xml` under junit_dir; returns (tests, failures,
    errors). Writes `junit_merged.xml` (or `output`) in the same dir."""
    junit_dir = pathlib.Path(junit_dir)
    sources = sorted(
        p
        for p in junit_dir.glob("junit_*.xml")
        if p.name != "junit_merged.xml"
    )
    merged = ET.Element("testsuites")
    tests = failures = errors = 0
    for path in sources:
        root = ET.parse(path).getroot()
        suites = (
            [root] if root.tag == "testsuite"
            else list(root.iter("testsuite"))
        )
        for suite in suites:
            suite.set("file", path.name)
            merged.append(suite)
            tests += int(suite.get("tests", 0))
            failures += int(suite.get("failures", 0))
            errors += int(suite.get("errors", 0))
    merged.set("tests", str(tests))
    merged.set("failures", str(failures))
    merged.set("errors", str(errors))
    out_path = pathlib.Path(output) if output else junit_dir / "junit_merged.xml"
    ET.ElementTree(merged).write(out_path, xml_declaration=True)
    return tests, failures, errors


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="junit-merge")
    parser.add_argument("junit_dir")
    parser.add_argument("-o", "--output", default=None)
    args = parser.parse_args(argv)
    tests, fails, errs = merge(args.junit_dir, args.output)
    print(f"merged {tests} tests: {fails} failures, {errs} errors")
    return 1 if (fails or errs) else 0


if __name__ == "__main__":
    sys.exit(main())
