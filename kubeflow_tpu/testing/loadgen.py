"""Multi-process open-loop load harness (ISSUE 17).

The serving bench phases were *closed-loop* until now: N client threads
each fire-wait-fire, so the moment the fleet slows down the clients
slow down with it — offered load sags exactly when the system is most
interesting, and coordinated omission hides the latency the user would
have seen. This module is the *open-loop* counterpart: arrivals follow
a fixed schedule computed up front (Poisson or uniform inter-arrival at
a fixed offered rate), and a request fires at its scheduled instant
whether or not earlier ones came back.

Scaling past the GIL is the other half: one Python process cannot tick
a 10k-client arrival schedule while also parsing 10k HTTP responses.
So the harness shards the schedule across WORKER PROCESSES (spawn
context — no inherited JAX/locks), each running its own event-driven
dispatcher plus a thread pool that absorbs in-flight requests, with
per-request records streamed back to the parent over a pipe and merged
into one report.

Determinism contract (same as the chaos schedules): the arrival
schedule and the per-arrival traffic-class assignment derive from an
explicit seed — two runs with the same seed offer byte-identical load.

Honesty contract: the report carries ``offered_rate_error`` — how far
the *achieved* arrival rate drifted from the requested one (scheduler
jitter, pool saturation). A harness that can't hold its offered rate
is measuring itself, not the fleet; the bench gates this at 5%.

Kept deliberately stdlib-only at module level: worker processes
re-import this module under the spawn context, and the dispatcher loop
must not pay a JAX import to send HTTP requests.
"""

from __future__ import annotations

import dataclasses
import multiprocessing
import random
import threading
import time
from concurrent.futures import ThreadPoolExecutor

# Outcome codes on the wire between worker and parent (tuples pickle
# cheaper than dicts at 10k+ records).
OK, SHED, ERROR = 0, 1, 2
_OUTCOMES = ("ok", "shed", "error")

# Priority/tenant ride the front door's headers (serving/server.py).
PRIORITY_HEADER = "X-KFTPU-Priority"
TENANT_HEADER = "X-KFTPU-Tenant"


@dataclasses.dataclass(frozen=True)
class TrafficClass:
    """One stream in the offered mix: which model it hits, at what
    priority, on whose quota, and its share of arrivals."""

    model: str
    priority: str = "standard"
    tenant: str = ""
    weight: float = 1.0


@dataclasses.dataclass
class ClassReport:
    model: str
    priority: str
    tenant: str
    count: int = 0
    ok: int = 0
    shed: int = 0
    error: int = 0
    p50_ms: float = 0.0
    p99_ms: float = 0.0


@dataclasses.dataclass
class LoadReport:
    """Merged result of one open-loop run."""

    offered_rate: float
    achieved_rate: float
    offered_rate_error: float
    fired: int
    ok: int
    shed: int
    error: int
    duration_s: float
    fire_lag_p99_ms: float
    # Aggregate latency over OK requests across every class.
    p50_ms: float
    p99_ms: float
    classes: list[ClassReport]

    def by_model(self) -> dict[str, ClassReport]:
        """Collapse classes onto models (a model may appear in several
        priority streams); percentiles are the worst stream's."""
        out: dict[str, ClassReport] = {}
        for c in self.classes:
            slot = out.setdefault(
                c.model, ClassReport(c.model, c.priority, c.tenant)
            )
            slot.count += c.count
            slot.ok += c.ok
            slot.shed += c.shed
            slot.error += c.error
            slot.p50_ms = max(slot.p50_ms, c.p50_ms)
            slot.p99_ms = max(slot.p99_ms, c.p99_ms)
        return out


def arrival_schedule(
    rate: float, count: int, *, seed: int, process: str = "poisson"
) -> list[float]:
    """Offsets (seconds from start) of `count` arrivals at offered
    `rate`. "poisson" draws exponential inter-arrival gaps (the
    open-system model); "uniform" ticks a metronome (for fidelity
    measurement, where schedule variance would mask harness jitter)."""
    if rate <= 0:
        raise ValueError(f"rate must be > 0, got {rate}")
    if process not in ("poisson", "uniform"):
        raise ValueError(f"unknown arrival process {process!r}")
    if process == "uniform":
        return [i / rate for i in range(count)]
    rng = random.Random(seed)
    t = 0.0
    out = []
    for _ in range(count):
        t += rng.expovariate(rate)
        out.append(t)
    return out


def assign_classes(
    classes: list[TrafficClass], count: int, *, seed: int
) -> list[int]:
    """Per-arrival class index, weighted + seeded (deterministic mix)."""
    if not classes:
        raise ValueError("need at least one TrafficClass")
    rng = random.Random(seed ^ 0x5EED)
    weights = [c.weight for c in classes]
    return rng.choices(range(len(classes)), weights=weights, k=count)


# -- targets --------------------------------------------------------------
#
# A target spec is a plain picklable dict; the worker process builds the
# actual request callable from it. Two modes:
#   {"mode": "noop", "work_us": 0}          — fidelity runs: measure the
#       harness itself (can it hold the offered rate?), no I/O.
#   {"mode": "http", "addr": "host:port", "shape": [...], "timeout_s": N}
#       — drive a live front door / model server with binary tensor
#       frames; 429 → shed, other non-200 / socket error → error.


def _build_target(spec: dict, classes: list[TrafficClass]):
    """Returns fn(cls_idx) -> outcome code. Called inside the worker."""
    mode = spec.get("mode", "noop")
    if mode == "noop":
        work_us = float(spec.get("work_us", 0))

        def noop(_cls_idx: int) -> int:
            if work_us:
                # Busy-spin, not sleep: models CPU-bound client work
                # without handing the GIL a scheduling excuse.
                end = time.perf_counter() + work_us / 1e6
                while time.perf_counter() < end:
                    pass
            return OK

        return noop
    if mode != "http":
        raise ValueError(f"unknown target mode {mode!r}")

    import http.client

    import numpy as np

    from kubeflow_tpu.serving import wire

    host, _, port = spec["addr"].partition(":")
    timeout_s = float(spec.get("timeout_s", 30.0))
    shape = tuple(spec.get("shape", (1, 32, 32, 3)))
    payload = wire.encode_tensor(
        np.zeros(shape, dtype=spec.get("dtype", "float32"))
    )
    paths = [f"/v1/models/{c.model}:predict" for c in classes]
    headers = [
        {
            "Content-Type": wire.TENSOR_CONTENT_TYPE,
            "Accept": wire.TENSOR_CONTENT_TYPE,
            PRIORITY_HEADER: c.priority,
            **({TENANT_HEADER: c.tenant} if c.tenant else {}),
        }
        for c in classes
    ]
    # One keep-alive connection per pool thread (thread-local), so the
    # server sees a realistic pooled client population rather than a
    # dial per request.
    local = threading.local()

    def send(cls_idx: int) -> int:
        conn = getattr(local, "conn", None)
        for attempt in (0, 1):
            if conn is None:
                conn = http.client.HTTPConnection(
                    host, int(port), timeout=timeout_s
                )
                local.conn = conn
            try:
                conn.request(
                    "POST", paths[cls_idx], body=payload,
                    headers=headers[cls_idx],
                )
                resp = conn.getresponse()
                resp.read()
                if resp.status == 200:
                    return OK
                if resp.status == 429:
                    return SHED
                return ERROR
            except OSError:
                # Stale keep-alive socket: redial once, then call it a
                # real error.
                conn.close()
                local.conn = conn = None
        return ERROR

    return send


# -- worker ---------------------------------------------------------------

_CHUNK = 2000  # records per pipe message — bounds pickling spikes


def _worker_main(conn, wspec: dict) -> None:
    """One load worker: handshake ready, wait for the shared start
    instant, then fire its schedule slice open-loop. Runs under the
    spawn context — everything arrives through `wspec` (picklable)."""
    classes = [TrafficClass(*c) for c in wspec["classes"]]
    arrivals = wspec["arrivals"]  # [(offset_s, cls_idx), ...] sorted
    target = _build_target(wspec["target"], classes)
    records: list[tuple] = []
    rlock = threading.Lock()

    def fire(offset: float, cls_idx: int, t0: float) -> None:
        start = time.monotonic()
        lag = start - (t0 + offset)
        outcome = target(cls_idx)
        latency = time.monotonic() - start
        with rlock:
            records.append((cls_idx, offset, lag, latency, outcome))

    pool = ThreadPoolExecutor(max_workers=int(wspec["concurrency"]))
    try:
        conn.send(("ready", None))
        msg, t0 = conn.recv()  # ("start", shared monotonic instant)
        if msg != "start":
            return
        for offset, cls_idx in arrivals:
            # Event-driven dispatch: sleep to the scheduled instant,
            # then hand off to the pool WITHOUT waiting for earlier
            # requests — the open-loop property. CLOCK_MONOTONIC is
            # system-wide on Linux, so t0 crosses the process boundary.
            delay = (t0 + offset) - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            pool.submit(fire, offset, cls_idx, t0)
        pool.shutdown(wait=True)
        for i in range(0, len(records), _CHUNK):
            conn.send(("records", records[i:i + _CHUNK]))
        conn.send(("done", len(records)))
    finally:
        conn.close()


# -- parent ---------------------------------------------------------------


def _percentile(ordered: list[float], q: float) -> float:
    if not ordered:
        return 0.0
    return ordered[min(len(ordered) - 1, int(q * len(ordered)))]


def _merge(
    records: list[tuple],
    classes: list[TrafficClass],
    rate: float,
) -> LoadReport:
    per_class: list[list[tuple]] = [[] for _ in classes]
    for rec in records:
        per_class[rec[0]].append(rec)
    reports = []
    for c, recs in zip(classes, per_class):
        lats = sorted(r[3] for r in recs if r[4] == OK)
        reports.append(
            ClassReport(
                model=c.model,
                priority=c.priority,
                tenant=c.tenant,
                count=len(recs),
                ok=sum(1 for r in recs if r[4] == OK),
                shed=sum(1 for r in recs if r[4] == SHED),
                error=sum(1 for r in recs if r[4] == ERROR),
                p50_ms=round(_percentile(lats, 0.50) * 1000, 3),
                p99_ms=round(_percentile(lats, 0.99) * 1000, 3),
            )
        )
    fires = sorted(r[1] + r[2] for r in records)  # offset + lag
    span = (fires[-1] - fires[0] + 1.0 / rate) if records else 0.0
    achieved = len(records) / span if span > 0 else 0.0
    lags = sorted(max(0.0, r[2]) for r in records)
    all_ok = sorted(r[3] for r in records if r[4] == OK)
    return LoadReport(
        offered_rate=rate,
        achieved_rate=round(achieved, 3),
        offered_rate_error=(
            round(abs(achieved - rate) / rate, 5) if rate else 0.0
        ),
        fired=len(records),
        ok=sum(r.ok for r in reports),
        shed=sum(r.shed for r in reports),
        error=sum(r.error for r in reports),
        duration_s=round(span, 3),
        fire_lag_p99_ms=round(_percentile(lags, 0.99) * 1000, 3),
        p50_ms=round(_percentile(all_ok, 0.50) * 1000, 3),
        p99_ms=round(_percentile(all_ok, 0.99) * 1000, 3),
        classes=reports,
    )


def run_open_loop(
    target: dict,
    classes: list[TrafficClass],
    *,
    rate: float,
    total: int,
    seed: int = 0,
    workers: int = 4,
    concurrency: int = 64,
    process: str = "poisson",
    start_delay_s: float = 0.5,
    timeout_s: float = 600.0,
) -> LoadReport:
    """Fire `total` arrivals at offered `rate` across `workers` spawned
    processes, merged into one LoadReport.

    The parent computes the full schedule and deals arrival i to worker
    i % workers — every worker holds a rate/workers thinning of the
    same point process, so the union reproduces the offered process
    exactly and a straggling worker shows up as fire lag, not as a
    silently reshaped schedule."""
    if total < 1:
        raise ValueError(f"total must be >= 1, got {total}")
    workers = max(1, min(workers, total))
    offsets = arrival_schedule(rate, total, seed=seed, process=process)
    cls_idx = assign_classes(classes, total, seed=seed)
    ctx = multiprocessing.get_context("spawn")
    procs, conns = [], []
    cls_tuples = [
        (c.model, c.priority, c.tenant, c.weight) for c in classes
    ]
    for w in range(workers):
        parent_conn, child_conn = ctx.Pipe()
        wspec = {
            "classes": cls_tuples,
            "arrivals": list(
                zip(offsets[w::workers], cls_idx[w::workers])
            ),
            "target": target,
            "concurrency": concurrency,
        }
        p = ctx.Process(
            target=_worker_main, args=(child_conn, wspec), daemon=True
        )
        p.start()
        child_conn.close()
        procs.append(p)
        conns.append(parent_conn)

    deadline = time.monotonic() + timeout_s
    records: list[tuple] = []
    try:
        for conn in conns:
            if not conn.poll(max(0.1, deadline - time.monotonic())):
                raise TimeoutError("loadgen worker never became ready")
            msg, _ = conn.recv()
            if msg != "ready":
                raise RuntimeError(f"unexpected worker message {msg!r}")
        # All workers armed: release them against one shared instant far
        # enough out that the start messages land first.
        t0 = time.monotonic() + start_delay_s
        for conn in conns:
            conn.send(("start", t0))
        pending = set(range(len(conns)))
        while pending:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"loadgen workers {sorted(pending)} still running "
                    f"after {timeout_s}s"
                )
            for i in list(pending):
                while i in pending and conns[i].poll(0.05):
                    # A worker that died mid-run closes its pipe: poll
                    # reports EOF as readable and recv raises — surface
                    # that as a harness failure, not a hang.
                    try:
                        msg, payload = conns[i].recv()
                    except EOFError:
                        raise RuntimeError(
                            f"loadgen worker {i} exited before "
                            f"finishing its schedule"
                        ) from None
                    if msg == "records":
                        records.extend(payload)
                    elif msg == "done":
                        pending.discard(i)
    finally:
        for p in procs:
            p.join(timeout=10.0)
            if p.is_alive():
                p.kill()
                p.join(timeout=10.0)
        for conn in conns:
            conn.close()
    return _merge(records, classes, rate)


def run_open_loop_threaded(
    target,
    classes: list[TrafficClass],
    *,
    rate: float,
    total: int,
    seed: int = 0,
    concurrency: int = 64,
    process: str = "poisson",
) -> LoadReport:
    """In-process variant: same schedule/merge machinery, one dispatcher
    thread, `target` is a direct callable ``fn(TrafficClass) -> "ok" |
    "shed" | "error"``. For tests and for driving an in-process Router
    without the HTTP boundary; the multi-process version is the one
    that scales past the GIL."""
    offsets = arrival_schedule(rate, total, seed=seed, process=process)
    cls_idx = assign_classes(classes, total, seed=seed)
    records: list[tuple] = []
    rlock = threading.Lock()
    code = {name: i for i, name in enumerate(_OUTCOMES)}

    def fire(offset: float, ci: int, t0: float) -> None:
        start = time.monotonic()
        lag = start - (t0 + offset)
        try:
            outcome = code.get(target(classes[ci]), ERROR)
        except Exception:
            outcome = ERROR
        latency = time.monotonic() - start
        with rlock:
            records.append((ci, offset, lag, latency, outcome))

    pool = ThreadPoolExecutor(max_workers=concurrency)
    t0 = time.monotonic() + 0.05
    for offset, ci in zip(offsets, cls_idx):
        delay = (t0 + offset) - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        pool.submit(fire, offset, ci, t0)
    pool.shutdown(wait=True)
    return _merge(records, classes, rate)


def plan_rate(total: int, duration_s: float) -> float:
    """Offered rate that lands `total` arrivals in ~`duration_s`."""
    if duration_s <= 0:
        raise ValueError(f"duration_s must be > 0, got {duration_s}")
    return max(1e-9, total / duration_s)


__all__ = [
    "ClassReport",
    "LoadReport",
    "TrafficClass",
    "arrival_schedule",
    "assign_classes",
    "plan_rate",
    "run_open_loop",
    "run_open_loop_threaded",
]
