"""Dynamic lock-graph witness: the runtime half of kftpu-race.

The static pass (`ci/lint/concurrency.py`) derives the package's
lock-acquisition-order graph from source. A static analysis can only be
trusted if it provably does not under-approximate the paths real runs
take — so this module wraps `threading.Lock/RLock/Condition` and
records the acquisition-order edges a live process actually performs.
The chaos soak and the serving data-plane bench run under the witness
(opt-in: ``KFTPU_LOCKGRAPH=1``) and assert two things:

- the **observed** graph is acyclic (no run ever interleaved lock
  acquisitions in cycle-forming order), and
- every observed edge is **present in the static graph** — if a run
  acquires B while holding A and the static model has no A→B edge, the
  model's call-graph resolution missed a real path and must be fixed.

Naming matches the static side exactly: a lock is named by its
*allocation site* — ``<relpath>::<Class>.<attr>`` for ``self.X =
threading.Lock()`` inside a method (the textually-enclosing class IS
the static model's MRO defining class), ``<relpath>::<name>`` at module
level. ``threading.Condition(existing_lock)`` creates no new node: the
condition is an alias of the wrapped lock, and since the wrapped
instrumented lock is handed to the real Condition, the edges attribute
to the underlying lock automatically — the same aliasing rule the
static model applies.

Only locks allocated from files under ``kubeflow_tpu/`` are
instrumented; stdlib internals (`queue.Queue`'s mutex, `threading.Event`'s
condition) allocate from their own files and keep real primitives, so
the witness never sees — and never has to model — stdlib-private
ordering.
"""

from __future__ import annotations

import ast
import contextlib
import linecache
import os
import pathlib
import re
import sys
import threading
import _thread

_REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]
_PKG_PREFIX = str(_REPO_ROOT / "kubeflow_tpu") + os.sep

_SELF_ATTR_RE = re.compile(r"self\.(\w+)\s*(?::[^=]*)?=")
_NAME_RE = re.compile(r"^\s*(\w+)\s*(?::[^=]*)?=")


class _SiteIndex:
    """filename -> (line -> enclosing class name, line -> assigned attr),
    built once per file from its AST so allocation sites can be named
    identically to the static model."""

    def __init__(self) -> None:
        self._cache: dict[str, tuple[dict[int, str], dict[int, str]]] = {}

    def _build(self, filename: str) -> tuple[dict[int, str], dict[int, str]]:
        classes: dict[int, str] = {}
        attrs: dict[int, str] = {}
        try:
            tree = ast.parse(
                pathlib.Path(filename).read_text()
            )
        except (OSError, SyntaxError):
            return classes, attrs
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                for line in range(node.lineno, (node.end_lineno or node.lineno) + 1):
                    # Innermost class wins: later (nested, higher lineno)
                    # ClassDefs overwrite the enclosing one's range.
                    classes[line] = node.name
            target = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
            elif isinstance(node, ast.AnnAssign):
                target = node.target
            if target is None:
                continue
            if (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
            ):
                name = target.attr
            elif isinstance(target, ast.Name):
                name = target.id
            else:
                continue
            for line in range(node.lineno, (node.end_lineno or node.lineno) + 1):
                attrs.setdefault(line, name)
        return classes, attrs

    def name_for(self, filename: str, lineno: int) -> str:
        if filename not in self._cache:
            self._cache[filename] = self._build(filename)
        classes, attrs = self._cache[filename]
        relpath = filename
        try:
            relpath = pathlib.Path(filename).resolve().relative_to(
                _REPO_ROOT
            ).as_posix()
        except ValueError:
            pass
        attr = attrs.get(lineno)
        if attr is None:
            line = linecache.getline(filename, lineno)
            m = _SELF_ATTR_RE.search(line) or _NAME_RE.match(line)
            attr = m.group(1) if m else f"line{lineno}"
        cls = classes.get(lineno)
        if cls:
            return f"{relpath}::{cls}.{attr}"
        return f"{relpath}::{attr}"


class _InstrumentedLock:
    """Delegating wrapper around a real Lock/RLock that reports
    successful acquires/releases to the witness. Implements
    `_is_owned` by its own owner tracking so a real Condition wrapping
    it never has to probe with `acquire(0)` (which would record a
    spurious self-edge)."""

    def __init__(self, real, name: str, witness: "LockGraphWitness"):
        self._real = real
        self._kftpu_name = name
        self._witness = witness
        self._owner: int | None = None
        self._count = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._real.acquire(blocking, timeout)
        if got:
            self._owner = _thread.get_ident()
            self._count += 1
            self._witness._on_acquire(self._kftpu_name)
        return got

    def release(self) -> None:
        self._count -= 1
        if self._count <= 0:
            self._owner = None
            self._count = 0
        self._real.release()
        self._witness._on_release(self._kftpu_name)

    def __enter__(self) -> "_InstrumentedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        locked = getattr(self._real, "locked", None)
        if locked is not None:
            return locked()
        return self._count > 0

    def _is_owned(self) -> bool:
        return self._owner == _thread.get_ident()

    def __repr__(self) -> str:
        return f"<kftpu-instrumented {self._kftpu_name} {self._real!r}>"


class LockGraphWitness:
    """Records the observed lock-acquisition-order edge set.

    Use as a context manager (or `install()`/`uninstall()`): while
    installed, every Lock/RLock/Condition *allocated* from package code
    is wrapped. Locks allocated before installation stay real and
    unobserved — run the workload's constructors inside the witness.
    """

    def __init__(self) -> None:
        # (held, acquired) -> True; guarded by a REAL lock so the
        # recorder can never participate in instrumented ordering.
        self._mutex = _thread.allocate_lock()
        self._edges: set[tuple[str, str]] = set()
        self._held: dict[int, list[str]] = {}
        self._saved: dict[str, object] = {}
        self._sites = _SiteIndex()
        self._installed = False

    # -- recording ----------------------------------------------------------

    def _on_acquire(self, name: str) -> None:
        tid = _thread.get_ident()
        with self._mutex:
            stack = self._held.setdefault(tid, [])
            for held in set(stack):
                if held != name:
                    self._edges.add((held, name))
            stack.append(name)

    def _on_release(self, name: str) -> None:
        tid = _thread.get_ident()
        with self._mutex:
            stack = self._held.get(tid)
            if stack is not None:
                for i in range(len(stack) - 1, -1, -1):
                    if stack[i] == name:
                        del stack[i]
                        break
                if not stack:
                    self._held.pop(tid, None)

    def record_edge(self, a: str, b: str) -> None:
        """Test hook: inject an observed edge directly."""
        with self._mutex:
            self._edges.add((a, b))

    @property
    def edges(self) -> frozenset[tuple[str, str]]:
        with self._mutex:
            return frozenset(self._edges)

    # -- factory patching ---------------------------------------------------

    def _caller_site(self) -> tuple[str, int] | None:
        """(filename, lineno) of the allocation when it came from
        package code, else None."""
        frame = sys._getframe(2)
        filename = frame.f_code.co_filename
        try:
            resolved = str(pathlib.Path(filename).resolve())
        except OSError:
            return None
        if not resolved.startswith(_PKG_PREFIX):
            return None
        return (filename, frame.f_lineno)

    def _make_lock(self, real_factory):
        def factory():
            site = self._caller_site()
            real = real_factory()
            if site is None:
                return real
            name = self._sites.name_for(*site)
            return _InstrumentedLock(real, name, self)

        return factory

    def _make_condition(self, real_condition, real_lock_factory):
        def factory(lock=None):
            if lock is not None:
                # Condition(existing_lock): alias — no new node. If the
                # wrapped lock is instrumented its edges already carry
                # the right name; if it's real, stay out of the way.
                return real_condition(lock)
            site = self._caller_site()
            if site is None:
                return real_condition()
            name = self._sites.name_for(*site)
            inner = _InstrumentedLock(
                real_lock_factory(), name, self
            )
            return real_condition(inner)

        return factory

    def install(self) -> "LockGraphWitness":
        assert not self._installed, "witness already installed"
        self._saved = {
            "Lock": threading.Lock,
            "RLock": threading.RLock,
            "Condition": threading.Condition,
        }
        real_lock, real_rlock = threading.Lock, threading.RLock
        real_condition = threading.Condition
        threading.Lock = self._make_lock(real_lock)  # type: ignore
        threading.RLock = self._make_lock(real_rlock)  # type: ignore
        threading.Condition = self._make_condition(  # type: ignore
            real_condition, real_lock
        )
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        threading.Lock = self._saved["Lock"]  # type: ignore
        threading.RLock = self._saved["RLock"]  # type: ignore
        threading.Condition = self._saved["Condition"]  # type: ignore
        self._installed = False

    def __enter__(self) -> "LockGraphWitness":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # -- assertions ---------------------------------------------------------

    def assert_acyclic(self) -> None:
        """The observed graph must have no cycle: a cycle means the run
        actually interleaved acquisitions in deadlock-capable order."""
        edges = self.edges
        adj: dict[str, list[str]] = {}
        for a, b in edges:
            adj.setdefault(a, []).append(b)
            adj.setdefault(b, [])
        state: dict[str, int] = {}  # 1 = on stack, 2 = done

        def visit(node: str, path: list[str]) -> None:
            state[node] = 1
            path.append(node)
            for nxt in sorted(adj[node]):
                if state.get(nxt) == 1:
                    cycle = path[path.index(nxt):] + [nxt]
                    raise AssertionError(
                        "observed lock-acquisition cycle: "
                        + " -> ".join(cycle)
                    )
                if nxt not in state:
                    visit(nxt, path)
            path.pop()
            state[node] = 2

        for node in sorted(adj):
            if node not in state:
                visit(node, [])

    def assert_subset_of_static(
        self, static: frozenset[tuple[str, str]] | None = None
    ) -> None:
        """Every observed edge must appear in the static lock-order
        graph — an unseen edge means `ci/lint/concurrency.py` missed a
        real code path and under-approximates."""
        if static is None:
            from kubeflow_tpu.ci.lint.concurrency import static_edges

            static = static_edges()
        missing = sorted(self.edges - static)
        if missing:
            lines = "\n".join(f"  {a} -> {b}" for a, b in missing)
            raise AssertionError(
                "observed acquisition edge(s) missing from the static "
                f"lock-order graph (kftpu-race under-approximates):\n"
                f"{lines}"
            )


ENV_FLAG = "KFTPU_LOCKGRAPH"


@contextlib.contextmanager
def maybe_witness():
    """Opt-in wrapper for soaks/benches: under ``KFTPU_LOCKGRAPH=1``
    runs the body instrumented and, on *successful* exit, asserts the
    observed graph is acyclic and a subset of the static graph; yields
    None (and does nothing) otherwise."""
    if os.environ.get(ENV_FLAG) != "1":
        yield None
        return
    witness = LockGraphWitness()
    witness.install()
    try:
        yield witness
    finally:
        witness.uninstall()
    witness.assert_acyclic()
    witness.assert_subset_of_static()
