"""Durable-store backends for the control plane (WAL + snapshot dir).

The reference's control plane is durable because it rides etcd — its
envtest fixture spins a real etcd+apiserver even for unit tests
(`profile-controller/controllers/suite_test.go:29-54`), and every
reconcile/requeue pattern assumes the store outlives any process. Our
apiserver persists through this module instead: an append-only, fsync'd
write-ahead log plus an atomically-replaced snapshot, in one directory:

    <dir>/snapshot.json   full state {format, rv, objects}
    <dir>/wal.log         one JSON record per committed write

The preferred backend is the compiled one (`native/src/wal.cc` via
ctypes); `PyWal` is a pure-Python twin with the same crash-safety
contract for environments without the native toolchain. Both guarantee:
append returns only after fdatasync; snapshot is tmp+fsync+rename+dirsync
before the WAL is truncated (a crash in between leaves stale WAL records,
which the reader skips by rv).
"""

from __future__ import annotations

import logging
import os

log = logging.getLogger(__name__)

# Snapshot format (bump on incompatible layout changes; the store refuses
# to load a snapshot from a different major format rather than guess).
FORMAT = 1


class PyWal:
    """Pure-Python WAL backend (same contract as native/src/wal.cc)."""

    def __init__(self, directory: str):
        self._dir = str(directory)
        os.makedirs(self._dir, mode=0o700, exist_ok=True)
        self._dir_fd = os.open(self._dir, os.O_RDONLY | os.O_DIRECTORY)
        self._fd = os.open(
            self._wal_path, os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o600
        )
        # The wal.log dirent must be durable from the start: appends only
        # fdatasync file DATA; a never-dir-fsynced file can vanish on
        # crash, losing every acked pre-snapshot write at once.
        os.fsync(self._dir_fd)

    @property
    def _wal_path(self) -> str:
        return os.path.join(self._dir, "wal.log")

    @property
    def _snap_path(self) -> str:
        return os.path.join(self._dir, "snapshot.json")

    def close(self) -> None:
        for attr in ("_fd", "_dir_fd"):
            fd = getattr(self, attr, None)
            if fd is not None:
                os.close(fd)
                setattr(self, attr, None)

    def append(self, line: str) -> None:
        data = (line + "\n").encode()
        while data:
            data = data[os.write(self._fd, data):]
        os.fdatasync(self._fd)

    def snapshot(self, text: str) -> None:
        tmp = self._snap_path + ".tmp"
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        try:
            data = text.encode()
            while data:
                data = data[os.write(fd, data):]
            os.fsync(fd)
        finally:
            os.close(fd)
        os.rename(tmp, self._snap_path)
        os.fsync(self._dir_fd)
        # Snapshot durable — now the WAL may shrink (see module docstring
        # for why this ordering is the crash-safe one). Truncation is by
        # REPLACEMENT, not O_TRUNC: the fresh log is a new inode renamed
        # over wal.log, so another process still holding the old fd (an
        # active-passive takeover's deposed predecessor,
        # testing/failover.py) appends into an orphaned file that no
        # restart will ever replay. Crash between the two renames leaves
        # the old pre-snapshot records in place — replay skips them by
        # rv, same contract as before.
        wal_tmp = self._wal_path + ".tmp"
        fresh = os.open(
            wal_tmp,
            os.O_WRONLY | os.O_APPEND | os.O_CREAT | os.O_TRUNC,
            0o600,
        )
        os.rename(wal_tmp, self._wal_path)
        os.close(self._fd)
        self._fd = fresh
        os.fsync(self._dir_fd)

    def _read(self, path: str) -> str:
        try:
            with open(path, encoding="utf-8", errors="replace") as f:
                return f.read()
        except FileNotFoundError:
            return ""

    def read_snapshot(self) -> str:
        return self._read(self._snap_path)

    def read_journal(self) -> str:
        return self._read(self._wal_path)


def open_wal(directory: str, backend: str = "auto"):
    """Open the persistence directory with the requested backend:
    ``native`` (compiled, raises if the toolchain can't build it),
    ``python``, or ``auto`` (native with Python fallback)."""
    if backend not in ("auto", "native", "python"):
        raise ValueError(f"unknown wal backend {backend!r}")
    if backend in ("auto", "native"):
        try:
            from kubeflow_tpu.native.core import NativeWal

            return NativeWal(directory)
        except Exception as e:
            if backend == "native":
                raise
            log.warning(
                "native WAL unavailable (%s); using Python backend", e
            )
    return PyWal(directory)
