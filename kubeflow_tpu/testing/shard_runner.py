"""Run one CI test shard: pytest over a target, junit into the shared
artifacts volume under a filesystem-safe name.

The fan-out step of `sharded_unit_tests_workflow` — the per-step wrapper
pattern of the reference's workload launchers (`tf-cnn/launcher.py:68-88`
wraps the benchmark; CI steps wrap pytest the same way):

    python -m kubeflow_tpu.testing.shard_runner <target> [--junit-dir D]
"""

from __future__ import annotations

import argparse
import re
import subprocess
import sys


def safe_name(target: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]", "_", target)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="shard-runner")
    parser.add_argument("target")
    parser.add_argument("--junit-dir", default="")
    parser.add_argument(
        "--pytest-args", default="-q", help="extra pytest flags (split on space)"
    )
    args = parser.parse_args(argv)
    cmd = [sys.executable, "-m", "pytest", args.target,
           *args.pytest_args.split()]
    if args.junit_dir:
        cmd.append(
            f"--junitxml={args.junit_dir}/junit_{safe_name(args.target)}.xml"
        )
    return subprocess.call(cmd)


if __name__ == "__main__":
    sys.exit(main())
