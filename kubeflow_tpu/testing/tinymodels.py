"""Tiny test-support models for the resilience suites.

`TinyMLP` is deliberately normalization-free: BatchNorm/LayerNorm models
normalize a scaled poison batch away before it reaches the loss, so
fault-injection suites (the guard unit tests and the kill-and-resume
soak) would never see their scheduled loss spikes. This invariant is
load-bearing — keep this model free of any normalization layer.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class TinyMLP(nn.Module):
    """No normalization anywhere: input scale reaches the loss and the
    gradients at full magnitude, so a scheduled loss_spike fault
    actually spikes."""

    num_classes: int = 10
    hidden: int = 16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1)).astype(jnp.float32)
        x = nn.relu(nn.Dense(self.hidden)(x))
        return nn.Dense(self.num_classes)(x).astype(jnp.float32)
