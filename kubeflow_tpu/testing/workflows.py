"""CI workflow definitions — the jsonnet-workflow analog, in Python.

The reference defines its Prow-triggered CI as Argo DAGs in jsonnet
(`testing/workflows/components/unit_tests.jsonnet`,
`kfctl_go_test.jsonnet:88-165`: checkout → build → deploy → pytest suites
→ teardown-in-exit-handler, all sharing an NFS volume with junit copied
out for Gubernator). These builders produce the same DAG shapes as
`Workflow` CRs for our workflow controller; `python -m pytest` replaces
the container images when run via the local pod runner.
"""

from __future__ import annotations

import sys

from kubeflow_tpu.api.objects import Resource, new_resource
from kubeflow_tpu.api.workflow import KIND, StepSpec, WorkflowSpec


def _pytest_step(
    name: str,
    target: str,
    *,
    dependencies: tuple[str, ...] = (),
    junit_dir: str = "",
    retries: int = 0,
) -> StepSpec:
    args = ["-m", "pytest", target, "-q"]
    if junit_dir:
        args += [f"--junitxml={junit_dir}/junit_{name}.xml"]
    return StepSpec(
        name=name,
        command=(sys.executable,),
        args=tuple(args),
        dependencies=dependencies,
        retries=retries,
    )


def unit_tests_workflow(
    name: str = "unit-tests",
    namespace: str = "kubeflow-ci",
    *,
    artifacts_dir: str = "",
) -> Resource:
    """The `unit_tests.jsonnet` analog — the only workflow active in the
    reference's `prow_config.yaml:8-12`: lint + unit suites in parallel,
    junit into the shared artifacts dir."""
    spec = WorkflowSpec(
        steps=(
            _pytest_step("test-core", "tests/", junit_dir=artifacts_dir),
            StepSpec(
                name="lint",
                command=(sys.executable, "-m", "compileall", "-q"),
                args=("kubeflow_tpu",),
            ),
        ),
        artifacts_dir=artifacts_dir,
    )
    return new_resource(KIND, name, namespace, spec=spec.to_dict())


def sharded_unit_tests_workflow(
    shards: tuple[str, ...],
    name: str = "unit-tests-sharded",
    namespace: str = "kubeflow-ci",
    *,
    artifacts_dir: str = "",
    collect_required: bool = True,
) -> Resource:
    """Fan-out CI: one pytest pod per shard (`withItems`), junit XML into
    the shared artifacts volume, then a collect step that merges the
    shards' junit into one suite — the Argo DAG + NFS + Gubernator-copy
    shape of `kfctl_go_test.jsonnet` expressed with the engine's own
    fan-out/artifact surfaces. `collect_required=False` adds a `when`
    guard demonstrating conditional collection (skip merging when a
    parameter disables it)."""
    if not artifacts_dir:
        raise ValueError(
            "sharded CI needs an artifacts_dir — junit collection is the "
            "point of the join step"
        )
    collect = StepSpec(
        name="collect-junit",
        command=(sys.executable, "-m", "kubeflow_tpu.testing.junit_merge"),
        args=(artifacts_dir,),
        dependencies=("shard",),
        when="" if collect_required
        else "${workflow.parameters.collect} == true",
    )
    spec = WorkflowSpec(
        steps=(
            StepSpec(
                name="shard",
                command=(
                    sys.executable, "-m",
                    "kubeflow_tpu.testing.shard_runner",
                ),
                args=("${item}", "--junit-dir", artifacts_dir),
                with_items=tuple(shards),
            ),
            collect,
        ),
        artifacts_dir=artifacts_dir,
        parameters={} if collect_required else {"collect": "true"},
    )
    return new_resource(KIND, name, namespace, spec=spec.to_dict())


def platform_e2e_workflow(
    name: str = "platform-e2e",
    namespace: str = "kubeflow-ci",
    *,
    artifacts_dir: str = "",
    deploy_args: tuple[str, ...] = (),
) -> Resource:
    """The `kfctl_go_test.jsonnet` analog: deploy the platform, assert
    readiness, run the conformance suites, tear down in the exit handler
    no matter what (:384-391)."""
    py = sys.executable
    spec = WorkflowSpec(
        steps=(
            StepSpec(
                name="deploy",
                command=(py, "-m", "kubeflow_tpu.deploy", "apply"),
                args=deploy_args,
                retries=2,  # the reference retried Apply(K8S) x3
            ),
            _pytest_step(
                "kf-is-ready",
                "tests/test_deploy.py",
                dependencies=("deploy",),
                junit_dir=artifacts_dir,
            ),
            _pytest_step(
                "serving-golden",
                "tests/test_serving.py",
                dependencies=("deploy",),
                junit_dir=artifacts_dir,
            ),
            _pytest_step(
                "studyjob",
                "tests/test_study.py",
                dependencies=("deploy",),
                junit_dir=artifacts_dir,
            ),
        ),
        on_exit=StepSpec(
            name="teardown",
            command=(py, "-m", "kubeflow_tpu.deploy", "delete"),
            args=deploy_args,
        ),
        artifacts_dir=artifacts_dir,
    )
    return new_resource(KIND, name, namespace, spec=spec.to_dict())
