"""Training runtime: train-step factories, data, metrics, checkpointing.

This is the tier the reference delegated to container images entirely
(`tf_cnn_benchmarks` inside pinned TF images — SURVEY.md §2 item 21, §6):
here it is a first-class library so the platform's operators, tuning
studies, and benchmarks all drive one code path.
"""

from kubeflow_tpu.train.trainer import Trainer, TrainConfig, TrainState
from kubeflow_tpu.train.data import SyntheticImages, SyntheticTokens
from kubeflow_tpu.train.checkpoint import Checkpointer, Restored
from kubeflow_tpu.train.guard import AnomalyGuard, GuardConfig
from kubeflow_tpu.train.loop import (
    ElasticResize,
    FitResult,
    Preempted,
    ResizeEvent,
    ResizeProposal,
    TrainingDiverged,
    fit,
)
from kubeflow_tpu.train.profiling import (
    MetricsLogger,
    PhaseRoofline,
    PhaseStat,
    Profiler,
    ProfileSchedule,
    annotate,
    annotated_scope,
    time_phase,
)
