"""Checkpoint / auto-resume.

The reference had no checkpoint story at all — training state was "the
job's problem" and platform-level resume meant idempotent re-apply
(SURVEY.md §5, checkpoint row). On TPU slices that is untenable: one host
failure kills the whole gang (§7.3), so save/restore is a core library.

Built on orbax CheckpointManager: async saves (training continues while the
write completes), retention policy, and sharded restore — each device reads
only its own shards, laid out by the NamedShardings of the abstract state.
"""

from __future__ import annotations

import logging
from pathlib import Path
from typing import Any

import jax
import orbax.checkpoint as ocp

log = logging.getLogger(__name__)


class Checkpointer:
    """Thin, typed wrapper over orbax for TrainState pytrees."""

    def __init__(
        self,
        directory: str | Path,
        *,
        save_interval_steps: int = 100,
        max_to_keep: int = 3,
    ):
        self.directory = Path(directory).absolute()
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                save_interval_steps=save_interval_steps,
                max_to_keep=max_to_keep,
                create=True,
                enable_async_checkpointing=True,
            ),
        )

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        """Maybe-save (respects save_interval_steps unless force)."""
        return self._mgr.save(
            step, args=ocp.args.StandardSave(state), force=force
        )

    def should_save(self, step: int) -> bool:
        """Would `save(step)` actually write? Lets callers run pre-save
        validation (e.g. divergence checks) only when it matters."""
        return self._mgr.should_save(step)

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore_latest(self, abstract_state: Any) -> tuple[Any, int] | None:
        """Restore the newest checkpoint onto `abstract_state`'s shardings.

        `abstract_state` is a pytree of jax.ShapeDtypeStruct (with
        .sharding set for sharded restore) — the Trainer's
        `abstract_state()` output. Returns None when no checkpoint exists.
        """
        step = self._mgr.latest_step()
        if step is None:
            return None
        state = self._mgr.restore(
            step, args=ocp.args.StandardRestore(abstract_state)
        )
        log.info("restored checkpoint step=%d from %s", step, self.directory)
        return state, step

    def wait(self) -> None:
        """Block until in-flight async saves are durable (call before
        process exit so a preemption can't lose the final save)."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
