"""Checkpoint / auto-resume with crash-consistent, verified saves.

The reference had no checkpoint story at all — training state was "the
job's problem" and platform-level resume meant idempotent re-apply
(SURVEY.md §5, checkpoint row). On TPU slices that is untenable: one host
failure kills the whole gang (§7.3), so save/restore is a core library.

Built on orbax CheckpointManager: async saves (training continues while
the write completes), retention policy, and sharded restore — each device
reads only its own shards, laid out by the NamedShardings of the abstract
state. On top of orbax, this module adds the durability contract a
preemptible fleet actually needs (docs/resilience.md):

- **Verification manifest.** After each save COMMITS, a background
  worker writes `kftpu_manifest.json` into the step directory: size +
  sha256 for every file orbax wrote, plus the data-iterator state
  captured at the step boundary. The manifest is written atomically
  (tmp + fsync + rename), so its presence certifies a complete,
  uncorrupted checkpoint — a SIGKILL between orbax's commit and the
  manifest write leaves an unverifiable step that restore treats as
  garbage, never a torn read.
- **Fallback restore.** `restore_latest` verifies the newest step
  against its manifest (and survives orbax restore errors); a step that
  fails is QUARANTINED (renamed out of the numeric step namespace, so a
  later save at the same step can't collide) and the next-newest valid
  checkpoint is tried. Corruption costs the steps since the last good
  save, not the run.
- **Resumable data.** The manifest carries the training data iterator's
  `state_dict()` so resume continues the batch sequence exactly —
  neither repeating nor skipping examples (`train/data.py` protocol).

**Single-writer contract.** One process owns a checkpoint directory's
mutations: saves, manifest writes, and quarantine renames. Everything
else opens the directory with `read_only=True` (saves refused, invalid
steps skipped non-destructively, directory never created). In a
multi-host gang, that writer is process 0 of a single-controller setup
— running N writer-mode Checkpointers over one shared directory is NOT
supported: each would re-hash every host's shards after every save
(O(N × checkpoint size) redundant reads) and their quarantine renames
could race another host's in-flight sharded restore, leaving hosts
resumed at different steps. Cross-host restore agreement (all hosts
picking the same fallback step) requires a collective the platform's
gang-restart path provides by restarting the whole gang from one
process's decision; see docs/resilience.md.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import queue
import threading
from pathlib import Path
from typing import Any, NamedTuple

import jax
import orbax.checkpoint as ocp

from kubeflow_tpu.utils import threads

log = logging.getLogger(__name__)

# Inside each step dir, next to orbax's files (which never collide with
# it); the checksums cover every file EXCEPT the manifest itself.
MANIFEST_NAME = "kftpu_manifest.json"
# Non-numeric prefix = invisible to orbax's step scan.
QUARANTINE_PREFIX = "corrupt-"


class Restored(NamedTuple):
    """`restore_latest` result: the state pytree, the step it was saved
    at, and the data-iterator state captured at that boundary (None for
    checkpoints saved without one)."""

    state: Any
    step: int
    data_state: dict | None


def _file_digest(path: Path) -> tuple[int, str]:
    h = hashlib.sha256()
    size = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            size += len(chunk)
            h.update(chunk)
    return size, h.hexdigest()


def write_manifest(step_dir: Path, data_state: dict | None) -> dict:
    """Checksum every committed file under `step_dir` and write the
    manifest atomically. Returns the manifest dict."""
    files: dict[str, dict] = {}
    for p in sorted(step_dir.rglob("*")):
        # Skip the manifest AND any leftover .tmp from a failed prior
        # attempt — checksumming a file that os.replace then removes
        # would make the manifest permanently self-invalidating.
        if not p.is_file() or p.name.startswith(MANIFEST_NAME):
            continue
        size, digest = _file_digest(p)
        files[str(p.relative_to(step_dir))] = {"size": size, "sha256": digest}
    if not files:
        # The checksum walk found NOTHING: retention eviction's rmtree
        # emptied the directory under us (files go before the dir). A
        # vacuous manifest would verify trivially yet restore nothing —
        # and writing it into the half-deleted dir can even break
        # rmtree's final rmdir (ENOTEMPTY), leaving a trap in the
        # numeric step namespace. Report it like any other vanished-
        # file race instead.
        raise FileNotFoundError(f"no files to certify under {step_dir}")
    manifest = {"version": 1, "files": files, "data_state": data_state}
    _replace_manifest(step_dir, manifest)
    return manifest


def _replace_manifest(step_dir: Path, manifest: dict) -> None:
    tmp = step_dir / (MANIFEST_NAME + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f)
        f.flush()
        os.fsync(f.fileno())
    # rename is the commit point: a crash leaves either no manifest
    # (unverifiable step -> restore falls back) or a complete one.
    os.replace(tmp, step_dir / MANIFEST_NAME)


def verify_manifest(step_dir: Path) -> dict | None:
    """The manifest if `step_dir` is a complete, uncorrupted checkpoint;
    None for anything else (missing/garbled manifest, missing file,
    size or checksum mismatch) — the caller falls back, never crashes."""
    try:
        with open(step_dir / MANIFEST_NAME) as f:
            manifest = json.load(f)
        files = manifest["files"]
    except (OSError, ValueError, KeyError, TypeError):
        # Unreadable, non-JSON, or JSON of the wrong shape (a list, a
        # null, a missing key): all just "corrupt manifest".
        return None
    if not isinstance(files, dict) or not files:
        # A manifest certifying ZERO files certifies nothing — it can
        # only come from a manifest write racing eviction (or hand
        # tampering) and a step that "verifies" but cannot restore
        # would turn the fallback walk into a hard crash.
        return None
    for rel, want in files.items():
        p = step_dir / rel
        try:
            size, digest = _file_digest(p)
        except OSError:
            return None
        if not isinstance(want, dict):
            return None
        if size != want.get("size") or digest != want.get("sha256"):
            return None
    return manifest


class Checkpointer:
    """Thin, typed wrapper over orbax for TrainState pytrees."""

    def __init__(
        self,
        directory: str | Path,
        *,
        save_interval_steps: int = 100,
        max_to_keep: int = 3,
        verify: bool = True,
        read_only: bool = False,
    ):
        """`read_only=True` marks a restore-only consumer (serving, an
        inspection job): `save()` is refused, the directory is never
        created (a mistyped path raises FileNotFoundError instead of
        mkdir-ing junk on the restore path), and invalid steps are
        SKIPPED non-destructively during restore instead of quarantined
        — renaming belongs to the directory's single writer, whose own
        restore must clear a torn step out of the numeric namespace
        before it can save there again. Read-only consumers may race
        that writer's in-flight saves (a committed step whose manifest
        is still being written looks unverifiable); skipping costs them
        freshness, renaming would cost the writer its checkpoint."""
        self.directory = Path(directory).absolute()
        self.verify = verify
        self.read_only = read_only
        if read_only and not self.directory.is_dir():
            raise FileNotFoundError(
                f"checkpoint directory {self.directory} does not exist "
                "(read_only Checkpointer never creates it)"
            )
        self._save_interval_steps = save_interval_steps
        self._max_to_keep = max_to_keep
        self._mgr = self._make_mgr()
        # Manifest writer: one worker drains (step, data_state) items,
        # waiting for the orbax commit before checksumming — saves stay
        # async for the training loop, but every committed step gets a
        # manifest without the step loop ever blocking on hashing.
        self._manifest_q: queue.Queue = queue.Queue()
        self._manifest_errors: list[Exception] = []
        self._manifest_thread: threading.Thread | None = None

    def _make_mgr(self) -> ocp.CheckpointManager:
        return ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                save_interval_steps=self._save_interval_steps,
                max_to_keep=self._max_to_keep,
                create=not self.read_only,
                enable_async_checkpointing=True,
            ),
        )

    # -- manifest worker ---------------------------------------------------

    def _manifest_loop(self) -> None:
        while True:
            item = self._manifest_q.get()
            try:
                if item is None:
                    return
                step, data_state = item
                try:
                    # Block THIS thread (not the step loop) until the
                    # async save commits, then certify what landed on
                    # disk. A commit FAILURE (disk full, IO error) is
                    # always an error — the step was never durable.
                    self._mgr.wait_until_finished()
                except Exception as e:
                    log.exception("async save for step %s failed", step)
                    self._manifest_errors.append(e)
                    continue
                step_dir = self.directory / str(step)
                # Retention eviction can race the checksum pass: rmtree
                # deletes files before the directory, so a first failure
                # with the dir still present may just be mid-eviction —
                # retry once, and only record an error if the dir
                # SURVIVES a failed retry (a real IO problem, not an
                # evicted step that needs no manifest anyway).
                for attempt in (0, 1):
                    try:
                        if step_dir.is_dir():
                            write_manifest(step_dir, data_state)
                        else:
                            log.info(
                                "checkpoint step %s evicted before its "
                                "manifest was written", step,
                            )
                        break
                    except FileNotFoundError:
                        # rmtree deletes files before the directory: a
                        # file vanishing beneath the checksum walk is
                        # retention eviction in progress even when the
                        # dir still exists on the immediate retry (a
                        # large step can stay mid-rmtree across both
                        # attempts). The evicted step needs no manifest
                        # — and if its files vanished for any other
                        # reason the step is simply unverifiable, which
                        # restore already treats as invalid. Either way
                        # it is never a durability failure of the save
                        # that just committed, so don't poison a later
                        # clean-exit wait() with it.
                        log.info(
                            "checkpoint step %s files vanished mid-"
                            "checksum (eviction in progress)", step,
                        )
                        break
                    except Exception as e:
                        if not step_dir.is_dir():
                            log.info(
                                "checkpoint step %s evicted mid-"
                                "checksum", step,
                            )
                            break
                        if attempt:  # recorded; surfaced by wait()
                            log.exception(
                                "manifest write for step %s failed", step
                            )
                            self._manifest_errors.append(e)
            finally:
                self._manifest_q.task_done()

    def _enqueue_manifest(self, step: int, data_state: dict | None) -> None:
        if self._manifest_thread is None or not self._manifest_thread.is_alive():
            self._manifest_thread = threading.Thread(
                target=self._manifest_loop, name="ckpt-manifest", daemon=True
            )
            self._manifest_thread.start()
        self._manifest_q.put((step, data_state))

    # -- save --------------------------------------------------------------

    def save(
        self,
        step: int,
        state: Any,
        *,
        force: bool = False,
        data_state: dict | None = None,
    ) -> bool:
        """Maybe-save (respects save_interval_steps unless force).
        `data_state` is the data iterator's `state_dict()` captured at
        this step boundary; it rides in the verification manifest so
        resume continues the exact batch sequence."""
        if self.read_only:
            raise RuntimeError(
                f"Checkpointer({self.directory}) is read_only: save() "
                "refused — only the directory's single writer may write"
            )
        saved = self._mgr.save(
            step, args=ocp.args.StandardSave(state), force=force
        )
        if saved:
            self._enqueue_manifest(step, data_state)
        return saved

    def update_data_state(
        self, step: int, data_state: dict | None
    ) -> bool:
        """Atomically replace the data-iterator state carried by an
        EXISTING step's manifest — files and checksums untouched, so
        the step still verifies. Divergence rollback uses this to make
        the perturbed salt durable immediately: a crash between the
        rollback and the next periodic save must resume onto the NEW
        trajectory, not replay the one that already diverged. Returns
        False when the step has no readable manifest to update (a
        verify=False or legacy writer's step)."""
        if self.read_only:
            raise RuntimeError(
                f"Checkpointer({self.directory}) is read_only: "
                "update_data_state() refused"
            )
        step_dir = self.directory / str(step)
        try:
            with open(step_dir / MANIFEST_NAME) as f:
                manifest = json.load(f)
        except (OSError, ValueError):
            return False
        if not isinstance(manifest, dict):
            return False
        manifest["data_state"] = data_state
        _replace_manifest(step_dir, manifest)
        return True

    def should_save(self, step: int) -> bool:
        """Would `save(step)` actually write? Lets callers run pre-save
        validation (e.g. divergence checks) only when it matters."""
        return not self.read_only and self._mgr.should_save(step)

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def all_steps(self) -> list[int]:
        return list(self._mgr.all_steps())

    # -- restore -----------------------------------------------------------

    def _quarantine(self, step: int) -> None:
        """Move an invalid step out of the numeric namespace (orbax's
        step scan ignores it) and rebuild the manager so its cached step
        list forgets the step — a later save at the same number must not
        collide with the corpse."""
        step_dir = self.directory / str(step)
        target = self.directory / f"{QUARANTINE_PREFIX}{step}"
        n = 0
        while target.exists():
            n += 1
            target = self.directory / f"{QUARANTINE_PREFIX}{step}.{n}"
        try:
            os.rename(step_dir, target)
            log.warning(
                "quarantined invalid checkpoint step %d -> %s",
                step, target.name,
            )
        except OSError:
            if step_dir.exists():
                # The rename failed but the corpse is still there (a
                # read-only mount, missing permissions): we can neither
                # clear nor reuse the step — surface it instead of
                # looping over the same invalid step forever.
                raise
            # Already gone (e.g. another process's retention eviction
            # raced us) — refreshing the manager below is all we need.
            log.warning("invalid checkpoint step %d disappeared", step)
        self._mgr.close()
        self._mgr = self._make_mgr()

    def restore_latest(self, abstract_state: Any) -> Restored | None:
        """Restore the newest VALID checkpoint onto `abstract_state`'s
        shardings.

        `abstract_state` is a pytree of jax.ShapeDtypeStruct (with
        .sharding set for sharded restore) — the Trainer's
        `abstract_state()` output. Returns None when no (valid)
        checkpoint exists.

        Every candidate step is verified against its manifest first
        (unless verify=False): a torn write, a flipped byte, a garbled
        manifest, or a step directory evicted mid-restore all fall back
        to the next-newest — corruption costs the steps since the last
        good save, never a crash or a silent load of damaged state. The
        directory's WRITER additionally quarantines each invalid step
        (renamed out of the numeric namespace, so its own later save at
        that number can't collide); `read_only` consumers skip
        non-destructively (see __init__).
        """
        self.wait()  # manifests for in-flight saves must be on disk
        # One descending walk over a snapshot of the step list: each
        # candidate is visited at most once, so an unremovable invalid
        # step can never spin this into a loop.
        for step in sorted(self._mgr.all_steps(), reverse=True):
            step_dir = self.directory / str(step)
            if self.verify:
                manifest = verify_manifest(step_dir)
                if manifest is None:
                    log.warning(
                        "checkpoint step %d failed verification "
                        "(corrupt, torn, or written without a manifest "
                        "— e.g. by a pre-manifest or verify=False "
                        "writer); falling back to the previous "
                        "checkpoint", step,
                    )
                    self._invalidate(step)
                    continue
            else:
                # No digest checks, but the manifest (when present)
                # still carries the data-iterator state resume needs.
                try:
                    with open(step_dir / MANIFEST_NAME) as f:
                        manifest = json.load(f)
                    if not isinstance(manifest, dict):
                        manifest = {}
                except (OSError, ValueError):
                    manifest = {}
            try:
                state = self._mgr.restore(
                    step, args=ocp.args.StandardRestore(abstract_state)
                )
            except Exception:
                # Orbax failed after verification passed. If the step
                # is still on disk and still certifies, the bytes are
                # fine — the failure is the CALLER'S (e.g. an
                # abstract_state whose tree no longer matches what was
                # saved, a changed TrainState shape): surface it loudly
                # rather than silently discarding the entire checkpoint
                # history and restarting from scratch.
                if (
                    self.verify
                    and step_dir.is_dir()
                    and verify_manifest(step_dir) is not None
                ):
                    raise
                # Otherwise the step vanished mid-restore (another
                # writer's retention eviction) or verify=False let a
                # corrupt step through: fall back.
                log.exception(
                    "restore of checkpoint step %d failed; falling back",
                    step,
                )
                self._invalidate(step)
                continue
            log.info(
                "restored checkpoint step=%d from %s", step, self.directory
            )
            return Restored(state, step, manifest.get("data_state"))
        return None

    def _invalidate(self, step: int) -> None:
        """Handle an invalid step per role: the writer quarantines it
        (it must be able to re-save that step number); a read-only
        consumer just leaves it for the writer and keeps walking."""
        if self.read_only:
            log.warning(
                "read-only restore skipping invalid checkpoint step %d "
                "(the writing process owns quarantine)", step,
            )
        else:
            self._quarantine(step)

    # -- lifecycle ---------------------------------------------------------

    def wait(self) -> None:
        """Block until in-flight async saves are durable AND their
        manifests are written (call before process exit so a preemption
        can't lose the final save or leave it unverifiable)."""
        self._mgr.wait_until_finished()
        # Bounded drain (KFTPU_STUCK_TIMEOUT_S): a wedged manifest
        # writer must fail the exit path loudly, not hang the trainer
        # silently through its final save.
        threads.join_queue(
            self._manifest_q, what="checkpoint manifest queue"
        )
        if self._manifest_errors:
            errors, self._manifest_errors = self._manifest_errors, []
            raise RuntimeError(
                f"checkpoint manifest writes failed: {errors!r}"
            ) from errors[0]

    def close(self) -> None:
        try:
            self.wait()
        finally:
            if self._manifest_thread is not None:
                self._manifest_q.put(None)
            self._mgr.close()
