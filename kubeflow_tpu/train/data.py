"""Input pipelines.

`SyntheticImages` mirrors the reference benchmark's default data mode:
`tf_cnn_benchmarks` runs on synthetic data unless told otherwise
(`tf-controller-examples/tf-cnn/README.md:19`), which isolates accelerator
throughput from input IO. Batches are created *already sharded* (jit with
out_shardings) so no single device ever holds the global batch, and
iteration costs nothing on the host — measured steps/sec is pure device
time.
"""

from __future__ import annotations

from typing import Iterator

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_tpu.parallel.sharding import batch_axes, batch_sharding


class SyntheticImages:
    """An infinite stream of one device-resident image batch."""

    def __init__(
        self,
        mesh: Mesh,
        batch_size: int,
        image_size: int = 224,
        num_classes: int = 1000,
        channels: int = 3,
        seed: int = 0,
        dtype=jnp.float32,
    ):
        if batch_size <= 0:
            raise ValueError(f"batch_size must be positive, got {batch_size}")
        k_img, k_lbl = jax.random.split(jax.random.PRNGKey(seed))
        sharding = batch_sharding(mesh, ndim=1)

        def make():
            img = jax.random.normal(
                k_img, (batch_size, image_size, image_size, channels), dtype
            )
            lbl = jax.random.randint(k_lbl, (batch_size,), 0, num_classes)
            return {"image": img, "label": lbl}

        self.batch = jax.jit(make, out_shardings=sharding)()
        self.batch_size = batch_size

    def __iter__(self) -> Iterator[dict]:
        while True:
            yield self.batch


class SyntheticTokens:
    """Synthetic LM batches: random token ids, next-token labels."""

    def __init__(
        self,
        mesh: Mesh,
        batch_size: int,
        seq_len: int,
        vocab_size: int,
        seed: int = 0,
    ):
        key = jax.random.PRNGKey(seed)
        # Sequence dim rides sp when present so ring attention gets
        # pre-sharded inputs.
        seq_axis = "sp" if "sp" in mesh.axis_names else None
        sharding = NamedSharding(mesh, P(batch_axes(mesh), seq_axis))

        def make():
            tokens = jax.random.randint(
                key, (batch_size, seq_len + 1), 0, vocab_size
            )
            return {"tokens": tokens[:, :-1], "labels": tokens[:, 1:]}

        self.batch = jax.jit(make, out_shardings=sharding)()
        self.batch_size = batch_size

    def __iter__(self) -> Iterator[dict]:
        while True:
            yield self.batch
