"""Input pipelines.

`SyntheticImages` mirrors the reference benchmark's default data mode:
`tf_cnn_benchmarks` runs on synthetic data unless told otherwise
(`tf-controller-examples/tf-cnn/README.md:19`), which isolates accelerator
throughput from input IO. Batches are created *already sharded* (jit with
out_shardings) so no single device ever holds the global batch, and
iteration costs nothing on the host — measured steps/sec is pure device
time.

Resumable-data protocol (docs/resilience.md): training iterables may
expose ``state_dict()`` / ``load_state_dict(sd)`` and the loop persists
that state inside every checkpoint, so a preempted run resumes the batch
sequence exactly — no repeated and no skipped examples. The optional
``perturb(salt)`` hook changes the FUTURE batch sequence without moving
the position; the loop calls it on divergence rollback so the retried
trajectory sees different data (the seed-perturbation escape hatch).
Both synthetic streams implement the protocol; positions count batches
yielded, which the loop keeps 1:1 with optimizer steps. ``perturb`` is
only offered with ``vary_per_step=True`` — a fixed single-batch stream
cannot change its future, so it exposes ``perturb = None`` and the
loop's rollback precondition refuses rather than replaying an
identical diverging trajectory.
"""

from __future__ import annotations

from typing import Iterator

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_tpu.parallel.sharding import batch_axes, batch_sharding


class _SyntheticStream:
    """Shared machinery for the synthetic streams: position/salt
    bookkeeping plus the per-step-vs-cached batch dispatch. Subclasses
    define the batch recipe and call `_init_stream` with it.

    State is on the ITERABLE (single live iterator per stream — the
    training loop's usage): `state_dict` snapshots the number of batches
    yielded, `load_state_dict` repositions, and iteration continues from
    there. With `vary_per_step=False` every batch is identical (the
    device-throughput-benchmark mode), so the position only matters for
    bookkeeping; with `vary_per_step=True` the batch at position p is a
    pure function of (seed, salt, p) — resume and rollback reproduce the
    exact sequence."""

    def _init_stream(self, make, sharding, vary_per_step: bool) -> None:
        """`make(pos, salt)` builds one batch from traced int32 scalars
        (one compile, any position)."""
        self.vary_per_step = vary_per_step
        self._position = 0
        self._salt = 0
        if vary_per_step:
            self._make = jax.jit(make, out_shardings=sharding)
        else:
            # A fixed stream cannot honor perturb(): every position
            # yields the identical cached batch, so a new salt changes
            # nothing. Shadow the method so capability probes (fit()'s
            # rollback precondition) see no perturb and refuse up front
            # instead of burning the rollback budget on byte-identical
            # retries of a trajectory that already diverged.
            self.perturb = None
            self.batch = jax.jit(make, out_shardings=sharding)(
                jnp.int32(0), jnp.int32(0)
            )

    # -- resumable-data protocol -------------------------------------------

    def rebind(self, mesh: Mesh) -> "_SyntheticStream":
        """The SAME stream on a different mesh — the data half of the
        elastic gang resize (docs/resilience.md). Batch content is a
        pure function of (seed, salt, position) and never of the mesh
        (the partitionable threefry derives every element's bits from
        its logical index), so the rebound stream yields bit-identical
        batches from the transplanted position: the (step -> batch
        position) identity mapping holds across a resize — zero
        repeated and zero skipped examples. Only the sharding layout of
        the yielded batches changes."""
        clone = type(self)(mesh, **self._ctor)
        clone.load_state_dict(self.state_dict())
        return clone

    def state_dict(self) -> dict:
        return {"position": self._position, "salt": self._salt}

    def load_state_dict(self, state: dict) -> None:
        self._position = int(state["position"])
        self._salt = int(state.get("salt", 0))

    def perturb(self, salt: int) -> None:
        """Reseed the FUTURE sequence without moving the position —
        divergence rollback's escape hatch. Only offered on
        `vary_per_step=True` streams (on a fixed stream the hook is
        shadowed to None, so `fit()` refuses rollback rather than
        retrying an identical trajectory)."""
        self._salt = int(salt)

    def __iter__(self) -> Iterator[dict]:
        while True:
            if self.vary_per_step:
                batch = self._make(
                    jnp.int32(self._position), jnp.int32(self._salt)
                )
            else:
                batch = self.batch
            self._position += 1
            yield batch


class SyntheticImages(_SyntheticStream):
    """An infinite stream of device-resident image batches.

    Default: ONE batch, yielded forever (pure device-throughput
    benchmarking). `vary_per_step=True` derives each batch from the
    yield position instead — per-position-unique, deterministic, and
    resumable, which is what the preemption soak trains on."""

    def __init__(
        self,
        mesh: Mesh,
        batch_size: int,
        image_size: int = 224,
        num_classes: int = 1000,
        channels: int = 3,
        seed: int = 0,
        dtype=jnp.float32,
        vary_per_step: bool = False,
    ):
        if batch_size <= 0:
            raise ValueError(f"batch_size must be positive, got {batch_size}")
        key = jax.random.PRNGKey(seed)
        self.batch_size = batch_size
        # Everything `rebind(mesh)` needs to rebuild this stream on a
        # resized mesh with the identical batch recipe.
        self._ctor = dict(
            batch_size=batch_size, image_size=image_size,
            num_classes=num_classes, channels=channels, seed=seed,
            dtype=dtype, vary_per_step=vary_per_step,
        )

        def make(pos, salt):
            k = jax.random.fold_in(jax.random.fold_in(key, salt), pos)
            k_img, k_lbl = jax.random.split(k)
            img = jax.random.normal(
                k_img, (batch_size, image_size, image_size, channels), dtype
            )
            lbl = jax.random.randint(k_lbl, (batch_size,), 0, num_classes)
            return {"image": img, "label": lbl}

        self._init_stream(make, batch_sharding(mesh, ndim=1), vary_per_step)


class SyntheticTokens(_SyntheticStream):
    """Synthetic LM batches: random token ids, next-token labels.

    Same single-batch default / `vary_per_step` split as
    `SyntheticImages`, same resumable-state protocol."""

    def __init__(
        self,
        mesh: Mesh,
        batch_size: int,
        seq_len: int,
        vocab_size: int,
        seed: int = 0,
        vary_per_step: bool = False,
    ):
        key = jax.random.PRNGKey(seed)
        # Sequence dim rides sp when present so ring attention gets
        # pre-sharded inputs.
        seq_axis = "sp" if "sp" in mesh.axis_names else None
        sharding = NamedSharding(mesh, P(batch_axes(mesh), seq_axis))
        self.batch_size = batch_size
        self._ctor = dict(
            batch_size=batch_size, seq_len=seq_len,
            vocab_size=vocab_size, seed=seed,
            vary_per_step=vary_per_step,
        )

        def make(pos, salt):
            k = jax.random.fold_in(jax.random.fold_in(key, salt), pos)
            tokens = jax.random.randint(
                k, (batch_size, seq_len + 1), 0, vocab_size
            )
            return {"tokens": tokens[:, :-1], "labels": tokens[:, 1:]}

        self._init_stream(make, sharding, vary_per_step)
