"""Per-step training anomaly guard: device-side, never-persist-a-NaN.

The seed loop's divergence story was a host-side `check_finite` that ran
only at log/save steps — a NaN at step 51 burned chips until step 100
and the only remedy was an exception. This module is the production
posture instead (PaLM-style loss-spike handling; `optax.apply_if_finite`
generalized to spike detection):

- EVERY step is screened on device: loss/grad-norm finiteness plus an
  EWMA spike test. No per-step host sync — the verdict is a device
  scalar that selects between the applied and skipped state inside the
  jitted train step; the host reads the counters only when it already
  reads metrics (log/save boundaries).
- A bad step is SKIPPED, not fatal: params, optimizer state and BN
  stats keep their pre-step values (the step counter still advances so
  checkpoint/data bookkeeping stays aligned). One poison batch costs
  one update, never the run.
- Skips are bounded: `max_consecutive_skips` rejected steps in a row
  flip a sticky `diverged` flag. The loop reacts by rolling back to the
  last checkpoint with a seed perturbation (`train/loop.py`), because a
  run that rejects everything is not training — it is diverged and
  needs a different trajectory, not more skips.

Guard state is a pytree of device scalars that rides INSIDE TrainState,
so it is checkpointed and restored with the params: a resumed run
remembers its skip counters, and a rollback resets them to the last
good state's values for free.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class GuardConfig:
    """Thresholds for the anomaly guard.

    The spike tests compare each step's loss/grad-norm against an EWMA
    of the ACCEPTED steps only (a skipped step must not drag the
    baseline toward the anomaly it was rejected for).
    """

    # EWMA smoothing for the accepted-loss / accepted-grad-norm
    # baselines. 0.05 ≈ a ~20-step memory: long enough to be stable,
    # short enough to track warmup-phase loss drops.
    ewma_alpha: float = 0.05
    # Spike detection stays off until this many steps were ACCEPTED —
    # the EWMA means nothing before it has data. Finiteness screening
    # is always on, from step 0.
    warmup_steps: int = 10
    # Skip the update when loss > loss_spike_factor * ewma_loss +
    # spike_slack. The multiplicative form is scale-free (works at CE≈7
    # and CE≈0.7 alike) but assumes a POSITIVE baseline — with a
    # non-positive EWMA (signed reward-style objectives) the spike test
    # disarms rather than misfires. The additive slack keeps
    # near-converged runs from flagging noise on a tiny positive
    # baseline; it defaults to 0 (off) — set it when losses approach 0.
    loss_spike_factor: float = 2.0
    spike_slack: float = 0.0
    # Same test for the global gradient norm — the earlier signal: a
    # poison batch often shows a 100x grad-norm before the loss moves.
    grad_spike_factor: float = 4.0
    # Sticky divergence after this many consecutive skips: the loop
    # rolls back to the last checkpoint (with a seed perturbation)
    # instead of skipping forever.
    max_consecutive_skips: int = 5

    def __post_init__(self) -> None:
        if not 0.0 < self.ewma_alpha <= 1.0:
            raise ValueError(f"ewma_alpha must be in (0, 1], got {self.ewma_alpha}")
        if self.loss_spike_factor <= 1.0 or self.grad_spike_factor <= 1.0:
            raise ValueError(
                "spike factors must be > 1 (a factor <= 1 would flag "
                f"ordinary steps): got loss={self.loss_spike_factor}, "
                f"grad={self.grad_spike_factor}"
            )
        if self.max_consecutive_skips < 1:
            raise ValueError(
                f"max_consecutive_skips must be >= 1, got "
                f"{self.max_consecutive_skips}"
            )


class AnomalyGuard:
    """Device-side per-step screen: finiteness + EWMA spike detection.

    Pure-functional: `init_state()` makes the scalar pytree,
    `apply(gstate, loss, grad_norm)` returns `(new_gstate, ok)` and is
    traced into the train step. The host-side helpers (`diverged`,
    `skipped_total`) read device scalars — call them only where the
    host already syncs (log/save boundaries), never per step.
    """

    def __init__(self, config: GuardConfig | None = None):
        self.config = config or GuardConfig()

    # -- device side (traced into the train step) --------------------------

    def init_state(self) -> dict[str, jax.Array]:
        return {
            "ewma_loss": jnp.zeros((), jnp.float32),
            "ewma_grad_norm": jnp.zeros((), jnp.float32),
            "accepted": jnp.zeros((), jnp.int32),
            "consecutive_skips": jnp.zeros((), jnp.int32),
            "skipped_total": jnp.zeros((), jnp.int32),
            "diverged": jnp.zeros((), jnp.int32),
        }

    def apply(
        self,
        gstate: dict,
        loss: jax.Array,
        grad_norm: jax.Array,
        update_finite: jax.Array | None = None,
    ) -> tuple[dict, jax.Array]:
        """One step's verdict. Returns (new_gstate, ok) where `ok` is a
        device bool scalar: True = apply the update, False = skip it.

        `update_finite` is the finiteness of the UPDATED state itself
        (the trainer passes an isfinite reduction over the post-update
        params): a finite loss and grad-norm do not guarantee the
        applied step stays finite — e.g. a huge-but-finite warmup
        gradient can overflow a parameter to inf — and an accepted
        overflow would poison every later checkpoint. Screening the
        update closes that hole at the verdict."""
        cfg = self.config
        loss = loss.astype(jnp.float32)
        grad_norm = grad_norm.astype(jnp.float32)

        finite = jnp.isfinite(loss) & jnp.isfinite(grad_norm)
        if update_finite is not None:
            finite = finite & update_finite
        warm = gstate["accepted"] >= cfg.warmup_steps
        # The multiplicative test only means anything against a POSITIVE
        # baseline: with ewma <= 0 (a reward-style signed objective, or
        # a degenerate all-zero grad norm) the threshold factor*ewma
        # would sit below every ordinary step and flag all of them — so
        # the spike test disarms there instead of misfiring (finiteness
        # screening still covers those runs; set spike_slack > 0 for an
        # additive threshold that works near zero).
        loss_spike = warm & (gstate["ewma_loss"] > 0) & (
            loss > cfg.loss_spike_factor * gstate["ewma_loss"] + cfg.spike_slack
        )
        grad_spike = warm & (gstate["ewma_grad_norm"] > 0) & (
            grad_norm
            > cfg.grad_spike_factor * gstate["ewma_grad_norm"] + cfg.spike_slack
        )
        ok = finite & ~loss_spike & ~grad_spike

        # The EWMA advances on accepted steps only, seeded by the first
        # accepted observation (an average that starts at 0 would flag
        # step warmup_steps+1 as a spike against a near-zero baseline).
        a = jnp.float32(cfg.ewma_alpha)
        first = gstate["accepted"] == 0
        upd_loss = jnp.where(
            first, loss, (1.0 - a) * gstate["ewma_loss"] + a * loss
        )
        upd_gnorm = jnp.where(
            first, grad_norm, (1.0 - a) * gstate["ewma_grad_norm"] + a * grad_norm
        )
        oki = ok.astype(jnp.int32)
        consecutive = jnp.where(ok, 0, gstate["consecutive_skips"] + 1)
        new_state = {
            "ewma_loss": jnp.where(ok, upd_loss, gstate["ewma_loss"]),
            "ewma_grad_norm": jnp.where(ok, upd_gnorm, gstate["ewma_grad_norm"]),
            "accepted": gstate["accepted"] + oki,
            "consecutive_skips": consecutive,
            "skipped_total": gstate["skipped_total"] + (1 - oki),
            # Sticky: once diverged, stays diverged until the loop rolls
            # back (restoring the pre-divergence guard state with it).
            "diverged": jnp.maximum(
                gstate["diverged"],
                (consecutive >= cfg.max_consecutive_skips).astype(jnp.int32),
            ),
        }
        return new_state, ok

    def metrics(self, gstate: dict, ok: jax.Array, grad_norm: jax.Array) -> dict:
        """Device-scalar metric entries for the step's metrics dict —
        fetched by the host only at its existing log/save boundaries."""
        return {
            "grad_norm": grad_norm,
            "guard_ok": ok.astype(jnp.int32),
            "guard_skipped_total": gstate["skipped_total"],
            "guard_consecutive_skips": gstate["consecutive_skips"],
            "guard_diverged": gstate["diverged"],
        }

    # -- host side (boundary-only reads) -----------------------------------

    @staticmethod
    def diverged(gstate: Any) -> bool:
        """Host-sync read of the sticky divergence flag. Boundary-only."""
        return bool(int(gstate["diverged"]))

    @staticmethod
    def skipped_total(gstate: Any) -> int:
        return int(gstate["skipped_total"])
