"""The training loop: resume, step, guard, checkpoint, report.

Failure semantics the reference lacked (SURVEY.md §5 "no elastic training,
no preemption handling"): the loop auto-resumes from the newest checkpoint,
detects divergence (NaN/inf loss) and raises instead of burning chips, and
forces a final durable save on exit — so the TpuJob operator's
restart-the-gang-on-failure policy composes with it to give
checkpoint-restart elasticity.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Callable, Iterable

import jax
import numpy as np

from kubeflow_tpu.train.checkpoint import Checkpointer
from kubeflow_tpu.train.profiling import Profiler
from kubeflow_tpu.train.trainer import Trainer, TrainState

log = logging.getLogger(__name__)


class TrainingDiverged(RuntimeError):
    """Loss became non-finite; restart from the last checkpoint with a
    different seed/schedule rather than continuing."""


@dataclasses.dataclass
class FitResult:
    state: TrainState
    history: list[dict]
    steps_done: int
    resumed_from: int | None


def fit(
    trainer: Trainer,
    data: Iterable[dict],
    total_steps: int,
    *,
    rng: jax.Array | None = None,
    checkpointer: Checkpointer | None = None,
    log_every: int = 50,
    on_metrics: Callable[[int, dict], None] | None = None,
    profiler: "Profiler | None" = None,
) -> FitResult:
    """Train for `total_steps` global steps, resuming if possible."""
    rng = rng if rng is not None else jax.random.PRNGKey(0)

    resumed_from = None
    state = None
    if checkpointer is not None:
        restored = checkpointer.restore_latest(trainer.abstract_state())
        if restored is not None:
            state, resumed_from = restored[0], int(restored[1])
    if state is None:
        state = trainer.init_state(rng)

    start_step = int(state.step)
    if start_step >= total_steps:
        log.info(
            "checkpoint already at step %d >= total_steps %d; nothing to do",
            start_step, total_steps,
        )
        return FitResult(
            state=state, history=[], steps_done=0, resumed_from=resumed_from
        )

    step_fn = trainer.make_train_step()
    it = iter(data)
    history: list[dict] = []
    t_last = time.perf_counter()
    examples = 0

    def check_finite(metrics, step: int) -> float:
        loss = float(metrics["loss"])
        if not np.isfinite(loss):
            # Never persisted: the check runs before any save at this step,
            # so resume always lands on the last finite state.
            raise TrainingDiverged(f"non-finite loss {loss} at step {step}")
        return loss

    try:
        for step in range(start_step, total_steps):
            try:
                batch = next(it)
            except StopIteration:
                raise ValueError(
                    f"data iterable exhausted at step {step} "
                    f"(needed {total_steps})"
                ) from None
            if profiler is not None:
                profiler.before_step(step)
            state, metrics = step_fn(state, batch)
            if profiler is not None:
                profiler.after_step(step)
            examples += trainer.config.batch_size
            is_last = step + 1 == total_steps
            if checkpointer is not None and (
                checkpointer.should_save(step + 1) or is_last
            ):
                check_finite(metrics, step + 1)
                checkpointer.save(step + 1, state, force=is_last)
            if (step + 1) % log_every == 0 or is_last:
                loss = check_finite(metrics, step + 1)
                now = time.perf_counter()
                rec = {
                    "step": step + 1,
                    "loss": loss,
                    # Absent in train_metrics="loss" mode (LM trainers
                    # skip the per-step full-vocab argmax).
                    "accuracy": float(metrics.get("accuracy", float("nan"))),
                    "examples_per_sec": examples / (now - t_last),
                }
                history.append(rec)
                if on_metrics is not None:
                    on_metrics(step + 1, rec)
                log.info(
                    "step %d loss %.4f acc %.3f %.1f ex/s",
                    rec["step"], rec["loss"], rec["accuracy"],
                    rec["examples_per_sec"],
                )
                t_last, examples = now, 0
    finally:
        # Even on the exception path: make enqueued saves durable (the
        # last good checkpoint is the recovery point) and close a live
        # trace (a diverging run should still leave a readable profile).
        if profiler is not None:
            profiler.close()
        if checkpointer is not None:
            checkpointer.wait()

    return FitResult(
        state=state,
        history=history,
        steps_done=total_steps - start_step,
        resumed_from=resumed_from,
    )
