"""The training loop: resume, step, guard, checkpoint, report.

Failure semantics the reference lacked (SURVEY.md §5 "no elastic training,
no preemption handling") — the full matrix lives in docs/resilience.md:

- **Auto-resume.** The loop restores the newest VALID checkpoint
  (`train/checkpoint.py` verifies manifests and falls back past
  corruption) and, when the data iterable implements the resumable-data
  protocol, repositions it from the state saved in that checkpoint — so
  a restarted run neither repeats nor skips batches.
- **Anomaly guard.** A trainer built with an `AnomalyGuard`
  (`train/guard.py`) screens EVERY step on device: non-finite or
  spiking steps are skipped, not applied, so a NaN at step 51 can never
  reach the step-100 checkpoint. On sustained divergence (bounded
  consecutive skips) the loop rolls back to the last checkpoint and
  perturbs the data seed — a different trajectory instead of a dead run.
- **Preemption.** SIGTERM/SIGINT is caught and honored at the next step
  boundary: one forced save (with data state), then a clean exit with a
  distinct `Preempted` result — the TpuJob operator's gang-restart
  policy composes with it to give checkpoint-restart elasticity with
  zero lost work.
- **Elastic resize.** A loop built with an `ElasticResize` can ABSORB a
  preemption instead of dying: when the scheduler has offered a
  shrink-to-fit target (`controllers/tpujob.py` resize proposals), the
  loop reshapes the mesh at the step boundary — rebuild the mesh at the
  new dp, re-shard the live `TrainState` across device sets (no
  checkpoint round-trip; `restore_latest` into the new topology is the
  fallback when a host is already gone), transplant the resumable-data
  state — and keeps training with the SAME global batch, so the
  trajectory (and the (step -> batch position) identity mapping) is
  unchanged. Growing back when capacity returns rides the same
  transition. Steps lost per preemption: ~0, vs a save-interval's worth
  under gang restart.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import signal as signal_module
import sys
import time
from typing import Any, Callable, Iterable

import jax
import numpy as np

from kubeflow_tpu.train.checkpoint import Checkpointer
from kubeflow_tpu.train.profiling import Profiler
from kubeflow_tpu.train.trainer import Trainer, TrainState

log = logging.getLogger(__name__)


class TrainingDiverged(RuntimeError):
    """Loss became non-finite (guardless runs) or the anomaly guard hit
    its rollback budget; restart from the last checkpoint with a
    different seed/schedule rather than continuing."""


@dataclasses.dataclass(frozen=True)
class ResizeProposal:
    """One elastic-resize target, honored at the next step boundary.

    `source="live"` re-shards the in-memory TrainState across meshes —
    the happy path, no checkpoint round-trip. `source="checkpoint"` is
    the fallback for when part of the old mesh is ALREADY gone (a host
    died with its shards): restore the newest verified checkpoint into
    the new topology instead — `Restored` states are shape-polymorphic
    on dp because checkpoints hold GLOBAL arrays and restore lays them
    out by the target trainer's NamedShardings."""

    dp: int
    source: str = "live"

    def __post_init__(self) -> None:
        if self.source not in ("live", "checkpoint"):
            raise ValueError(
                f"ResizeProposal.source must be 'live' or 'checkpoint', "
                f"got {self.source!r}"
            )


@dataclasses.dataclass(frozen=True)
class ResizeEvent:
    """One completed mesh resize (FitResult.resizes / on_resize)."""

    step: int           # the boundary the transition ran at
    from_dp: int
    to_dp: int
    source: str         # "live" or "checkpoint"
    seconds: float      # transition wall time
    # The preemption signal this resize absorbed (the gang reshaped
    # instead of dying); None for an unprompted resize (grow-back).
    absorbed_signum: int | None = None
    # source="checkpoint" only: the step actually restored (the steps
    # in between are recomputed — they were never durable anywhere).
    restored_step: int | None = None


@dataclasses.dataclass
class ElasticResize:
    """fit()'s elastic gang-resize driver (docs/resilience.md).

    - ``mesh_factory(dp)`` builds the target mesh — typically
      `parallel.mesh.build_mesh`/`build_hybrid_mesh` over the surviving
      hosts' devices.
    - ``data_factory(mesh, data)`` rebuilds the training iterable on the
      new mesh (the streams' ``rebind(mesh)``); fit() then transplants
      the resumable-data state, so batch content — a pure function of
      (seed, salt, position), never the mesh — continues the identity
      (step -> position) mapping: zero repeated or skipped batches.
    - ``propose(step, preempted)`` is polled at every step boundary.
      ``preempted=True`` means a SIGTERM/SIGINT arrived: returning a
      proposal then ABSORBS the signal (the gang shrinks instead of
      dying — the scheduler's shrink-to-fit ack); returning None lets
      the normal `Preempted` exit happen. With ``preempted=False`` a
      proposal drives an unprompted resize (grow-back when capacity
      returns).
    - ``on_resize(event)`` observes each completed transition (trace
      emission, the controller-facing ack).
    """

    mesh_factory: Callable[[int], Any]
    data_factory: Callable[[Any, Any], Any]
    propose: Callable[[int, bool], ResizeProposal | None]
    on_resize: Callable[[ResizeEvent], None] | None = None


def _mesh_dp(trainer: Trainer) -> int:
    return int(trainer.mesh.shape.get("dp", 1))


@dataclasses.dataclass
class FitResult:
    state: TrainState
    history: list[dict]
    steps_done: int
    resumed_from: int | None
    # Divergence rollbacks taken (guarded runs; 0 otherwise).
    rollbacks: int = 0
    # Elastic mesh resizes performed (ElasticResize runs; [] otherwise).
    resizes: list[ResizeEvent] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class Preempted(FitResult):
    """fit() observed SIGTERM/SIGINT: it stopped at a step boundary
    after an emergency forced save — resume from the checkpoint to
    continue with zero lost work. `isinstance(result, Preempted)`
    distinguishes a preemption from completion."""

    signum: int | None = None


def _data_state(data: Any) -> dict | None:
    sd = getattr(data, "state_dict", None)
    return sd() if callable(sd) else None


def _load_data_state(data: Any, state: dict | None) -> None:
    ld = getattr(data, "load_state_dict", None)
    if state is not None and callable(ld):
        ld(state)


def fit(
    trainer: Trainer,
    data: Iterable[dict],
    total_steps: int,
    *,
    rng: jax.Array | None = None,
    checkpointer: Checkpointer | None = None,
    log_every: int = 50,
    on_metrics: Callable[[int, dict], None] | None = None,
    profiler: "Profiler | None" = None,
    handle_signals: bool = True,
    max_rollbacks: int = 3,
    elastic: ElasticResize | None = None,
) -> FitResult:
    """Train for `total_steps` global steps, resuming if possible.

    `handle_signals=False` opts out of the SIGTERM/SIGINT preemption
    handler (e.g. when the caller owns signal disposition); handlers are
    only ever installed on the main thread and are restored on exit.
    `max_rollbacks` bounds divergence rollbacks before the loop gives up
    and raises `TrainingDiverged`. `elastic` enables elastic gang
    resize: proposals are polled at every step boundary, and a proposal
    arriving with a preemption signal absorbs it — the mesh reshapes
    instead of the process dying (see `ElasticResize`).
    """
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    guard = trainer.guard

    resumed_from = None
    state = None
    if checkpointer is not None:
        restored = checkpointer.restore_latest(trainer.abstract_state())
        if restored is not None:
            state, resumed_from = restored.state, int(restored.step)
            _load_data_state(data, restored.data_state)
    if state is None:
        state = trainer.init_state(rng)

    start_step = int(state.step)
    if start_step >= total_steps:
        log.info(
            "checkpoint already at step %d >= total_steps %d; nothing to do",
            start_step, total_steps,
        )
        return FitResult(
            state=state, history=[], steps_done=0, resumed_from=resumed_from
        )

    step_fn = trainer.make_train_step()
    it = iter(data)
    history: list[dict] = []
    t_last = time.perf_counter()
    examples = 0
    rollbacks = 0
    resizes: list[ResizeEvent] = []
    preempt: dict = {"signum": None}
    installed: dict = {}
    if handle_signals:
        def _restore_handlers() -> None:
            for sig, prev in installed.items():
                # prev is None when the pre-fit handler was installed
                # outside Python (sigaction in a launcher/C extension);
                # signal.signal(sig, None) raises TypeError, so fall
                # back to SIG_DFL — imperfect, but it neither crashes
                # nor leaves our flag-setter swallowing signals.
                signal_module.signal(
                    sig,
                    prev if prev is not None else signal_module.SIG_DFL,
                )

        def _on_signal(signum, frame):
            if preempt["signum"] is not None:
                # Second delivery (e.g. Ctrl-C during a multi-minute
                # XLA compile that never reaches a step boundary):
                # escalate — restore the pre-fit disposition and
                # re-deliver so the default behavior (KeyboardInterrupt
                # / termination) applies instead of a dead flag.
                _restore_handlers()
                os.kill(os.getpid(), signum)
                return
            # Flag only: the loop honors it at the next step boundary
            # (an async save mid-step would tear the state).
            preempt["signum"] = signum

        try:
            for sig in (signal_module.SIGTERM, signal_module.SIGINT):
                installed[sig] = signal_module.signal(sig, _on_signal)
        except ValueError:  # not the main thread: caller owns signals
            installed = {}

    def check_finite(metrics, step: int) -> float:
        loss = float(metrics["loss"])
        if not np.isfinite(loss):
            # Never persisted: the check runs before any save at this
            # step, so resume always lands on the last finite state.
            raise TrainingDiverged(f"non-finite loss {loss} at step {step}")
        return loss

    def rollback(step: int) -> tuple[TrainState, int]:
        """Divergence: restore the last good checkpoint and perturb the
        data seed so the retried trajectory differs."""
        nonlocal it
        restored = (
            checkpointer.restore_latest(trainer.abstract_state())
            if checkpointer is not None
            else None
        )
        if restored is None:
            raise TrainingDiverged(
                f"sustained divergence at step {step} and no checkpoint "
                "to roll back to"
            )
        perturb = getattr(data, "perturb", None)
        if (
            restored.data_state is None
            or not callable(getattr(data, "load_state_dict", None))
            or not callable(perturb)
        ):
            # Without resumable data the replayed steps would silently
            # consume batch positions that don't match their step
            # numbers (a fresh iter() restarts a list, a generator just
            # keeps going); without perturb() the replay is a
            # deterministic re-run that diverges identically — either
            # way, refuse up front rather than burn the rollback budget
            # on wrong or provably futile retries.
            raise TrainingDiverged(
                f"sustained divergence at step {step}: rollback needs "
                "resumable, perturbable data (state_dict/"
                "load_state_dict/perturb — see docs/resilience.md); "
                "restart manually from the last checkpoint with a "
                "different data order instead"
            )
        _load_data_state(data, restored.data_state)
        # Monotonic salt: past the checkpoint's own salt (which a prior
        # incarnation's rollback may already have burned) AND past this
        # process's earlier attempts — every retry gets a genuinely new
        # trajectory, never a replay of one that already diverged.
        salt = int(restored.data_state.get("salt", 0)) + rollbacks
        perturb(salt)
        # Make the perturbed salt durable NOW by rewriting the restored
        # step's manifest data_state (checksums untouched): the next
        # periodic save may be a full interval away, and a crash in that
        # window would otherwise resume onto the already-diverged salt
        # and re-burn the whole divergence segment every incarnation.
        checkpointer.update_data_state(
            int(restored.step), _data_state(data)
        )
        it = iter(data)
        log.warning(
            "anomaly guard: sustained divergence at step %d; rolled back "
            "to checkpoint step %d (rollback %d/%d, data salt -> %d)",
            step, restored.step, rollbacks, max_rollbacks, salt,
        )
        return restored.state, int(restored.step)

    result: FitResult | None = None
    step = start_step
    try:
        while step < total_steps:
            try:
                batch = next(it)
            except StopIteration:
                raise ValueError(
                    f"data iterable exhausted at step {step} "
                    f"(needed {total_steps})"
                ) from None
            if profiler is not None:
                profiler.before_step(step)
            state, metrics = step_fn(state, batch)
            if profiler is not None:
                profiler.after_step(step)
            step += 1
            examples += trainer.config.batch_size
            is_last = step == total_steps
            preempted = preempt["signum"] is not None
            want_save = checkpointer is not None and (
                checkpointer.should_save(step) or is_last
            )
            # A preempted boundary always logs: the exit step must reach
            # history/on_metrics before the loop returns.
            want_log = step % log_every == 0 or is_last or preempted

            # Guard verdicts are device scalars; read them only where
            # the host syncs anyway (boundaries), never per step.
            if guard is not None and (want_save or want_log or preempted):
                if guard.diverged(state.guard):
                    if preempted or rollbacks >= max_rollbacks:
                        # Dying or out of budget: the last good
                        # checkpoint stays the recovery point — never
                        # save (or roll back under) a diverged state.
                        raise TrainingDiverged(
                            f"sustained divergence at step {step} after "
                            f"{rollbacks} rollback(s)"
                        )
                    rollbacks += 1
                    state, step = rollback(step)
                    continue

            saved = False
            if want_save:
                if guard is None:
                    check_finite(metrics, step)
                checkpointer.save(
                    step, state,
                    force=is_last or preempted,
                    data_state=_data_state(data),
                )
                saved = True
            if want_log:
                if guard is None:
                    loss = check_finite(metrics, step)
                else:
                    # A skipped step may legitimately log a non-finite
                    # loss — the update was rejected on device, so the
                    # STATE stayed finite; nothing here can persist it.
                    loss = float(metrics["loss"])
                now = time.perf_counter()
                rec = {
                    "step": step,
                    "loss": loss,
                    # Absent in train_metrics="loss" mode (LM trainers
                    # skip the per-step full-vocab argmax).
                    "accuracy": float(metrics.get("accuracy", float("nan"))),
                    "examples_per_sec": examples / (now - t_last),
                }
                if guard is not None:
                    rec["grad_norm"] = float(metrics["grad_norm"])
                    rec["guard_skipped_total"] = int(
                        metrics["guard_skipped_total"]
                    )
                    rec["rollbacks"] = rollbacks
                history.append(rec)
                if on_metrics is not None:
                    on_metrics(step, rec)
                log.info(
                    "step %d loss %.4f acc %.3f %.1f ex/s",
                    rec["step"], rec["loss"], rec["accuracy"],
                    rec["examples_per_sec"],
                )
                t_last, examples = now, 0
            # -- elastic resize (docs/resilience.md) -------------------
            # Polled at the boundary AFTER save/log so the transition
            # always starts from a fully-accounted step. A proposal
            # arriving with a preemption signal absorbs it: the gang
            # reshapes instead of dying, and the loop keeps training —
            # the whole point of shrink-to-fit over gang restart.
            if elastic is not None and not is_last:
                proposal = elastic.propose(step, preempted)
                if proposal is not None and proposal.dp != _mesh_dp(trainer):
                    t0 = time.perf_counter()
                    from_dp = _mesh_dp(trainer)
                    at_step = step
                    new_mesh = elastic.mesh_factory(proposal.dp)
                    new_trainer = trainer.resize(new_mesh)
                    restored_step = None
                    if proposal.source == "checkpoint":
                        # Part of the old mesh is already gone (a host
                        # died with its shards): the live state is not
                        # recoverable — restore the newest verified
                        # checkpoint INTO the new topology. Checkpoints
                        # hold global arrays, so the restore is shape-
                        # polymorphic on dp by construction.
                        if checkpointer is None:
                            raise RuntimeError(
                                "resize with source='checkpoint' needs "
                                "a checkpointer (the live state went "
                                "down with the dead host)"
                            )
                        restored = checkpointer.restore_latest(
                            new_trainer.abstract_state()
                        )
                        if restored is None:
                            raise RuntimeError(
                                f"resize at step {step}: no valid "
                                "checkpoint to restore into the new "
                                "topology"
                            )
                        state = restored.state
                        restored_step = step = int(restored.step)
                        data_state = restored.data_state
                    else:
                        # Happy path: re-shard the LIVE state across
                        # device sets — no checkpoint round-trip, no
                        # recomputed steps.
                        state = new_trainer.reshard_state(state)
                        data_state = _data_state(data)
                    trainer = new_trainer
                    data = elastic.data_factory(new_mesh, data)
                    # Transplant the resumable-data state: content is a
                    # pure function of (seed, salt, position), never the
                    # mesh, so the (step -> position) identity mapping
                    # holds across the resize — zero repeated or
                    # skipped batches.
                    _load_data_state(data, data_state)
                    it = iter(data)
                    step_fn = trainer.make_train_step()
                    event = ResizeEvent(
                        step=at_step,
                        from_dp=from_dp,
                        to_dp=proposal.dp,
                        source=proposal.source,
                        seconds=time.perf_counter() - t0,
                        absorbed_signum=(
                            preempt["signum"] if preempted else None
                        ),
                        restored_step=restored_step,
                    )
                    resizes.append(event)
                    log.warning(
                        "elastic resize at step %d: dp %d -> %d "
                        "(source=%s, absorbed_signum=%s, %.2fs)",
                        event.step, event.from_dp, event.to_dp,
                        event.source, event.absorbed_signum,
                        event.seconds,
                    )
                    if elastic.on_resize is not None:
                        elastic.on_resize(event)
                    if preempted:
                        # Absorbed: the preemption cost a resize, not
                        # the gang.
                        preempt["signum"] = None
                        preempted = False
            if preempted:
                if checkpointer is not None and not saved:
                    # Emergency save at the boundary: the preemption
                    # costs zero steps.
                    checkpointer.save(
                        step, state, force=True,
                        data_state=_data_state(data),
                    )
                log.warning(
                    "preemption signal %s honored at step %d: %s, "
                    "exiting cleanly",
                    preempt["signum"], step,
                    "emergency save done" if checkpointer is not None
                    else "NO checkpointer — progress not saved",
                )
                result = Preempted(
                    state=state,
                    history=history,
                    steps_done=step - start_step,
                    resumed_from=resumed_from,
                    rollbacks=rollbacks,
                    resizes=resizes,
                    signum=preempt["signum"],
                )
                break
    finally:
        # Even on the exception path: restore signal disposition, make
        # enqueued saves durable (the last good checkpoint is the
        # recovery point) and close a live trace (a diverging run should
        # still leave a readable profile).
        if installed:
            _restore_handlers()
        if profiler is not None:
            profiler.close()
        if checkpointer is not None:
            if sys.exc_info()[0] is None:
                # Clean exit (completion or Preempted): a durability
                # failure here means the "saved" work is NOT safe —
                # surface it instead of returning a result that claims
                # zero lost steps.
                checkpointer.wait()
            else:
                # An exception is already unwinding (TrainingDiverged,
                # a KeyboardInterrupt escalation): that is the story —
                # still try to make enqueued saves durable, but demote
                # a wait() failure to a log line so it cannot replace
                # the in-flight exception and break callers' typed
                # handling.
                try:
                    checkpointer.wait()
                except Exception:
                    log.exception(
                        "checkpoint wait failed while another "
                        "exception was unwinding"
                    )

    if result is not None:
        return result
    return FitResult(
        state=state,
        history=history,
        steps_done=total_steps - start_step,
        resumed_from=resumed_from,
        rollbacks=rollbacks,
        resizes=resizes,
    )
