"""The training loop: resume, step, guard, checkpoint, report.

Failure semantics the reference lacked (SURVEY.md §5 "no elastic training,
no preemption handling") — the full matrix lives in docs/resilience.md:

- **Auto-resume.** The loop restores the newest VALID checkpoint
  (`train/checkpoint.py` verifies manifests and falls back past
  corruption) and, when the data iterable implements the resumable-data
  protocol, repositions it from the state saved in that checkpoint — so
  a restarted run neither repeats nor skips batches.
- **Anomaly guard.** A trainer built with an `AnomalyGuard`
  (`train/guard.py`) screens EVERY step on device: non-finite or
  spiking steps are skipped, not applied, so a NaN at step 51 can never
  reach the step-100 checkpoint. On sustained divergence (bounded
  consecutive skips) the loop rolls back to the last checkpoint and
  perturbs the data seed — a different trajectory instead of a dead run.
- **Preemption.** SIGTERM/SIGINT is caught and honored at the next step
  boundary: one forced save (with data state), then a clean exit with a
  distinct `Preempted` result — the TpuJob operator's gang-restart
  policy composes with it to give checkpoint-restart elasticity with
  zero lost work.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import signal as signal_module
import sys
import time
from typing import Any, Callable, Iterable

import jax
import numpy as np

from kubeflow_tpu.train.checkpoint import Checkpointer
from kubeflow_tpu.train.profiling import Profiler
from kubeflow_tpu.train.trainer import Trainer, TrainState

log = logging.getLogger(__name__)


class TrainingDiverged(RuntimeError):
    """Loss became non-finite (guardless runs) or the anomaly guard hit
    its rollback budget; restart from the last checkpoint with a
    different seed/schedule rather than continuing."""


@dataclasses.dataclass
class FitResult:
    state: TrainState
    history: list[dict]
    steps_done: int
    resumed_from: int | None
    # Divergence rollbacks taken (guarded runs; 0 otherwise).
    rollbacks: int = 0


@dataclasses.dataclass
class Preempted(FitResult):
    """fit() observed SIGTERM/SIGINT: it stopped at a step boundary
    after an emergency forced save — resume from the checkpoint to
    continue with zero lost work. `isinstance(result, Preempted)`
    distinguishes a preemption from completion."""

    signum: int | None = None


def _data_state(data: Any) -> dict | None:
    sd = getattr(data, "state_dict", None)
    return sd() if callable(sd) else None


def _load_data_state(data: Any, state: dict | None) -> None:
    ld = getattr(data, "load_state_dict", None)
    if state is not None and callable(ld):
        ld(state)


def fit(
    trainer: Trainer,
    data: Iterable[dict],
    total_steps: int,
    *,
    rng: jax.Array | None = None,
    checkpointer: Checkpointer | None = None,
    log_every: int = 50,
    on_metrics: Callable[[int, dict], None] | None = None,
    profiler: "Profiler | None" = None,
    handle_signals: bool = True,
    max_rollbacks: int = 3,
) -> FitResult:
    """Train for `total_steps` global steps, resuming if possible.

    `handle_signals=False` opts out of the SIGTERM/SIGINT preemption
    handler (e.g. when the caller owns signal disposition); handlers are
    only ever installed on the main thread and are restored on exit.
    `max_rollbacks` bounds divergence rollbacks before the loop gives up
    and raises `TrainingDiverged`.
    """
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    guard = trainer.guard

    resumed_from = None
    state = None
    if checkpointer is not None:
        restored = checkpointer.restore_latest(trainer.abstract_state())
        if restored is not None:
            state, resumed_from = restored.state, int(restored.step)
            _load_data_state(data, restored.data_state)
    if state is None:
        state = trainer.init_state(rng)

    start_step = int(state.step)
    if start_step >= total_steps:
        log.info(
            "checkpoint already at step %d >= total_steps %d; nothing to do",
            start_step, total_steps,
        )
        return FitResult(
            state=state, history=[], steps_done=0, resumed_from=resumed_from
        )

    step_fn = trainer.make_train_step()
    it = iter(data)
    history: list[dict] = []
    t_last = time.perf_counter()
    examples = 0
    rollbacks = 0
    preempt: dict = {"signum": None}
    installed: dict = {}
    if handle_signals:
        def _restore_handlers() -> None:
            for sig, prev in installed.items():
                # prev is None when the pre-fit handler was installed
                # outside Python (sigaction in a launcher/C extension);
                # signal.signal(sig, None) raises TypeError, so fall
                # back to SIG_DFL — imperfect, but it neither crashes
                # nor leaves our flag-setter swallowing signals.
                signal_module.signal(
                    sig,
                    prev if prev is not None else signal_module.SIG_DFL,
                )

        def _on_signal(signum, frame):
            if preempt["signum"] is not None:
                # Second delivery (e.g. Ctrl-C during a multi-minute
                # XLA compile that never reaches a step boundary):
                # escalate — restore the pre-fit disposition and
                # re-deliver so the default behavior (KeyboardInterrupt
                # / termination) applies instead of a dead flag.
                _restore_handlers()
                os.kill(os.getpid(), signum)
                return
            # Flag only: the loop honors it at the next step boundary
            # (an async save mid-step would tear the state).
            preempt["signum"] = signum

        try:
            for sig in (signal_module.SIGTERM, signal_module.SIGINT):
                installed[sig] = signal_module.signal(sig, _on_signal)
        except ValueError:  # not the main thread: caller owns signals
            installed = {}

    def check_finite(metrics, step: int) -> float:
        loss = float(metrics["loss"])
        if not np.isfinite(loss):
            # Never persisted: the check runs before any save at this
            # step, so resume always lands on the last finite state.
            raise TrainingDiverged(f"non-finite loss {loss} at step {step}")
        return loss

    def rollback(step: int) -> tuple[TrainState, int]:
        """Divergence: restore the last good checkpoint and perturb the
        data seed so the retried trajectory differs."""
        nonlocal it
        restored = (
            checkpointer.restore_latest(trainer.abstract_state())
            if checkpointer is not None
            else None
        )
        if restored is None:
            raise TrainingDiverged(
                f"sustained divergence at step {step} and no checkpoint "
                "to roll back to"
            )
        perturb = getattr(data, "perturb", None)
        if (
            restored.data_state is None
            or not callable(getattr(data, "load_state_dict", None))
            or not callable(perturb)
        ):
            # Without resumable data the replayed steps would silently
            # consume batch positions that don't match their step
            # numbers (a fresh iter() restarts a list, a generator just
            # keeps going); without perturb() the replay is a
            # deterministic re-run that diverges identically — either
            # way, refuse up front rather than burn the rollback budget
            # on wrong or provably futile retries.
            raise TrainingDiverged(
                f"sustained divergence at step {step}: rollback needs "
                "resumable, perturbable data (state_dict/"
                "load_state_dict/perturb — see docs/resilience.md); "
                "restart manually from the last checkpoint with a "
                "different data order instead"
            )
        _load_data_state(data, restored.data_state)
        # Monotonic salt: past the checkpoint's own salt (which a prior
        # incarnation's rollback may already have burned) AND past this
        # process's earlier attempts — every retry gets a genuinely new
        # trajectory, never a replay of one that already diverged.
        salt = int(restored.data_state.get("salt", 0)) + rollbacks
        perturb(salt)
        # Make the perturbed salt durable NOW by rewriting the restored
        # step's manifest data_state (checksums untouched): the next
        # periodic save may be a full interval away, and a crash in that
        # window would otherwise resume onto the already-diverged salt
        # and re-burn the whole divergence segment every incarnation.
        checkpointer.update_data_state(
            int(restored.step), _data_state(data)
        )
        it = iter(data)
        log.warning(
            "anomaly guard: sustained divergence at step %d; rolled back "
            "to checkpoint step %d (rollback %d/%d, data salt -> %d)",
            step, restored.step, rollbacks, max_rollbacks, salt,
        )
        return restored.state, int(restored.step)

    result: FitResult | None = None
    step = start_step
    try:
        while step < total_steps:
            try:
                batch = next(it)
            except StopIteration:
                raise ValueError(
                    f"data iterable exhausted at step {step} "
                    f"(needed {total_steps})"
                ) from None
            if profiler is not None:
                profiler.before_step(step)
            state, metrics = step_fn(state, batch)
            if profiler is not None:
                profiler.after_step(step)
            step += 1
            examples += trainer.config.batch_size
            is_last = step == total_steps
            preempted = preempt["signum"] is not None
            want_save = checkpointer is not None and (
                checkpointer.should_save(step) or is_last
            )
            # A preempted boundary always logs: the exit step must reach
            # history/on_metrics before the loop returns.
            want_log = step % log_every == 0 or is_last or preempted

            # Guard verdicts are device scalars; read them only where
            # the host syncs anyway (boundaries), never per step.
            if guard is not None and (want_save or want_log or preempted):
                if guard.diverged(state.guard):
                    if preempted or rollbacks >= max_rollbacks:
                        # Dying or out of budget: the last good
                        # checkpoint stays the recovery point — never
                        # save (or roll back under) a diverged state.
                        raise TrainingDiverged(
                            f"sustained divergence at step {step} after "
                            f"{rollbacks} rollback(s)"
                        )
                    rollbacks += 1
                    state, step = rollback(step)
                    continue

            saved = False
            if want_save:
                if guard is None:
                    check_finite(metrics, step)
                checkpointer.save(
                    step, state,
                    force=is_last or preempted,
                    data_state=_data_state(data),
                )
                saved = True
            if want_log:
                if guard is None:
                    loss = check_finite(metrics, step)
                else:
                    # A skipped step may legitimately log a non-finite
                    # loss — the update was rejected on device, so the
                    # STATE stayed finite; nothing here can persist it.
                    loss = float(metrics["loss"])
                now = time.perf_counter()
                rec = {
                    "step": step,
                    "loss": loss,
                    # Absent in train_metrics="loss" mode (LM trainers
                    # skip the per-step full-vocab argmax).
                    "accuracy": float(metrics.get("accuracy", float("nan"))),
                    "examples_per_sec": examples / (now - t_last),
                }
                if guard is not None:
                    rec["grad_norm"] = float(metrics["grad_norm"])
                    rec["guard_skipped_total"] = int(
                        metrics["guard_skipped_total"]
                    )
                    rec["rollbacks"] = rollbacks
                history.append(rec)
                if on_metrics is not None:
                    on_metrics(step, rec)
                log.info(
                    "step %d loss %.4f acc %.3f %.1f ex/s",
                    rec["step"], rec["loss"], rec["accuracy"],
                    rec["examples_per_sec"],
                )
                t_last, examples = now, 0
            if preempted:
                if checkpointer is not None and not saved:
                    # Emergency save at the boundary: the preemption
                    # costs zero steps.
                    checkpointer.save(
                        step, state, force=True,
                        data_state=_data_state(data),
                    )
                log.warning(
                    "preemption signal %s honored at step %d: %s, "
                    "exiting cleanly",
                    preempt["signum"], step,
                    "emergency save done" if checkpointer is not None
                    else "NO checkpointer — progress not saved",
                )
                result = Preempted(
                    state=state,
                    history=history,
                    steps_done=step - start_step,
                    resumed_from=resumed_from,
                    rollbacks=rollbacks,
                    signum=preempt["signum"],
                )
                break
    finally:
        # Even on the exception path: restore signal disposition, make
        # enqueued saves durable (the last good checkpoint is the
        # recovery point) and close a live trace (a diverging run should
        # still leave a readable profile).
        if installed:
            _restore_handlers()
        if profiler is not None:
            profiler.close()
        if checkpointer is not None:
            if sys.exc_info()[0] is None:
                # Clean exit (completion or Preempted): a durability
                # failure here means the "saved" work is NOT safe —
                # surface it instead of returning a result that claims
                # zero lost steps.
                checkpointer.wait()
            else:
                # An exception is already unwinding (TrainingDiverged,
                # a KeyboardInterrupt escalation): that is the story —
                # still try to make enqueued saves durable, but demote
                # a wait() failure to a log line so it cannot replace
                # the in-flight exception and break callers' typed
                # handling.
                try:
                    checkpointer.wait()
                except Exception:
                    log.exception(
                        "checkpoint wait failed while another "
                        "exception was unwinding"
                    )

    if result is not None:
        return result
    return FitResult(
        state=state,
        history=history,
        steps_done=total_steps - start_step,
        resumed_from=resumed_from,
        rollbacks=rollbacks,
    )
