"""Profiling: first-class jax.profiler trace capture for training jobs.

The reference had no runtime instrumentation — profiling was a *served
workload* (a Tensorboard CR pointed at a logdir, SURVEY.md §5 tracing
row). The TPU-native version completes that loop: the training loop
captures a windowed `jax.profiler` trace (XLA ops, TPU step time, HBM
usage) into the job's logdir in the exact layout TensorBoard's profile
plugin reads (`<logdir>/plugins/profile/<run>/`), and a `Tensorboard` CR
with `logspath` at that directory serves it. Capture is windowed because
tracing is expensive: profile steps [start, start+steps), not the whole
run.

Also here: `annotate` / `annotated_scope` — TraceAnnotation wrappers so
named regions show up on the trace timeline — and the per-phase
roofline layer (`time_phase`, `PhaseRoofline`): the mechanical version
of the hand-built phase table in docs/architecture.md Round 5. A bench
times each phase of a step (attention fwd/bwd, MLP, optimizer) with the
fence discipline tunneled TPUs require, attaches the phase's modeled
TFLOP and HBM bytes, and the roofline classifies which hardware
resource each phase saturates against the chip's peaks — so "where the
ceiling is" is a printed artifact, not a one-off spreadsheet.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import logging
import pathlib
import time
from typing import Any

import jax

log = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class ProfileSchedule:
    """Trace `num_steps` steps, beginning `start_step` steps after this
    process's first step. Relative (not absolute) on purpose: a resumed
    run's first steps pay XLA recompilation, and the warmup skip must
    apply there too."""

    start_step: int = 10  # skip compile + warmup by default
    num_steps: int = 3

    def validate(self) -> None:
        if self.start_step < 0 or self.num_steps < 1:
            raise ValueError("start_step >= 0 and num_steps >= 1 required")


class Profiler:
    """Windowed trace capture driven by the training loop.

    Call `before_step(step)` / `after_step(step)` around each step; the
    profiler starts the trace at `schedule.start_step` and stops it after
    `schedule.num_steps` steps. Stop is crash-safe: `close()` (call in a
    finally) terminates a live trace so a diverging run still leaves a
    readable profile on disk.
    """

    def __init__(
        self,
        logdir: str | pathlib.Path,
        schedule: ProfileSchedule | None = None,
    ):
        self.logdir = pathlib.Path(logdir)
        self.schedule = schedule or ProfileSchedule()
        self.schedule.validate()
        self._active = False
        self._done = False
        self._first_step: int | None = None

    def before_step(self, step: int) -> None:
        if self._first_step is None:
            self._first_step = step
        if (
            not self._done
            and not self._active
            and step >= self._first_step + self.schedule.start_step
        ):
            self.logdir.mkdir(parents=True, exist_ok=True)
            jax.profiler.start_trace(str(self.logdir))
            self._active = True
            self._started_at = step
            log.info("profiler: trace started at step %d", step)

    def after_step(self, step: int) -> None:
        if (
            self._active
            and step + 1 >= self._started_at + self.schedule.num_steps
        ):
            self._stop()

    def _stop(self) -> None:
        jax.profiler.stop_trace()
        self._active = False
        self._done = True
        log.info("profiler: trace written under %s", self.logdir)

    def close(self) -> None:
        if self._active:
            self._stop()

    @property
    def trace_written(self) -> bool:
        return self._done


# -- per-phase roofline ------------------------------------------------------

# v5e chip peaks (docs/architecture.md roofline sections use the same
# constants): bf16 matmul throughput and HBM bandwidth.
V5E_PEAK_TFLOPS = 197.0
V5E_PEAK_GBPS = 819.0


def time_phase(fn, *args, warmup: int = 2, steps: int = 5) -> float:
    """Milliseconds per call of `fn(*args)`, fence-disciplined.

    Same contract as bench.py's `timed_run`: on tunneled/remote
    platforms `block_until_ready` can return before the device has
    executed, so the warmup ends — and the timed window closes — with a
    scalar device_get of the first output leaf (the only reliable
    fence)."""
    import jax

    out = None
    for _ in range(max(1, warmup)):
        out = fn(*args)
    float(jax.tree_util.tree_leaves(out)[0].sum())
    t0 = time.perf_counter()
    for _ in range(max(1, steps)):
        out = fn(*args)
    float(jax.tree_util.tree_leaves(out)[0].sum())
    return (time.perf_counter() - t0) / max(1, steps) * 1000.0


@dataclasses.dataclass(frozen=True)
class PhaseStat:
    """One measured phase with its modeled work: wall-clock plus the
    analytic TFLOP / GB-moved the phase's schedule says it must do
    (model FLOPs and modeled HBM bytes — recompute is NOT counted,
    matching the MFU convention)."""

    name: str
    ms: float
    tflop: float
    gb: float

    def achieved_tflops(self) -> float:
        return self.tflop / (self.ms / 1000.0) if self.ms > 0 else 0.0

    def achieved_gbps(self) -> float:
        return self.gb / (self.ms / 1000.0) if self.ms > 0 else 0.0


class PhaseRoofline:
    """Mechanical per-phase roofline: add phases, read the table.

    `bound_by` mirrors the classification convention of the hand-built
    Round-5 table (docs/architecture.md): the phase is "HBM" when
    bandwidth utilization dominates compute by >= 0.3 of peak,
    "MXU-side" when compute dominates by >= 0.15, and "mixed → <dominant>"
    in between — the mixed labels name the resource any further win
    must come from."""

    def __init__(
        self,
        peak_tflops: float = V5E_PEAK_TFLOPS,
        peak_gbps: float = V5E_PEAK_GBPS,
    ):
        self.peak_tflops = peak_tflops
        self.peak_gbps = peak_gbps
        self.phases: list[PhaseStat] = []

    def add(self, name: str, *, ms: float, tflop: float, gb: float) -> dict:
        self.phases.append(PhaseStat(name, ms, tflop, gb))
        return self.rows()[-1]

    def _bound(self, compute_frac: float, bw_frac: float) -> str:
        if bw_frac - compute_frac >= 0.3:
            return "HBM"
        if compute_frac - bw_frac >= 0.15:
            return "MXU-side"
        return "mixed → HBM" if bw_frac >= compute_frac else "mixed → MXU"

    def rows(self) -> list[dict]:
        out = []
        for p in self.phases:
            tf = p.achieved_tflops()
            gbps = p.achieved_gbps()
            cf = tf / self.peak_tflops if self.peak_tflops else 0.0
            bf = gbps / self.peak_gbps if self.peak_gbps else 0.0
            out.append(
                {
                    "phase": p.name,
                    "ms": round(p.ms, 2),
                    "tflop": round(p.tflop, 2),
                    "gb": round(p.gb, 2),
                    "achieved_tflops": round(tf, 1),
                    "achieved_gbps": round(gbps, 1),
                    "compute_frac": round(cf, 3),
                    "bw_frac": round(bf, 3),
                    "bound_by": self._bound(cf, bf),
                }
            )
        return out

    def saturated(self) -> str:
        """The step's binding resource: the bound of the phase that
        spends the most wall-clock (what "attack the dominant phase"
        should attack)."""
        if not self.phases:
            return "none"
        rows = self.rows()
        top = max(rows, key=lambda r: r["ms"])
        return f"{top['phase']}: {top['bound_by']}"

    def table(self) -> str:
        """Markdown table, same columns as the Round-5 hand-built one."""
        lines = [
            "| phase | ms | TFLOP | GB moved | achieved | bound by |",
            "|---|---|---|---|---|---|",
        ]
        for r in self.rows():
            lines.append(
                f"| {r['phase']} | {r['ms']:g} | {r['tflop']:g} | "
                f"{r['gb']:g} | {r['achieved_tflops']:g} TF/s "
                f"({r['compute_frac'] * 100:.0f}%), "
                f"{r['achieved_gbps']:g} GB/s "
                f"({r['bw_frac'] * 100:.0f}%) | {r['bound_by']} |"
            )
        return "\n".join(lines)


def annotate(name: str):
    """Decorator: mark a function as a named region on the trace."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            with jax.profiler.TraceAnnotation(name):
                return fn(*args, **kwargs)

        return wrapped

    return deco


def annotated_scope(name: str):
    """Context manager: named region on the trace timeline."""
    return jax.profiler.TraceAnnotation(name)


class MetricsLogger:
    """JSONL metrics sink living next to the profile traces, so one
    `Tensorboard` CR's logspath covers both step metrics and the profile
    plugin (the dashboard's activities view reads the same file)."""

    def __init__(self, logdir: str | pathlib.Path, filename: str = "metrics.jsonl"):
        self.path = pathlib.Path(logdir) / filename
        self.path.parent.mkdir(parents=True, exist_ok=True)

    def __call__(self, step: int, record: dict[str, Any]) -> None:
        with self.path.open("a") as f:
            f.write(
                json.dumps({"ts": time.time(), "step": step, **record}) + "\n"
            )

    def read(self) -> list[dict]:
        if not self.path.exists():
            return []
        return [
            json.loads(line)
            for line in self.path.read_text().splitlines()
            if line.strip()
        ]
