"""Profiling: first-class jax.profiler trace capture for training jobs.

The reference had no runtime instrumentation — profiling was a *served
workload* (a Tensorboard CR pointed at a logdir, SURVEY.md §5 tracing
row). The TPU-native version completes that loop: the training loop
captures a windowed `jax.profiler` trace (XLA ops, TPU step time, HBM
usage) into the job's logdir in the exact layout TensorBoard's profile
plugin reads (`<logdir>/plugins/profile/<run>/`), and a `Tensorboard` CR
with `logspath` at that directory serves it. Capture is windowed because
tracing is expensive: profile steps [start, start+steps), not the whole
run.

Also here: `annotate` / `annotated_scope` — TraceAnnotation wrappers so
named regions show up on the trace timeline.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import logging
import pathlib
import time
from typing import Any

import jax

log = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class ProfileSchedule:
    """Trace `num_steps` steps, beginning `start_step` steps after this
    process's first step. Relative (not absolute) on purpose: a resumed
    run's first steps pay XLA recompilation, and the warmup skip must
    apply there too."""

    start_step: int = 10  # skip compile + warmup by default
    num_steps: int = 3

    def validate(self) -> None:
        if self.start_step < 0 or self.num_steps < 1:
            raise ValueError("start_step >= 0 and num_steps >= 1 required")


class Profiler:
    """Windowed trace capture driven by the training loop.

    Call `before_step(step)` / `after_step(step)` around each step; the
    profiler starts the trace at `schedule.start_step` and stops it after
    `schedule.num_steps` steps. Stop is crash-safe: `close()` (call in a
    finally) terminates a live trace so a diverging run still leaves a
    readable profile on disk.
    """

    def __init__(
        self,
        logdir: str | pathlib.Path,
        schedule: ProfileSchedule | None = None,
    ):
        self.logdir = pathlib.Path(logdir)
        self.schedule = schedule or ProfileSchedule()
        self.schedule.validate()
        self._active = False
        self._done = False
        self._first_step: int | None = None

    def before_step(self, step: int) -> None:
        if self._first_step is None:
            self._first_step = step
        if (
            not self._done
            and not self._active
            and step >= self._first_step + self.schedule.start_step
        ):
            self.logdir.mkdir(parents=True, exist_ok=True)
            jax.profiler.start_trace(str(self.logdir))
            self._active = True
            self._started_at = step
            log.info("profiler: trace started at step %d", step)

    def after_step(self, step: int) -> None:
        if (
            self._active
            and step + 1 >= self._started_at + self.schedule.num_steps
        ):
            self._stop()

    def _stop(self) -> None:
        jax.profiler.stop_trace()
        self._active = False
        self._done = True
        log.info("profiler: trace written under %s", self.logdir)

    def close(self) -> None:
        if self._active:
            self._stop()

    @property
    def trace_written(self) -> bool:
        return self._done


def annotate(name: str):
    """Decorator: mark a function as a named region on the trace."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            with jax.profiler.TraceAnnotation(name):
                return fn(*args, **kwargs)

        return wrapped

    return deco


def annotated_scope(name: str):
    """Context manager: named region on the trace timeline."""
    return jax.profiler.TraceAnnotation(name)


class MetricsLogger:
    """JSONL metrics sink living next to the profile traces, so one
    `Tensorboard` CR's logspath covers both step metrics and the profile
    plugin (the dashboard's activities view reads the same file)."""

    def __init__(self, logdir: str | pathlib.Path, filename: str = "metrics.jsonl"):
        self.path = pathlib.Path(logdir) / filename
        self.path.parent.mkdir(parents=True, exist_ok=True)

    def __call__(self, step: int, record: dict[str, Any]) -> None:
        with self.path.open("a") as f:
            f.write(
                json.dumps({"ts": time.time(), "step": step, **record}) + "\n"
            )

    def read(self) -> list[dict]:
        if not self.path.exists():
            return []
        return [
            json.loads(line)
            for line in self.path.read_text().splitlines()
            if line.strip()
        ]
