"""Typed record datasets over the native loader.

A `RecordSpec` names fixed-shape fields (static shapes are an XLA
requirement, and fixed-size records are what makes the native loader's
random access O(1)); `RecordDataset` decodes the loader's raw batches
into per-field numpy arrays and, with a mesh, delivers device-resident
sharded batches for the training loop.

Sharding composes with the TpuJob gang contract: pass
``process_env=ProcessEnv.from_env()`` inside a gang and each process
reads only its shard (the reference reached the same split through
TF_CONFIG task indices, `tf-controller-examples/tf-cnn/launcher.py:68-88`).
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Mapping

import numpy as np

from kubeflow_tpu.native.dataloader import RecordLoader, RecordWriter
from kubeflow_tpu.parallel.distributed import ProcessEnv


@dataclasses.dataclass(frozen=True)
class Field:
    name: str
    dtype: str
    shape: tuple[int, ...]

    @property
    def nbytes(self) -> int:
        return int(np.dtype(self.dtype).itemsize * np.prod(self.shape, initial=1))


@dataclasses.dataclass(frozen=True)
class RecordSpec:
    fields: tuple[Field, ...]

    @classmethod
    def of(cls, **fields: tuple[str, tuple[int, ...]]) -> "RecordSpec":
        """RecordSpec.of(image=("uint8", (224, 224, 3)), label=("int32", ()))"""
        return cls(
            tuple(Field(n, dt, tuple(sh)) for n, (dt, sh) in fields.items())
        )

    @property
    def record_bytes(self) -> int:
        return sum(f.nbytes for f in self.fields)

    def encode(self, example: Mapping[str, np.ndarray]) -> bytes:
        parts = []
        for f in self.fields:
            arr = np.asarray(example[f.name], dtype=f.dtype).reshape(f.shape)
            parts.append(arr.tobytes())
        return b"".join(parts)

    def decode_batch(self, raw: np.ndarray) -> dict[str, np.ndarray]:
        """[batch, record_bytes] uint8 -> dict of [batch, *shape] arrays.
        Zero-copy views into the batch buffer."""
        out: dict[str, np.ndarray] = {}
        offset = 0
        n = raw.shape[0]
        for f in self.fields:
            view = raw[:, offset:offset + f.nbytes]
            out[f.name] = np.ascontiguousarray(view).view(f.dtype).reshape(
                (n, *f.shape)
            )
            offset += f.nbytes
        return out


def write_records(
    path: str, spec: RecordSpec, examples: Iterator[Mapping[str, np.ndarray]]
) -> int:
    """Write examples to a record file; returns the count."""
    with RecordWriter(path, spec.record_bytes) as w:
        for ex in examples:
            w.append(spec.encode(ex))
        return w.count


class RecordDataset:
    """Decoded, optionally device-resident batches from record files.

    Implements the resumable-data protocol (docs/resilience.md): the
    native loader counts batches delivered, `state_dict()` snapshots
    that position, and `load_state_dict` reopens the loader (same
    paths, seed and shuffle args — the native shuffle is a pure
    function of them) and fast-forwards to the snapshot, so a resumed
    run continues the exact batch sequence. The fast-forward drains and
    discards `position` batches — O(position) IO, paid once per resume,
    which is the honest cost of random access into a shuffled stream.
    """

    def __init__(
        self,
        paths: list[str] | str,
        spec: RecordSpec,
        batch_size: int,
        *,
        process_env: ProcessEnv | None = None,
        shuffle_buffer: int = 0,
        seed: int = 0,
        num_threads: int = 4,
        prefetch: int = 2,
        drop_remainder: bool = True,
        epochs: int = 0,
    ):
        env = process_env or ProcessEnv()
        if batch_size % env.num_processes != 0:
            raise ValueError(
                f"global batch {batch_size} must divide evenly over "
                f"{env.num_processes} processes"
            )
        self.spec = spec
        self.global_batch_size = batch_size
        self.local_batch_size = batch_size // env.num_processes
        self._loader_kwargs = dict(
            shard_id=env.process_id,
            shards=env.num_processes,
            shuffle_buffer=shuffle_buffer,
            seed=seed,
            num_threads=num_threads,
            prefetch=prefetch,
            drop_remainder=drop_remainder,
            epochs=epochs,
        )
        self._paths = paths
        self._loader = self._open()
        if self._loader.record_bytes != spec.record_bytes:
            raise ValueError(
                f"file records are {self._loader.record_bytes} bytes but the "
                f"spec decodes {spec.record_bytes}"
            )

    def _open(self) -> RecordLoader:
        return RecordLoader(
            self._paths, self.local_batch_size, **self._loader_kwargs
        )

    @property
    def shard_records(self) -> int:
        return self._loader.shard_records

    # -- resumable-data protocol -------------------------------------------

    def state_dict(self) -> dict:
        return {"batches_delivered": self._loader.batches_delivered}

    def load_state_dict(self, state: dict) -> None:
        """Reposition to `state`: reopen the (deterministically seeded)
        loader and fast-forward past the already-consumed batches."""
        target = int(state["batches_delivered"])
        if self._loader.batches_delivered > target:
            self._loader = self._open()
        while self._loader.batches_delivered < target:
            if self._loader.next() is None:
                raise ValueError(
                    f"cannot fast-forward to batch {target}: the stream "
                    f"ended at {self._loader.batches_delivered} (fewer "
                    "epochs than the checkpoint was trained with?)"
                )

    def __iter__(self) -> Iterator[dict[str, np.ndarray]]:
        for raw, n in self._loader:
            batch = self.spec.decode_batch(raw[:n])
            yield batch

    def device_iter(self, mesh) -> "_DeviceIter":
        """Batches placed on the mesh, sharded over the batch axes (the
        data-parallel layout the trainer expects). The returned iterator
        forwards the resumable-data protocol to this dataset, so it can
        be handed straight to `fit(..., checkpointer=...)`."""
        return _DeviceIter(self, mesh)


class _DeviceIter:
    """Device-placing view over a RecordDataset that keeps the dataset's
    resumable state reachable from the object the training loop holds.
    Iterable AND an iterator: `iter()` starts a fresh device-placing
    pass (the underlying loader's position carries over, as before),
    while `next()` on the view itself keeps working for direct callers
    of the old generator-returning API."""

    def __init__(self, dataset: RecordDataset, mesh):
        self._dataset = dataset
        self._mesh = mesh
        self._gen: Iterator[dict] | None = None

    def state_dict(self) -> dict:
        return self._dataset.state_dict()

    def load_state_dict(self, state: dict) -> None:
        self._dataset.load_state_dict(state)
        # Repositioning may have reopened the loader; a cached direct-
        # next() generator would keep draining the stale one.
        self._gen = None

    def rebind(self, mesh) -> "_DeviceIter":
        """The same dataset on a different mesh (elastic resize): the
        resumable position lives on the DATASET, which the rebound view
        shares, so iteration continues at the identical batch — only
        the device placement of the yielded batches changes."""
        return _DeviceIter(self._dataset, mesh)

    def __next__(self) -> dict:
        if self._gen is None:
            self._gen = iter(self)
        return next(self._gen)

    def __iter__(self) -> Iterator[dict]:
        import jax

        from kubeflow_tpu.parallel.sharding import batch_sharding

        sharding = batch_sharding(self._mesh, ndim=1)
        for batch in self._dataset:
            yield {
                k: jax.device_put(v, sharding) for k, v in batch.items()
            }
