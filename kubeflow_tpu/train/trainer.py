"""Sharded train-step factory.

The scaling recipe end-to-end: the model carries logical axis names, the
mesh carries physical axes, `flax.linen.logical_to_mesh_sharding` joins them
through the rules table, and one `jax.jit` with explicit in/out shardings
compiles the whole step — XLA inserts every collective (gradient psum over
dp, all-gather/reduce-scatter for fsdp, tp all-reduces) that the reference
obtained from parameter servers and Horovod rings (SURVEY.md §2.2).

No pmap, no per-device Python: a single traced program over the global mesh,
which is what lets the same trainer run 1 chip or a multi-slice pod.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Mapping

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from flax import core, struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_tpu.parallel import sharding as shlib


def _ensure_partitionable_rng() -> None:
    """Sharding-invariant initialization: the pinned jax defaults to the
    non-partitionable threefry, whose draws depend on the physical
    layout — the SAME PRNGKey then yields different params on a
    tp-sharded mesh than on one device (the exact semantics drift
    test_lm_tp_matches_single_device pins: "partitioning must not change
    semantics"). The partitionable form derives every element's bits
    from its logical index, so init_state is identical on any mesh.

    Called from Trainer construction — not at import — so merely
    importing this module never mutates process-global PRNG semantics;
    only actually binding a sharded trainer opts the process in.
    """
    if not jax.config.jax_threefry_partitionable:
        jax.config.update("jax_threefry_partitionable", True)


class TrainState(struct.PyTreeNode):
    """Step counter + params + optimizer + BN state, one donate-able pytree.

    `guard` is the anomaly guard's scalar pytree (`train/guard.py`) when
    the trainer was built with one, else an empty dict (no leaves). It
    lives inside TrainState so checkpoints carry it: a resumed or
    rolled-back run restores its skip counters with its params."""

    step: jax.Array
    params: core.FrozenDict | dict
    opt_state: optax.OptState
    batch_stats: core.FrozenDict | dict = struct.field(default_factory=dict)
    guard: dict = struct.field(default_factory=dict)
    apply_fn: Callable = struct.field(pytree_node=False, default=None)
    tx: optax.GradientTransformation = struct.field(pytree_node=False, default=None)

    def apply_gradients(self, *, grads, **updates) -> "TrainState":
        upd, new_opt = self.tx.update(grads, self.opt_state, self.params)
        return self.replace(
            step=self.step + 1,
            params=optax.apply_updates(self.params, upd),
            opt_state=new_opt,
            **updates,
        )


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    batch_size: int = 256
    learning_rate: float = 0.4
    warmup_steps: int = 200
    total_steps: int = 10_000
    momentum: float = 0.9
    weight_decay: float = 1e-4
    label_smoothing: float = 0.1
    # "sgd" (benchmark parity with tf_cnn_benchmarks' default) or "adamw"
    optimizer: str = "sgd"
    fsdp_params: bool = True
    # Per-step training metrics: "full" also computes accuracy (an
    # argmax over the logits — at LM vocab sizes that is a multi-GB
    # logits readback per step, which production LM trainers skip);
    # "loss" returns the objective only. Eval always computes both.
    train_metrics: str = "full"
    # adamw first-moment dtype. The optimizer step is pure HBM
    # bandwidth (measured 677 GB/s = 83% of v5e peak on the 350M LM
    # bench); storing mu in bf16 halves its read+write traffic for a
    # measured +1.1% step throughput with no observable loss impact —
    # the MaxText default. The second moment stays f32 (it accumulates
    # squares; bf16 there costs real precision). "float32" opts out.
    adam_mu_dtype: str = "bfloat16"
    # Whole-step rematerialization: wrap the loss forward in
    # jax.checkpoint with the named policy ("full", "dots", "attn",
    # "flash" — resolved by models.transformer.checkpoint_policy). This
    # is the trainer-level knob for models WITHOUT their own per-block
    # remat (or with remat_policy="none"): e.g. step_remat="flash" pins
    # only each attention's output + lse across the whole step, so the
    # backward recomputes the cheap dense layers but never re-runs a
    # flash forward kernel. None (default) = no step-level checkpoint;
    # per-block policies in the model compose underneath either way.
    step_remat: str | None = None
    # Per-microbatch gradient accumulation: split each batch into
    # `accum_steps` microbatches and run them through a `lax.scan` whose
    # per-tick forward is wrapped in `jax.checkpoint`, differentiating
    # through the scan — the backward walks the microbatches in reverse,
    # recomputing each tick's forward, so activation memory is bounded
    # by ONE microbatch in flight instead of the whole batch. Composes
    # with `step_remat` and the model's per-block `remat_policy` (those
    # govern what the per-tick recompute itself saves — e.g. "flash"
    # still pins attention outputs + lse within a tick). Works on any
    # mesh, pp or not; grads and loss equal the full-batch step's (mean
    # of equal-sized microbatch means). 1 = off.
    accum_steps: int = 1
    # The model computes its own objective: the train/eval steps call
    # `apply(variables, batch[input], train=..., labels=batch[label])`
    # and take the returned SCALAR as the loss instead of computing
    # cross-entropy on returned logits. This is how the pipelined
    # transformer's last-stage loss path is driven (the logits never
    # leave the last pp stage — only the loss scalar crosses the pp
    # axis). Requires train_metrics="loss" (no logits → no accuracy)
    # and label_smoothing=0.0 (the model's objective, not the
    # trainer's, defines any smoothing).
    loss_in_model: bool = False

    def __post_init__(self) -> None:
        # A typo ("Full", "all") would silently behave as "loss" and drop
        # per-step accuracy; fail loudly instead.
        if self.train_metrics not in ("full", "loss"):
            raise ValueError(
                f"train_metrics must be 'full' or 'loss', got "
                f"{self.train_metrics!r}"
            )
        if self.optimizer not in ("sgd", "adamw"):
            raise ValueError(f"unknown optimizer {self.optimizer!r}")
        if self.step_remat is not None and self.step_remat not in (
            "full", "dots", "attn", "flash"
        ):
            raise ValueError(
                f"step_remat must be None, 'full', 'dots', 'attn', or "
                f"'flash', got {self.step_remat!r}"
            )
        if self.adam_mu_dtype not in ("bfloat16", "float32"):
            raise ValueError(
                f"adam_mu_dtype must be 'bfloat16' or 'float32', got "
                f"{self.adam_mu_dtype!r}"
            )
        if self.accum_steps < 1:
            raise ValueError(
                f"accum_steps must be >= 1, got {self.accum_steps}"
            )
        if self.batch_size % self.accum_steps:
            raise ValueError(
                f"batch_size ({self.batch_size}) must divide into "
                f"{self.accum_steps} accumulation microbatches"
            )
        if self.loss_in_model:
            if self.train_metrics != "loss":
                raise ValueError(
                    "loss_in_model=True returns no logits; accuracy is "
                    "unavailable — set train_metrics='loss'"
                )
            if self.label_smoothing:
                raise ValueError(
                    "loss_in_model=True delegates the objective to the "
                    "model; TrainConfig.label_smoothing would be "
                    "silently ignored — set it to 0.0"
                )


def decay_mask(params) -> Any:
    """Weight decay applies to matrices/filters only — never to the 1-D
    params (BN/LN scales and biases)."""
    return jax.tree_util.tree_map(lambda p: p.ndim > 1, params)


def make_optimizer(config: TrainConfig) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=config.learning_rate,
        warmup_steps=config.warmup_steps,
        decay_steps=max(config.total_steps, config.warmup_steps + 1),
    )
    if config.optimizer == "sgd":
        return optax.chain(
            optax.add_decayed_weights(config.weight_decay, mask=decay_mask),
            optax.sgd(schedule, momentum=config.momentum, nesterov=True),
        )
    if config.optimizer == "adamw":
        return optax.adamw(
            schedule,
            weight_decay=config.weight_decay,
            mu_dtype=jnp.bfloat16
            if config.adam_mu_dtype == "bfloat16"
            else jnp.float32,
        )
    raise ValueError(f"unknown optimizer {config.optimizer!r}")


def softmax_cross_entropy(logits, labels, label_smoothing: float = 0.0):
    """Fused gather-based cross entropy (equals
    `optax.softmax_cross_entropy(logits, smoothed_onehot).mean()`).

    The one-hot formulation materializes a [B, S, vocab] dense target and
    streams it from HBM alongside the logits; at LM vocab sizes that is
    gigabytes per step of pure bandwidth waste on an HBM-bound chip. The
    identity `CE = logsumexp(logits) - logits[label]` (smoothing mixes in
    `logsumexp - mean(logits)`, the uniform-target term) needs only a
    rank-reducing reduce and a gather, both of which XLA fuses into the
    logits producer."""
    logits = logits.astype(jnp.float32)
    log_z = jax.scipy.special.logsumexp(logits, axis=-1)
    label_logits = jnp.take_along_axis(
        logits, labels[..., None], axis=-1
    )[..., 0]
    nll = log_z - label_logits
    if label_smoothing:
        uniform = log_z - logits.mean(axis=-1)
        nll = (1.0 - label_smoothing) * nll + label_smoothing * uniform
    return nll.mean()


class Trainer:
    """Binds (model, config, mesh) into sharded init/train-step callables."""

    def __init__(
        self,
        model: nn.Module,
        config: TrainConfig,
        mesh: Mesh,
        rules: Mapping[str, Any] | None = None,
        example_input_shape: tuple = (2, 224, 224, 3),
        input_key: str = "image",
        label_key: str = "label",
        example_input_dtype: Any = jnp.float32,
        guard: "Any | None" = None,
    ):
        _ensure_partitionable_rng()
        self.model = model
        self.config = config
        self.mesh = mesh
        # Optional AnomalyGuard (train/guard.py): when set, every train
        # step screens loss/grad-norm on device and skips anomalous
        # updates instead of applying them (see make_train_step).
        self.guard = guard
        self.rules = dict(
            rules
            if rules is not None
            else shlib.default_rules(fsdp_params=config.fsdp_params)
        )
        self.tx = make_optimizer(config)
        # The init dummy batch must divide evenly over the mesh batch axes
        # (model code may shard_map over them, e.g. ring attention).
        dp_total = shlib.batch_shard_count(mesh)
        lead = example_input_shape[0]
        if lead % dp_total:
            lead = dp_total * max(1, -(-lead // dp_total))
        self.example_input_shape = (lead, *example_input_shape[1:])
        self.example_input_dtype = example_input_dtype
        self.input_key = input_key
        self.label_key = label_key
        self._shardings = None
        self._abstract = None

    # -- state construction ------------------------------------------------

    def _init_boxed(self, rng) -> TrainState:
        """Init keeping flax Partitioned boxes so logical names survive
        through eval_shape into the optimizer state (optax tree_maps rebuild
        the boxes, which is how momentum inherits the param shardings)."""
        dummy = jnp.zeros(self.example_input_shape, self.example_input_dtype)
        variables = self.model.init(rng, dummy, train=False)
        params = variables["params"]
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=self.tx.init(params),
            batch_stats=variables.get("batch_stats", {}),
            guard=self.guard.init_state() if self.guard is not None else {},
            apply_fn=self.model.apply,
            tx=self.tx,
        )

    def _abstract_boxed(self) -> TrainState:
        if self._abstract is None:
            self._abstract = jax.eval_shape(
                self._init_boxed, jax.random.PRNGKey(0)
            )
        return self._abstract

    def state_shardings(self) -> TrainState:
        """NamedSharding tree for TrainState, from logical annotations."""
        if self._shardings is None:
            logical = nn.get_partition_spec(self._abstract_boxed())
            self._shardings = nn.logical_to_mesh_sharding(
                logical, self.mesh, list(self.rules.items())
            )
        return self._shardings

    def abstract_state(self) -> TrainState:
        """ShapeDtypeStruct pytree with shardings attached — the template
        for sharded checkpoint restore (each device reads its own shards)."""
        abstract = nn.meta.unbox(self._abstract_boxed())
        return jax.tree_util.tree_map(
            lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
            abstract,
            self.state_shardings(),
        )

    def init_state(self, rng) -> TrainState:
        shardings = self.state_shardings()
        init = jax.jit(
            lambda r: nn.meta.unbox(self._init_boxed(r)),
            out_shardings=shardings,
        )
        return init(rng)

    def batch_sharding(self, ndim: int = 1) -> NamedSharding:
        return shlib.batch_sharding(self.mesh, ndim)

    # -- elastic resize ----------------------------------------------------

    def resize(self, mesh: Mesh) -> "Trainer":
        """A new Trainer bound to `mesh` — the trainer half of the
        elastic gang-resize transition (docs/resilience.md).

        Only the data-parallel axes (dp/fsdp) may change size: the
        model-parallel axes (pp/sp/ep/tp) define how PARAMETERS are laid
        out across chips, and reshaping those mid-run is a different
        (restart-shaped) operation. The divisor math is validated up
        front (`parallel.mesh.resize_spec`) so a degenerate target
        fails with the arithmetic spelled out instead of an opaque
        reshape error deep in sharding."""
        from kubeflow_tpu.parallel.mesh import mesh_spec_of, resize_spec

        old_spec = mesh_spec_of(self.mesh)
        new_spec = mesh_spec_of(mesh)
        for axis in ("pp", "sp", "ep", "tp"):
            old_n, new_n = getattr(old_spec, axis), getattr(new_spec, axis)
            if old_n != new_n:
                raise ValueError(
                    f"elastic resize reshapes only the data-parallel "
                    f"axes; {axis} changed {old_n} -> {new_n} — "
                    f"model-parallel resharding needs a gang restart"
                )
        # Spell out the device/batch divisor math for the target dp
        # (fsdp rides along as part of the batch-shard product).
        resize_spec(
            dataclasses.replace(old_spec, fsdp=new_spec.fsdp),
            new_spec.dp,
            n_devices=int(mesh.devices.size),
            global_batch=self.config.batch_size,
        )
        return Trainer(
            self.model,
            self.config,
            mesh,
            rules=self.rules,
            example_input_shape=self.example_input_shape,
            input_key=self.input_key,
            label_key=self.label_key,
            example_input_dtype=self.example_input_dtype,
            guard=self.guard,
        )

    def reshard_state(self, state: TrainState) -> TrainState:
        """Re-shard a LIVE TrainState onto this trainer's mesh — the
        happy-path resize needs no checkpoint round-trip. Leaf-wise
        `jax.device_put` onto the new NamedShardings (jax reshards
        across device sets, so a state living on the old mesh's devices
        lands distributed over the new mesh's), rebuilt on THIS
        trainer's treedef so the static fields (apply_fn, tx) are this
        trainer's own rather than the old mesh's closures."""
        shardings = self.state_shardings()
        src = jax.tree_util.tree_leaves(state)
        dst = jax.tree_util.tree_leaves(shardings)
        if len(src) != len(dst):
            raise ValueError(
                f"TrainState has {len(src)} leaves but this trainer's "
                f"state tree has {len(dst)} — resize must keep the "
                "model/optimizer/guard structure identical"
            )
        leaves = [jax.device_put(x, s) for x, s in zip(src, dst)]
        return jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(shardings), leaves
        )

    # -- the step ----------------------------------------------------------

    def make_train_step(self):
        cfg = self.config
        guard = self.guard
        input_key = self.input_key
        label_key = self.label_key
        mesh = self.mesh
        batch_parts = tuple(shlib.batch_axes(mesh))
        # Accuracy needs logits; the loss-in-model path never sees them.
        has_acc = cfg.train_metrics == "full" and not cfg.loss_in_model

        def train_step(state: TrainState, batch):
            def forward_loss(params, mb, stats_in):
                """(loss, (batch_stats, accuracy)) for one (micro)batch.

                Metrics that survive accumulation are SCALARS computed
                in here (accuracy is an argmax reduced to a mean, never
                the logits themselves), so the per-tick backward frees
                each microbatch's logits before the next tick runs.
                `stats_in` is the batch_stats this tick reads — under
                accumulation each microbatch consumes the previous
                tick's updated stats (sequential BN semantics), not the
                step's starting stats."""
                variables = {"params": params}
                # "losses" is the dedicated channel for scalar auxiliary
                # losses (MoE load balancing etc.) — kept separate from
                # flax's general-purpose "intermediates" so diagnostics
                # never leak into the objective.
                mutable = ["losses"]
                if stats_in:
                    variables["batch_stats"] = stats_in
                    mutable.append("batch_stats")

                if cfg.loss_in_model:
                    # The model owns the objective (e.g. the pipelined
                    # transformer's last-stage per-microbatch CE): apply
                    # returns the scalar loss directly.
                    def forward(variables):
                        return state.apply_fn(
                            variables, mb[input_key], train=True,
                            labels=mb[label_key], mutable=mutable,
                        )
                else:
                    def forward(variables):
                        return state.apply_fn(
                            variables, mb[input_key], train=True,
                            mutable=mutable,
                        )

                if cfg.step_remat is not None:
                    from kubeflow_tpu.models.transformer import (
                        checkpoint_policy,
                    )

                    forward = jax.checkpoint(
                        forward, policy=checkpoint_policy(cfg.step_remat)
                    )
                out, new_vars = forward(variables)
                if cfg.loss_in_model:
                    loss = out
                    acc = jnp.zeros(())
                else:
                    loss = softmax_cross_entropy(
                        out, mb[label_key], cfg.label_smoothing
                    )
                    acc = (
                        jnp.mean(
                            (jnp.argmax(out, -1) == mb[label_key])
                            .astype(jnp.float32)
                        )
                        if has_acc
                        else jnp.zeros(())
                    )
                for aux in jax.tree_util.tree_leaves(
                    new_vars.get("losses", {})
                ):
                    loss = loss + aux
                return loss, (
                    new_vars.get("batch_stats", stats_in), acc
                )

            accum = cfg.accum_steps
            if accum == 1:
                (loss, (bstats, acc)), grads = jax.value_and_grad(
                    forward_loss, has_aux=True
                )(state.params, batch, state.batch_stats)
            else:
                lead = jax.tree_util.tree_leaves(batch)[0].shape[0]
                if lead % accum:
                    raise ValueError(
                        f"batch ({lead}) must divide into "
                        f"{accum} accumulation microbatches"
                    )
                microbatches = jax.tree_util.tree_map(
                    lambda a: a.reshape(
                        (accum, a.shape[0] // accum) + a.shape[1:]
                    ),
                    batch,
                )
                # Each microbatch keeps the batch sharding on its (now
                # second) example axis; the scan axis is unsharded.
                microbatches = jax.lax.with_sharding_constraint(
                    microbatches,
                    NamedSharding(mesh, P(None, batch_parts)),
                )
                # Per-tick checkpoint: differentiating through the scan
                # re-runs ONE microbatch's forward per backward tick —
                # activation memory is bounded by microbatches in
                # flight, not the whole batch. step_remat / the model's
                # remat_policy still govern what that per-tick
                # recompute itself saves.
                tick = jax.checkpoint(forward_loss)

                def accum_loss(params):
                    def body(carry, mb):
                        lsum, asum, bs = carry
                        # Thread batch_stats tick to tick: each
                        # microbatch's BN update builds on the previous
                        # one's, so the step's final stats reflect
                        # EVERY microbatch (sequential-small-batch
                        # semantics), not just the last.
                        loss, (bs, acc) = tick(params, mb, bs)
                        return (lsum + loss, asum + acc, bs), None

                    carry0 = (jnp.zeros(()), jnp.zeros(()),
                              state.batch_stats)
                    (lsum, asum, bstats), _ = jax.lax.scan(
                        body, carry0, microbatches
                    )
                    # Mean over equal-sized microbatches == the
                    # full-batch mean, so grads match accum_steps=1.
                    return lsum / accum, (bstats, asum / accum)

                (loss, (bstats, acc)), grads = jax.value_and_grad(
                    accum_loss, has_aux=True
                )(state.params)

            metrics = {"loss": loss}
            if has_acc:
                metrics["accuracy"] = acc
            if guard is None:
                state = state.apply_gradients(grads=grads, batch_stats=bstats)
                return state, metrics

            # Anomaly guard: screen this step's loss/grad-norm AND the
            # finiteness of the updated params ON DEVICE (a finite
            # gradient can still overflow a param to inf — an accepted
            # overflow would poison every later checkpoint), then
            # select between the applied and the skipped state
            # leaf-wise. A rejected step keeps params, optimizer state
            # and BN stats untouched (the bad batch must not leak into
            # anything), but still advances the step counter so
            # checkpoint/data bookkeeping stays step-aligned. The
            # verdict never syncs to the host — the select + isfinite
            # cost extra HBM passes over the state, not a device fence.
            grad_norm = optax.global_norm(grads)
            applied = state.apply_gradients(grads=grads, batch_stats=bstats)
            # batch_stats are screened too: a huge-but-finite poison
            # batch can keep loss/grads/params finite (BN normalizes it
            # away) while its batch variance overflows the f32 running
            # stats to inf — accepted, that inf rides into every later
            # checkpoint and breaks eval/serving (train=False).
            update_finite = jnp.bool_(True)
            for leaf in jax.tree_util.tree_leaves(
                (applied.params, applied.batch_stats)
            ):
                if jnp.issubdtype(leaf.dtype, jnp.floating):
                    update_finite &= jnp.all(jnp.isfinite(leaf))
            gstate, ok = guard.apply(
                state.guard, loss, grad_norm, update_finite=update_finite
            )
            applied = applied.replace(guard=gstate)
            skipped = state.replace(step=state.step + 1, guard=gstate)
            state = jax.tree_util.tree_map(
                lambda a, b: jnp.where(ok, a, b), applied, skipped
            )
            metrics.update(guard.metrics(gstate, ok, grad_norm))
            return state, metrics

        return jax.jit(
            train_step,
            donate_argnums=0,
            out_shardings=(self.state_shardings(), None),
        )

    def make_eval_step(self):
        cfg = self.config
        input_key, label_key = self.input_key, self.label_key

        def eval_step(state: TrainState, batch):
            variables = {"params": state.params}
            if state.batch_stats:
                variables["batch_stats"] = state.batch_stats
            if cfg.loss_in_model:
                # The model computes its own objective; no logits ever
                # reach the host side of the step, so loss is the only
                # eval metric on this path.
                return {
                    "loss": state.apply_fn(
                        variables, batch[input_key], train=False,
                        labels=batch[label_key],
                    )
                }
            logits = state.apply_fn(variables, batch[input_key], train=False)
            return {
                "loss": softmax_cross_entropy(logits, batch[label_key]),
                "accuracy": jnp.mean(
                    (jnp.argmax(logits, -1) == batch[label_key]).astype(
                        jnp.float32
                    )
                ),
            }

        return jax.jit(eval_step)
