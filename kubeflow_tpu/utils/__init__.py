"""Shared utilities: metrics registry, structured logging helpers."""

from kubeflow_tpu.utils.metrics import Counter, Gauge, MetricsRegistry
