"""Prometheus-style metrics, dependency-free.

The reference instruments everything with Prometheus (SURVEY.md §5:
notebook collector `pkg/metrics/metrics.go:22-99`, profile counters +
heartbeat `monitoring.go:27-59`, kfam request metrics). This module gives
controllers and servers the same conventions — counters/gauges with label
sets and text exposition — without depending on prometheus_client.
"""

from __future__ import annotations

import threading
from typing import Iterable, Mapping


def _fmt_labels(labels: Mapping[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class _Metric:
    def __init__(self, name: str, help_: str, label_names: Iterable[str] = ()):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def _key(self, labels: Mapping[str, str]) -> tuple:
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, "
                f"got {tuple(labels)}"
            )
        return tuple(labels[k] for k in self.label_names)

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def expose(self, kind: str) -> str:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} {kind}",
        ]
        with self._lock:
            if not self._values and not self.label_names:
                lines.append(f"{self.name} 0")
            for key, val in sorted(self._values.items()):
                labels = dict(zip(self.label_names, key))
                lines.append(f"{self.name}{_fmt_labels(labels)} {val:g}")
        return "\n".join(lines)


class Counter(_Metric):
    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            key = self._key(labels)
            self._values[key] = self._values.get(key, 0.0) + amount

    def expose_text(self) -> str:
        return self.expose("counter")


class Gauge(_Metric):
    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[self._key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        with self._lock:
            key = self._key(labels)
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def expose_text(self) -> str:
        return self.expose("gauge")


class MetricsRegistry:
    """Named collection of metrics with a /metrics text endpoint body."""

    def __init__(self):
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str = "", labels: Iterable[str] = ()) -> Counter:
        return self._register(name, Counter(name, help_, labels))

    def gauge(self, name: str, help_: str = "", labels: Iterable[str] = ()) -> Gauge:
        return self._register(name, Gauge(name, help_, labels))

    def _register(self, name: str, metric: _Metric) -> _Metric:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if type(existing) is not type(metric) or (
                    existing.label_names != metric.label_names
                ):
                    raise ValueError(f"metric {name} re-registered differently")
                return existing
            self._metrics[name] = metric
            return metric

    def expose_text(self) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        return "\n".join(m.expose_text() for m in metrics) + "\n"
