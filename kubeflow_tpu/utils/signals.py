"""Graceful-shutdown signal handling for threaded server processes.

Every long-running entrypoint (platform launcher, deploy server,
admission webhook, e2e apiserver worker) needs the same three subtle
properties, so they live in one place:

- **Event-based handlers, installed early.** A handler that raises
  (KeyboardInterrupt-style) can unwind through half-constructed boot
  state; setting an Event lets the main function finish (or abort) its
  boot and run one well-defined cleanup path. Installing before
  anything serves means a stop signal can never catch the boot window
  on the default disposition. SIGINT is installed explicitly even
  though Python normally does it: a backgrounding non-interactive shell
  starts children with SIGINT=SIG_IGN, and Python then skips its
  default handler — `kill -INT` would silently no-op.
- **Poll, don't park.** A process-directed signal can be DELIVERED to a
  non-main thread; the Python-level handler then only runs when the
  MAIN thread next executes bytecode. A main thread parked in a bare
  ``Event.wait()`` (sem_wait) or ``time.sleep(3600)`` never gets there
  — reproduced in the restart e2e, where a worker ignored its SIGTERM
  forever. Waking every half second bounds shutdown latency instead.
"""

from __future__ import annotations

import signal
import threading


def install_shutdown_handlers(
    signals: tuple[int, ...] = (signal.SIGTERM, signal.SIGINT),
) -> threading.Event:
    """Install handlers for `signals` that set the returned Event.
    Call from the MAIN thread, before serving anything."""
    stop = threading.Event()
    for sig in signals:
        signal.signal(sig, lambda *_: stop.set())
    return stop


def wait_for_shutdown(stop: threading.Event, poll: float = 0.5) -> None:
    """Block the main thread until `stop` is set — polling (see module
    docstring for why), and treating a raw KeyboardInterrupt (Ctrl-C
    delivered before/around our handler) as the same request."""
    try:
        while not stop.wait(poll):
            pass
    except KeyboardInterrupt:
        stop.set()
