"""Bounded joins with loud stuck-thread diagnostics.

The kftpu-race pass (`ci/lint/concurrency.py`, rule ``untimed-join``)
bans bare ``thread.join()`` / ``queue.Queue.join()`` in the package: a
stuck worker then hangs its caller forever with nothing pointing at the
culprit. These helpers are the sanctioned replacement — they wait up to
a deadline (default `KFTPU_STUCK_TIMEOUT_S`, 300s) and then raise
`StuckThreadError` carrying a stack dump of every live thread, so a
wedged shutdown fails loudly with the evidence attached instead of
silently parking in `pthread_cond_wait`.

`queue.Queue.join()` has no timeout parameter at all; `join_queue`
reimplements the drain-wait against the queue's own ``all_tasks_done``
condition, which is the documented synchronization `Queue.join` uses.
"""

from __future__ import annotations

import os
import queue as queue_mod
import sys
import threading
import time
import traceback

DEFAULT_TIMEOUT_S = 300.0


class StuckThreadError(RuntimeError):
    """A bounded join expired: some thread/queue never finished."""


def stuck_timeout_s() -> float:
    """The default deadline, overridable via KFTPU_STUCK_TIMEOUT_S."""
    raw = os.environ.get("KFTPU_STUCK_TIMEOUT_S", "")
    try:
        return float(raw) if raw else DEFAULT_TIMEOUT_S
    except ValueError:
        return DEFAULT_TIMEOUT_S


def dump_thread_stacks() -> str:
    """One formatted stack per live thread — the diagnostic payload a
    stuck join attaches so the wedge names its culprit."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in sorted(sys._current_frames().items()):
        name = names.get(ident, "?")
        stack = "".join(traceback.format_stack(frame))
        out.append(f"--- thread {name} (ident={ident}) ---\n{stack}")
    return "\n".join(out)


def join_thread(
    thread: threading.Thread,
    timeout: float | None = None,
    *,
    what: str = "",
) -> None:
    """`thread.join` with a deadline; raises `StuckThreadError` (with
    all-thread stacks) instead of hanging forever."""
    deadline = timeout if timeout is not None else stuck_timeout_s()
    thread.join(deadline)
    if thread.is_alive():
        label = what or thread.name
        raise StuckThreadError(
            f"{label} still running after {deadline:.0f}s join — "
            f"thread stacks:\n{dump_thread_stacks()}"
        )


def join_queue(
    q: "queue_mod.Queue",
    timeout: float | None = None,
    *,
    what: str = "",
) -> None:
    """`queue.Queue.join` with a deadline (the stdlib method has none);
    raises `StuckThreadError` with all-thread stacks on expiry."""
    deadline_s = timeout if timeout is not None else stuck_timeout_s()
    deadline = time.monotonic() + deadline_s
    with q.all_tasks_done:
        while q.unfinished_tasks:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                label = what or "queue"
                raise StuckThreadError(
                    f"{label} still has {q.unfinished_tasks} "
                    f"unfinished task(s) after {deadline_s:.0f}s — "
                    f"thread stacks:\n{dump_thread_stacks()}"
                )
            q.all_tasks_done.wait(remaining)


def run_until_interrupt(thread: threading.Thread) -> bool:
    """Foreground-serve loop for `__main__` entry points: park on the
    server thread in bounded slices (so the join is interruptible and
    never an untimed wedge) until it exits or the operator hits ^C.
    Returns True when interrupted, False when the thread exited."""
    try:
        while thread.is_alive():
            thread.join(1.0)
    except KeyboardInterrupt:
        return True
    return False
