"""Lightweight distributed-tracing spans for the control plane.

The reference has no tracing at all (SURVEY.md §5: "No distributed
tracing (no OpenTelemetry/jaeger)"); debugging a slow notebook spawn
meant reading four components' logs. This closes that gap with an
OTel-shaped core small enough to have zero dependencies:

- `Tracer.span(name, **attrs)` — context manager; nesting via a
  contextvar gives parent/child links; each top-level span starts a new
  trace id. Thread- and async-safe (contextvars propagate per thread).
- spans record start/end monotonic-derived wall times, duration,
  attributes, and an error flag when the body raises.
- finished spans land in a bounded ring buffer (`export()` drains JSON
  dicts, oldest dropped on overflow) — the in-process collector; ship
  them wherever by draining periodically.
- `trace_header()`/`from_header()` carry the trace id across HTTP hops
  (`x-kftpu-trace-id`, the platform's traceparent analog), so a web
  request's span tree continues into kfam/controllers.

Integration: the controller runtime wraps every reconcile in a span and
the WSGI core wraps every request; both attach the standard attributes
(controller/key/outcome, method/path/status).
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import threading
import time
import uuid
from collections import deque
from typing import Any, Iterator

HEADER = "x-kftpu-trace-id"

_current: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "kftpu_current_span", default=None
)


@dataclasses.dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: str | None
    start: float
    attributes: dict[str, Any]
    end: float | None = None
    error: str | None = None

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "parentId": self.parent_id,
            "start": self.start,
            "end": self.end,
            "durationMs": (
                None if self.end is None else (self.end - self.start) * 1e3
            ),
            "attributes": dict(self.attributes),
            "error": self.error,
        }


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


class Tracer:
    def __init__(self, capacity: int = 2048):
        self._lock = threading.Lock()
        self._finished: deque[dict] = deque(maxlen=capacity)
        self.dropped = 0
        self._capacity = capacity

    @contextlib.contextmanager
    def span(
        self,
        name: str,
        *,
        trace_id: str | None = None,
        **attributes: Any,
    ) -> Iterator[Span]:
        parent = _current.get()
        span = Span(
            name=name,
            trace_id=(
                trace_id
                or (parent.trace_id if parent is not None else _new_id())
            ),
            span_id=_new_id(),
            parent_id=parent.span_id if parent is not None else None,
            start=time.time(),
            attributes=dict(attributes),
        )
        token = _current.set(span)
        try:
            yield span
        except Exception as e:
            span.error = f"{type(e).__name__}: {e}"
            raise
        finally:
            _current.reset(token)
            span.end = time.time()
            with self._lock:
                if len(self._finished) == self._capacity:
                    self.dropped += 1
                self._finished.append(span.to_dict())

    def export(self) -> list[dict]:
        """Drain all finished spans (oldest first)."""
        with self._lock:
            out = list(self._finished)
            self._finished.clear()
            return out

    def pending(self) -> int:
        with self._lock:
            return len(self._finished)


# The process-wide tracer the runtime and web tier report to. Tests may
# instantiate their own.
tracer = Tracer()


def current_trace_id() -> str | None:
    span = _current.get()
    return span.trace_id if span is not None else None


def trace_header() -> dict[str, str]:
    """Headers to propagate the active trace across an HTTP hop."""
    trace_id = current_trace_id()
    return {HEADER: trace_id} if trace_id else {}


def from_header(headers: Any) -> str | None:
    """The inbound trace id, if the caller sent one. `headers` is any
    mapping with a case-insensitive-ish get (WSGI request headers)."""
    if headers is None:
        return None
    get = getattr(headers, "get", None)
    if get is None:
        return None
    return get(HEADER) or get(HEADER.upper()) or None
