"""Shared web-application core — the `crud_backend` analog.

The reference ships a shared Flask library
(`components/crud-web-apps/common/backend/kubeflow/kubeflow/crud_backend/`)
that every CRUD UI backend builds on: before-request header authn
(`authn.py:39`), SubjectAccessReview authz (`authz.py:46-80`), typed K8s
API wrappers, uniform success/error JSON envelopes (`api/utils.py:6`), and
liveness probes. This package provides the same core on the stdlib WSGI
interface (no Flask in the image) so every app in `kubeflow_tpu.apps`
shares one authn/authz/error surface.
"""

from kubeflow_tpu.web.authn import HeaderAuthn
from kubeflow_tpu.web.authz import Forbidden, ensure_authorized
from kubeflow_tpu.web.wsgi import (
    App,
    HttpError,
    Request,
    Response,
    TestClient,
    json_response,
    success_response,
)

__all__ = [
    "App",
    "Forbidden",
    "HeaderAuthn",
    "HttpError",
    "Request",
    "Response",
    "TestClient",
    "ensure_authorized",
    "json_response",
    "success_response",
]
