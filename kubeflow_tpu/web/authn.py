"""Header-based authentication — the platform's trust model.

The reference trusts the mesh gateway to authenticate and inject a user-id
header; backends read it and strip an optional prefix
(`crud_backend/authn.py:39`, `jupyter-web-app/.../auth.py:41`,
`centraldashboard/app/attach_user_middleware.ts`). Knobs mirror the
reference's: USERID_HEADER (default `x-goog-authenticated-user-email`,
`access-management/main.go:38`) and USERID_PREFIX (`accounts.google.com:`).
"""

from __future__ import annotations

import os

from kubeflow_tpu.web.wsgi import Request, Response, error_response

DEFAULT_HEADER = "x-goog-authenticated-user-email"
DEFAULT_PREFIX = "accounts.google.com:"

# Probe/static paths that must work without identity (kubelet probes).
SKIP_PATHS = ("/healthz", "/metrics")


class HeaderAuthn:
    """Before-request hook: resolve `request.user` or 401."""

    def __init__(
        self,
        header: str | None = None,
        prefix: str | None = None,
        anonymous: str | None = None,
    ):
        self.header = (
            header
            if header is not None
            else os.environ.get("USERID_HEADER", DEFAULT_HEADER)
        ).lower()
        self.prefix = (
            prefix
            if prefix is not None
            else os.environ.get("USERID_PREFIX", DEFAULT_PREFIX)
        )
        # Dev-mode escape hatch (crud_backend config.py dev mode): treat
        # unauthenticated requests as this fixed user instead of 401.
        self.anonymous = anonymous

    def __call__(self, req: Request) -> Response | None:
        if req.path in SKIP_PATHS:
            return None
        raw = req.headers.get(self.header, "")
        if raw.startswith(self.prefix):
            raw = raw[len(self.prefix):]
        if not raw:
            if self.anonymous:
                req.user = self.anonymous
                return None
            return error_response(
                401, f"no user identity in header {self.header!r}"
            )
        req.user = raw
        return None
