"""Per-request authorization via SubjectAccessReview.

Every reference backend guards each handler with an SAR
(`crud_backend/authz.py:46-80`: build SAR for (user, verb, resource,
namespace), 403 with a readable message on deny). Same surface here,
answered by the in-process RBAC evaluator.
"""

from __future__ import annotations

from kubeflow_tpu.api.rbac import subject_access_review
from kubeflow_tpu.testing.fake_apiserver import FakeApiServer
from kubeflow_tpu.web.wsgi import HttpError, Request


class Forbidden(HttpError):
    def __init__(self, message: str):
        super().__init__(403, message)


# The HTTP method a mesh sidecar would see for each API verb — used when
# the caller doesn't hand us the live request (mesh `to.operation.methods`
# rules match HTTP methods, not K8s verbs).
_VERB_METHODS = {
    "get": "GET", "list": "GET", "watch": "GET",
    "create": "POST", "update": "PUT", "patch": "PATCH",
    "delete": "DELETE",
}


def ensure_authorized(
    api: FakeApiServer,
    user: str | None,
    verb: str,
    resource: str,
    namespace: str = "",
    request: Request | None = None,
) -> None:
    if user is None:
        raise HttpError(401, "request has no authenticated user")
    if not subject_access_review(api, user, verb, resource, namespace):
        scope = f"in namespace {namespace!r}" if namespace else "cluster-wide"
        raise Forbidden(
            f"user {user!r} is not allowed to {verb} {resource} {scope}"
        )
    if namespace:
        # Second gate, mirroring production traffic flow: RBAC authorizes
        # the API verb, the mesh admits the principal's OPERATION into
        # the namespace (`profile_controller.go:190` owner policy + kfam
        # contributor policies with method constraints). RBAC-without-
        # mesh-policy must fail closed here, not silently skip the mesh.
        from kubeflow_tpu.web.mesh import ensure_mesh_admits

        ensure_mesh_admits(
            api,
            user,
            namespace,
            method=(
                request.method if request is not None
                else _VERB_METHODS.get(verb)
            ),
            path=request.path if request is not None else None,
        )
