"""Mesh-policy admission — the Istio AuthorizationPolicy evaluator.

The reference puts every tenant namespace behind Istio RBAC: the profile
controller creates the owner's ServiceRole/ServiceRoleBinding at
namespace creation (`profile_controller.go:190`) and kfam adds
contributor bindings (`kfam/bindings.go:76-128`). Traffic into the
namespace's services is admitted by the sidecars, not the apps. Our
platform-in-a-box has no sidecars, so the web tier evaluates the same
policy objects at the request boundary.

Semantics follow Istio's ALLOW-policy rules: a namespace with no ALLOW
policies admits everyone (policy-free namespaces stay open — hand-made
test namespaces, system namespaces); once any ALLOW policy exists, a
request is admitted only if some policy rule matches its principal (an
empty `from` clause matches all sources).
"""

from __future__ import annotations

from kubeflow_tpu.testing.fake_apiserver import FakeApiServer
from kubeflow_tpu.web.wsgi import HttpError


def mesh_admits(api: FakeApiServer, user: str, namespace: str) -> bool:
    policies = [
        p
        for p in api.list("AuthorizationPolicy", namespace)
        if p.spec.get("action", "ALLOW") == "ALLOW"
    ]
    if not policies:
        return True
    for policy in policies:
        for rule in policy.spec.get("rules", []):
            sources = rule.get("from", [])
            if not sources:
                return True
            for source in sources:
                if user in source.get("source", {}).get("principals", []):
                    return True
    return False


def ensure_mesh_admits(
    api: FakeApiServer, user: str, namespace: str
) -> None:
    from kubeflow_tpu.api.rbac import is_cluster_admin

    # Cluster-admins reach workloads through the platform gateway, which
    # the mesh trusts (the reference's admins bypass the mesh via
    # kubectl; the dashboard's admin probe is `api_default.go:270`).
    if is_cluster_admin(api, user):
        return
    if not mesh_admits(api, user, namespace):
        raise HttpError(
            403,
            f"mesh policy denies {user!r} access to namespace "
            f"{namespace!r} (no AuthorizationPolicy admits this "
            "principal — ask the profile owner for a contributor binding)",
        )
