"""Mesh-policy admission — the Istio AuthorizationPolicy evaluator.

The reference puts every tenant namespace behind Istio RBAC: the profile
controller creates the owner's ServiceRole/ServiceRoleBinding at
namespace creation (`profile_controller.go:190`) and kfam adds
contributor bindings (`kfam/bindings.go:76-128`). The ServiceRole rules
carry services/methods/paths constraints with exact/prefix/suffix `*`
matching (`istiorbac/v1alpha1/servicerole_types.go:38-75`); traffic into
the namespace's services is admitted by the sidecars, not the apps. Our
platform-in-a-box has no sidecars, so the web tier evaluates the same
policy objects at the request boundary.

Semantics follow Istio's AuthorizationPolicy evaluation order:

1. If any DENY policy has a rule matching the request → deny.
2. If the namespace has no ALLOW policies → allow (policy-free
   namespaces stay open: hand-made test namespaces, system namespaces).
3. Otherwise allow iff some ALLOW policy rule matches.

A rule matches when its `from` matches the principal AND its `to`
matches the operation; an empty/missing clause matches anything — which
makes `rules: []` the deny-all idiom (the policy flips the namespace
into enforce mode yet admits nobody), and `rules: [{}]` allow-all.
Principals and paths support Istio's exact, `prefix*`, and `*suffix`
match forms; methods are exact HTTP verbs.
"""

from __future__ import annotations

from kubeflow_tpu.testing.fake_apiserver import FakeApiServer
from kubeflow_tpu.web.wsgi import HttpError


def _match(pattern: str, value: str) -> bool:
    """Istio string match: exact, `foo*` prefix, `*foo` suffix, `*` any
    (`servicerole_types.go:33-41` documents the same three forms)."""
    if pattern == "*":
        return True
    if pattern.endswith("*"):
        return value.startswith(pattern[:-1])
    if pattern.startswith("*"):
        return value.endswith(pattern[1:])
    return pattern == value


def _from_matches(rule: dict, user: str) -> bool:
    sources = rule.get("from", [])
    if not sources:
        return True  # no source constraint = any principal
    return any(
        any(
            _match(p, user)
            for p in source.get("source", {}).get("principals", [])
        )
        for source in sources
    )


def _to_matches(
    rule: dict,
    method: str | None,
    path: str | None,
    *,
    fail_closed: bool = False,
) -> bool:
    operations = rule.get("to", [])
    if not operations:
        return True  # no operation constraint = any method/path
    for to in operations:
        op = to.get("operation", {})
        methods = op.get("methods", [])
        paths = op.get("paths", [])
        # A None method/path means the caller didn't present one (an
        # in-process check without a request). In Istio every request
        # carries both, so a constrained rule always gets something to
        # match; here a DENY rule must treat the absent value as
        # MATCHING (fail closed) — otherwise method-scoped DENY policies
        # silently fail open for exactly the callers that bypass HTTP.
        method_ok = not methods or (
            fail_closed
            if method is None
            else any(_match(m, method) for m in methods)
        )
        path_ok = not paths or (
            fail_closed
            if path is None
            else any(_match(p, path) for p in paths)
        )
        if method_ok and path_ok:
            return True
    return False


def _rule_matches(
    rule: dict,
    user: str,
    method: str | None,
    path: str | None,
    *,
    fail_closed: bool = False,
) -> bool:
    return _from_matches(rule, user) and _to_matches(
        rule, method, path, fail_closed=fail_closed
    )


def mesh_admits(
    api: FakeApiServer,
    user: str,
    namespace: str,
    *,
    method: str | None = None,
    path: str | None = None,
) -> bool:
    policies = api.list("AuthorizationPolicy", namespace)
    allows = [p for p in policies if p.spec.get("action", "ALLOW") == "ALLOW"]
    denies = [p for p in policies if p.spec.get("action") == "DENY"]
    # DENY is evaluated first and wins (Istio's order of evaluation).
    # fail_closed: an absent method/path matches constrained DENY rules.
    for policy in denies:
        if any(
            _rule_matches(rule, user, method, path, fail_closed=True)
            for rule in policy.spec.get("rules", [])
        ):
            return False
    if not allows:
        return True
    return any(
        _rule_matches(rule, user, method, path)
        for policy in allows
        for rule in policy.spec.get("rules", [])
    )


def ensure_mesh_admits(
    api: FakeApiServer,
    user: str,
    namespace: str,
    *,
    method: str | None = None,
    path: str | None = None,
) -> None:
    from kubeflow_tpu.api.rbac import is_cluster_admin

    # Cluster-admins reach workloads through the platform gateway, which
    # the mesh trusts (the reference's admins bypass the mesh via
    # kubectl; the dashboard's admin probe is `api_default.go:270`).
    if is_cluster_admin(api, user):
        return
    if not mesh_admits(api, user, namespace, method=method, path=path):
        what = f" {method}" if method else ""
        raise HttpError(
            403,
            f"mesh policy denies {user!r}{what} access to namespace "
            f"{namespace!r} (no AuthorizationPolicy admits this "
            "principal for this operation — ask the profile owner for a "
            "contributor binding)",
        )
