"""OpenAPI surface for the platform's HTTP apps + the drift gate.

The reference ships a swagger spec for its deploy service
(`bootstrap/api/swagger.yaml`) and generates kfam from swagger
(`access-management/kfam` is swagger-codegen output). Here the specs live
in `docs/api/*.yaml` and this module keeps them honest: `route_table`
extracts an App's live routing table in OpenAPI path form, and
`spec_drift` diffs it against a spec — run in CI by
`tests/test_openapi.py`, so a route added without a spec update (or vice
versa) fails the build instead of rotting silently.

`python -m kubeflow_tpu.web.openapi` regenerates skeletons to diff
against when authoring.
"""

from __future__ import annotations

import re

from kubeflow_tpu.web.wsgi import App

_PARAM_RE = re.compile(r"<([a-zA-Z_][a-zA-Z0-9_]*)(:path)?>")

# Probe endpoint — implicitly present on every App; specs document it via
# a shared snippet but the drift gate tolerates either choice.
_IMPLICIT = {("get", "/healthz")}


def _openapi_path(pattern: str) -> str:
    return _PARAM_RE.sub(lambda m: "{" + m.group(1) + "}", pattern)


def route_table(app: App) -> set[tuple[str, str]]:
    """The app's live operations as (lowercase method, OpenAPI path)."""
    out = set()
    for route in app._routes:
        path = _openapi_path(route.pattern)
        for method in route.methods:
            out.add((method.lower(), path))
    return out


def spec_operations(spec: dict) -> set[tuple[str, str]]:
    """The spec's operations as (lowercase method, path)."""
    out = set()
    for path, ops in (spec.get("paths") or {}).items():
        for method in ops:
            if method.lower() in (
                "get", "post", "put", "patch", "delete", "head", "options"
            ):
                out.add((method.lower(), path))
    return out


def spec_drift(app: App, spec: dict) -> list[str]:
    """Human-readable drift between an app's routes and its spec; empty
    means in sync. /healthz may be documented or not."""
    routes = route_table(app) - _IMPLICIT
    documented = spec_operations(spec) - _IMPLICIT
    drift = []
    for method, path in sorted(routes - documented):
        drift.append(f"route not in spec: {method.upper()} {path}")
    for method, path in sorted(documented - routes):
        drift.append(f"spec documents missing route: {method.upper()} {path}")
    return drift


def skeleton(app: App, title: str, version: str = "v1") -> dict:
    """A minimal valid OpenAPI 3 document for the app's current routes —
    the starting point for the checked-in, human-enriched spec."""
    paths: dict[str, dict] = {}
    for method, path in sorted(route_table(app) - _IMPLICIT):
        op = {
            "summary": f"{method.upper()} {path}",
            "responses": {"200": {"description": "OK"}},
        }
        params = re.findall(r"\{([a-zA-Z_][a-zA-Z0-9_]*)\}", path)
        if params:
            op["parameters"] = [
                {
                    "name": p,
                    "in": "path",
                    "required": True,
                    "schema": {"type": "string"},
                }
                for p in params
            ]
        paths.setdefault(path, {})[method] = op
    return {
        "openapi": "3.0.3",
        "info": {"title": title, "version": version},
        "paths": paths,
    }


def main() -> None:
    import sys

    import yaml

    from kubeflow_tpu.apps.kfam import KfamApp
    from kubeflow_tpu.controllers.webhook import MutatingWebhookApp
    from kubeflow_tpu.deploy.provisioner import FakeCloud
    from kubeflow_tpu.deploy.server import DeployServer
    from kubeflow_tpu.testing.apiserver_http import ApiServerApp
    from kubeflow_tpu.testing.fake_apiserver import FakeApiServer

    api = FakeApiServer()
    for app, title in (
        (ApiServerApp(api), "kubeflow-tpu apiserver facade"),
        (KfamApp(api), "kubeflow-tpu access management (kfam)"),
        (DeployServer(api, FakeCloud(api)), "kubeflow-tpu deploy service"),
        (
            MutatingWebhookApp(lambda obj, op: obj),
            "kubeflow-tpu admission webhook",
        ),
    ):
        sys.stdout.write(f"# --- {app.name} ---\n")
        yaml.safe_dump(skeleton(app, title), sys.stdout, sort_keys=False)


if __name__ == "__main__":
    main()
