"""Control-plane TLS: a platform CA + server certificates.

The reference never serves a custom listener in clear: its only in-repo
custom server is TLS-only (`admission-webhook/main.go:443` raw TLS,
`:597`), apiserver traffic is always TLS, and the edge is
IAP-authenticated (`metric-collector/service-readiness/
kubeflow-readiness.py:21-38`). Our facade authenticates every request
with bearer tokens (`api/tokens.py`) — tokens that must not ride
plaintext between processes. This module is the cert plumbing:

- `ensure_tls_dir(dir)` mints (idempotently) a CA plus a server cert
  with localhost/127.0.0.1 SANs into `dir` and returns the paths — the
  launcher calls it at boot, clients pin `ca.crt`;
- `server_context`/`client_context` build the ssl contexts both ends
  use (client side verifies against the pinned CA only — no system
  trust store, so a stolen public CA cert is useless against us).

Key files are written 0600. Certs are valid for ~2 years; the CA is an
issuing root only (pathlen 0, CA:TRUE), the server key is a leaf.
"""

from __future__ import annotations

import dataclasses
import datetime
import ipaddress
import os
import ssl
import threading

_mint_lock = threading.Lock()


@dataclasses.dataclass(frozen=True)
class TlsPaths:
    ca_cert: str
    server_cert: str
    server_key: str


def _write_private(path: str, data: bytes) -> None:
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "wb") as f:
        f.write(data)


def _expiring_soon(cert_path: str, margin_days: float = 30.0) -> bool:
    """True when the cert is unreadable, expired, or within the renewal
    margin — a state-dir older than the cert lifetime must re-mint at
    boot, not serve an expired cert forever."""
    from cryptography import x509

    try:
        with open(cert_path, "rb") as f:
            cert = x509.load_pem_x509_certificate(f.read())
    except (OSError, ValueError):
        return True
    now = datetime.datetime.now(datetime.timezone.utc)
    return cert.not_valid_after_utc <= now + datetime.timedelta(
        days=margin_days
    )


def read_hosts_marker(directory: str) -> tuple[str, ...]:
    """The host set the directory's cert was minted for, or () when the
    dir has no minted cert yet. Lets callers that auto-detect hosts keep
    a durable restart's SANs stable (re-probing a changed DHCP lease
    would silently re-mint the CA and break every pinned client)."""
    try:
        with open(os.path.join(directory, "hosts")) as f:
            line = f.read().strip()
    except (FileNotFoundError, NotADirectoryError):
        return ()
    return tuple(h for h in line.split(",") if h)


def ensure_tls_dir(
    directory: str, hosts: tuple[str, ...] = ("localhost", "127.0.0.1")
) -> TlsPaths:
    """Mint (or reuse) a CA + server cert pair under `directory`."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    os.makedirs(directory, mode=0o700, exist_ok=True)
    paths = TlsPaths(
        ca_cert=os.path.join(directory, "ca.crt"),
        server_cert=os.path.join(directory, "server.crt"),
        server_key=os.path.join(directory, "server.key"),
    )
    hosts_marker = os.path.join(directory, "hosts")
    hosts_line = ",".join(hosts)
    with _mint_lock:
        if all(
            os.path.exists(p)
            for p in (paths.ca_cert, paths.server_cert, paths.server_key)
        ):
            prior = read_hosts_marker(directory)
            # Set comparison: callers merge prior + flag-supplied names
            # in varying orders; a reordering is not a reason to re-mint
            # the CA and break pinned clients.
            if set(prior) == set(hosts) and not _expiring_soon(
                paths.server_cert
            ):
                # Durable restart: same CA, clients stay pinned.
                return paths
            # Host set changed (rebooted with a different --host) or the
            # cert is near/past expiry (the CA key is deliberately not
            # kept, so renewal IS a re-mint) — re-mint the whole dir;
            # clients re-pin the printed CA.

        now = datetime.datetime.now(datetime.timezone.utc)
        not_after = now + datetime.timedelta(days=730)

        def name(cn: str) -> x509.Name:
            return x509.Name(
                [x509.NameAttribute(NameOID.COMMON_NAME, cn)]
            )

        # EC keys: small, fast handshakes, no RSA keygen latency at boot.
        ca_key = ec.generate_private_key(ec.SECP256R1())
        ca_cert = (
            x509.CertificateBuilder()
            .subject_name(name("kubeflow-tpu-ca"))
            .issuer_name(name("kubeflow-tpu-ca"))
            .public_key(ca_key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now)
            .not_valid_after(not_after)
            .add_extension(
                x509.BasicConstraints(ca=True, path_length=0), critical=True
            )
            .sign(ca_key, hashes.SHA256())
        )

        server_key = ec.generate_private_key(ec.SECP256R1())
        sans: list[x509.GeneralName] = []
        for host in hosts:
            try:
                sans.append(x509.IPAddress(ipaddress.ip_address(host)))
            except ValueError:
                sans.append(x509.DNSName(host))
        server_cert = (
            x509.CertificateBuilder()
            .subject_name(name("kubeflow-tpu-apiserver"))
            .issuer_name(ca_cert.subject)
            .public_key(server_key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now)
            .not_valid_after(not_after)
            .add_extension(
                x509.BasicConstraints(ca=False, path_length=None),
                critical=True,
            )
            .add_extension(
                x509.SubjectAlternativeName(sans), critical=False
            )
            .sign(ca_key, hashes.SHA256())
        )

        pem = serialization.Encoding.PEM
        with open(paths.ca_cert, "wb") as f:
            f.write(ca_cert.public_bytes(pem))
        with open(paths.server_cert, "wb") as f:
            f.write(server_cert.public_bytes(pem))
        _write_private(
            paths.server_key,
            server_key.private_bytes(
                pem,
                serialization.PrivateFormat.PKCS8,
                serialization.NoEncryption(),
            ),
        )
        with open(hosts_marker, "w") as f:
            f.write(hosts_line + "\n")
        # The CA key is NOT persisted: nothing needs to issue later certs
        # (rotation = re-mint the whole dir), and a CA key on disk is the
        # one secret that would let an attacker impersonate the apiserver.
        return paths


def server_context(paths: TlsPaths) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.minimum_version = ssl.TLSVersion.TLSv1_2
    ctx.load_cert_chain(paths.server_cert, paths.server_key)
    return ctx


def is_pem_data(value: str) -> bool:
    """True when `value` is inline PEM material rather than a file path.
    The single shared sniff — webhook config building, store-side
    caBundle validation, and client_context all route through it so the
    heuristic can never drift between the three."""
    return "-----BEGIN" in value


def client_context(ca_cert: str) -> ssl.SSLContext:
    """Verify the server against the pinned platform CA only.

    `ca_cert` is either inline PEM data (`is_pem_data` — the K8s
    `caBundle` form, self-contained and safe to ship in a CR created by
    a remote client) or a local file path (the legacy/local convenience
    form; only meaningful when caller and CA file share a filesystem)."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.minimum_version = ssl.TLSVersion.TLSv1_2
    ctx.verify_mode = ssl.CERT_REQUIRED
    ctx.check_hostname = True
    if is_pem_data(ca_cert):
        ctx.load_verify_locations(cadata=ca_cert)
    else:
        ctx.load_verify_locations(cafile=ca_cert)
    return ctx
