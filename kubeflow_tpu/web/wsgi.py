"""Minimal WSGI application core: routing, JSON envelopes, error mapping.

Plays the role Flask plays for the reference's web backends
(`crud_backend/serving.py`, `base_app.py:22-175`): path-parameter routing,
before-request hooks (authn slots in here), JSON request/response helpers,
and a uniform error surface that maps storage errors onto HTTP statuses.
`serve()` hosts apps on an HTTP/1.1 threading server with persistent
connections and chunked streaming responses (the WSGI `__call__` remains
for external hosts); `TestClient` drives the app in-process for tests
(the reference tests its Flask apps the same way, via
`app.test_client()`).
"""

from __future__ import annotations

import http.client
import http.server
import json
import logging
import mimetypes
import pathlib
import re
import threading
import traceback
from typing import Any, Callable
from urllib.parse import parse_qs
import socketserver

from kubeflow_tpu.testing import fake_apiserver as storage
from kubeflow_tpu.utils import tracing

log = logging.getLogger(__name__)



class HttpError(Exception):
    def __init__(
        self,
        status: int,
        message: str,
        headers: list[tuple[str, str]] | None = None,
    ):
        super().__init__(message)
        self.status = status
        self.message = message
        # Extra response headers the error must carry to be honest —
        # e.g. 429 + Retry-After (the serving boundary's backpressure
        # contract, serving/server.py).
        self.headers = list(headers or [])


class Request:
    def __init__(self, environ: dict):
        self.environ = environ
        self.method = environ.get("REQUEST_METHOD", "GET").upper()
        self.path = environ.get("PATH_INFO", "/")
        self.query: dict[str, str] = {
            k: v[-1]
            for k, v in parse_qs(environ.get("QUERY_STRING", "")).items()
        }
        self.headers: dict[str, str] = {}
        for key, value in environ.items():
            if key.startswith("HTTP_"):
                self.headers[key[5:].replace("_", "-").lower()] = value
        if "CONTENT_TYPE" in environ:
            self.headers["content-type"] = environ["CONTENT_TYPE"]
        self.path_params: dict[str, str] = {}
        self.user: str | None = None  # set by the authn hook
        self._body: bytes | None = None

    @property
    def body(self) -> bytes:
        if self._body is None:
            try:
                length = int(self.environ.get("CONTENT_LENGTH") or 0)
            except ValueError:
                length = 0
            stream = self.environ.get("wsgi.input")
            self._body = stream.read(length) if stream and length else b""
        return self._body

    def json(self) -> dict:
        if not self.body:
            return {}
        try:
            parsed = json.loads(self.body)
        except ValueError as e:
            raise HttpError(400, f"invalid JSON body: {e}") from e
        if not isinstance(parsed, dict):
            raise HttpError(400, "JSON body must be an object")
        return parsed


class Response:
    def __init__(
        self,
        body: bytes = b"",
        status: int = 200,
        content_type: str = "application/json",
        headers: list[tuple[str, str]] | None = None,
    ):
        self.body = body
        self.status = status
        self.headers = list(headers or [])
        self.headers.append(("Content-Type", content_type))

    @property
    def status_line(self) -> str:
        return f"{self.status} {http.client.responses.get(self.status, 'Unknown')}"

    @property
    def content_type(self) -> str:
        for key, value in self.headers:
            if key.lower() == "content-type":
                return value
        return ""

    def json(self) -> dict:
        return json.loads(self.body)


class StreamResponse(Response):
    """A response whose body is produced incrementally (chunked transfer
    on the wire). `chunks` is an iterable of bytes; each chunk is framed
    and flushed as soon as it is produced, so a handler can hold the
    connection open and push events as they happen — the transport under
    the streaming watch (client-go's chunked watch stream analog)."""

    def __init__(
        self,
        chunks,
        status: int = 200,
        content_type: str = "application/json",
        headers: list[tuple[str, str]] | None = None,
    ):
        super().__init__(b"", status=status, content_type=content_type,
                         headers=headers)
        self.chunks = chunks


def encode_json(payload: Any) -> bytes:
    """THE JSON wire encoder: compact separators, utf-8. Every response
    body (and the apiserver facade's cached watch-event lines) goes
    through here so the wire form is uniformly slim — the fat default
    separators cost ~2 bytes per key on every object of every list."""
    return json.dumps(payload, separators=(",", ":")).encode()


def json_response(
    payload: Any,
    status: int = 200,
    headers: list[tuple[str, str]] | None = None,
) -> Response:
    return Response(encode_json(payload), status=status, headers=headers)


def success_response(field: str | None = None, value: Any = None) -> Response:
    """The crud_backend envelope (`api/utils.py:6`): always
    `{"success": true, "status": 200, <field>: <value>}`."""
    body: dict[str, Any] = {"success": True, "status": 200}
    if field is not None:
        body[field] = value
    return json_response(body)


def error_response(
    status: int,
    message: str,
    headers: list[tuple[str, str]] | None = None,
) -> Response:
    return json_response(
        {"success": False, "status": status, "log": message},
        status=status,
        headers=headers,
    )


class _Route:
    def __init__(self, pattern: str, methods: tuple[str, ...], handler):
        self.methods = methods
        self.handler = handler
        # Declared form, kept for introspection (the OpenAPI drift gate
        # derives spec paths from it — kubeflow_tpu/web/openapi.py).
        self.pattern = pattern
        # <name> matches one path segment; <name:path> matches the rest of
        # the path, slashes included (catch-all routes). Single-pass sub so
        # the emitted (?P<name>...) groups are never re-substituted.
        def group(m: re.Match) -> str:
            return (
                f"(?P<{m.group(1)}>.*)"
                if m.group(2)
                else f"(?P<{m.group(1)}>[^/]+)"
            )

        regex = re.sub(
            r"<([a-zA-Z_][a-zA-Z0-9_]*)(:path)?>", group, pattern
        )
        self.regex = re.compile(f"^{regex}$")


class App:
    """A WSGI application with path-param routes and before-request hooks."""

    def __init__(self, name: str):
        self.name = name
        self._routes: list[_Route] = []
        self._before: list[Callable[[Request], Response | None]] = []
        self._static_root: pathlib.Path | None = None
        self._static_index: str = "index.html"
        self.add_route("/healthz", self._healthz, methods=("GET",))

    def mount_static(
        self, root: str | pathlib.Path, index: str = "index.html"
    ) -> None:
        """Serve the app's SPA: GET / returns `index`, other unmatched GET
        paths are looked up under `root` (the crud_backend pattern of one
        backend serving both /api and its compiled frontend,
        `crud_backend/serving.py`). API routes always win."""
        self._static_root = pathlib.Path(root).resolve()
        self._static_index = index

    def _try_static(self, req: Request) -> Response | None:
        if self._static_root is None or req.method != "GET":
            return None
        rel = req.path.lstrip("/") or self._static_index
        target = (self._static_root / rel).resolve()
        # resolve() collapses ../ — refuse anything escaping the root.
        if not target.is_relative_to(self._static_root):
            return None
        if not target.is_file():
            return None
        ctype = (
            mimetypes.guess_type(str(target))[0] or "application/octet-stream"
        )
        return Response(body=target.read_bytes(), content_type=ctype)

    def _healthz(self, req: Request) -> Response:
        # Probe endpoint (crud_backend registers the same; authn hooks
        # must skip it so kubelet probes don't need identity headers).
        return json_response({"app": self.name, "ok": True})

    def before_request(
        self, hook: Callable[[Request], Response | None]
    ) -> None:
        self._before.append(hook)

    def add_route(
        self,
        pattern: str,
        handler: Callable[[Request], Response],
        methods: tuple[str, ...] = ("GET",),
    ) -> None:
        self._routes.append(
            _Route(pattern, tuple(m.upper() for m in methods), handler)
        )

    def route(self, pattern: str, methods: tuple[str, ...] = ("GET",)):
        def deco(handler):
            self.add_route(pattern, handler, methods)
            return handler

        return deco

    # -- dispatch ----------------------------------------------------------

    def handle(self, req: Request) -> Response:
        # Every request is a span; an inbound x-kftpu-trace-id header
        # continues the caller's trace (the traceparent analog).
        with tracing.tracer.span(
            "http",
            trace_id=tracing.from_header(req.headers),
            app=self.name,
            method=req.method,
            path=req.path,
        ) as span:
            resp = self._handle_inner(req)
            span.attributes["status"] = resp.status
            return resp

    def _handle_inner(self, req: Request) -> Response:
        try:
            return self._dispatch(req)
        except HttpError as e:
            return error_response(e.status, e.message, headers=e.headers)
        except storage.NotFound as e:
            return error_response(404, str(e))
        except storage.AlreadyExists as e:
            return error_response(409, str(e))
        except storage.Conflict as e:
            return error_response(409, str(e))
        except storage.Invalid as e:
            return error_response(422, str(e))
        except storage.Unavailable as e:
            # Fail-stopped durable store (WAL write failed): etcd-down
            # semantics, clients should back off/retry elsewhere.
            return error_response(503, str(e))
        except Exception as e:  # crud_backend's catch-all 500 handler
            log.error("%s: unhandled error: %s", self.name, e)
            log.debug("%s", traceback.format_exc())
            return error_response(500, f"internal error: {e}")

    def _dispatch(self, req: Request) -> Response:
        # Hooks run on EVERY request, matched or not (crud_backend's
        # global before_request): unauthenticated clients must not be able
        # to probe the route table via 404-vs-405 responses.
        for hook in self._before:
            resp = hook(req)
            if resp is not None:
                return resp
        matched_path = False
        for route in self._routes:
            m = route.regex.match(req.path)
            if not m:
                continue
            matched_path = True
            if req.method not in route.methods:
                continue
            req.path_params = m.groupdict()
            return route.handler(req)
        if matched_path:
            raise HttpError(405, f"{req.method} not allowed on {req.path}")
        static = self._try_static(req)
        if static is not None:
            return static
        raise HttpError(404, f"no route for {req.path}")

    # -- WSGI --------------------------------------------------------------

    def __call__(self, environ: dict, start_response):
        # WSGI compatibility shim (serve() speaks HTTP/1.1 directly; this
        # lets the same App run under any external WSGI host).
        resp = self.handle(Request(environ))
        start_response(resp.status_line, resp.headers)
        if isinstance(resp, StreamResponse):
            return resp.chunks
        return [resp.body]


class _Http11Handler(http.server.BaseHTTPRequestHandler):
    """HTTP/1.1 handler with persistent connections.

    The previous server was wsgiref's (HTTP/1.0, one request per
    connection), which made every control-plane call — and with the TLS
    facade, every watch poll of every client — pay a fresh TCP + TLS
    handshake. Reference controllers hold ONE connection through
    client-go's shared transport (`notebook_controller.go:516` manager);
    this handler gives our clients the same: the per-CONNECTION thread
    loops on `handle_one_request` until the peer closes or idles out."""

    protocol_version = "HTTP/1.1"
    # TCP_NODELAY (StreamRequestHandler honors this): predict responses
    # are small writes on a persistent connection, and Nagle + delayed
    # ACK turns each into a ~40ms stall on the reply leg.
    disable_nagle_algorithm = True
    # One knob, two jobs: reaps idle keep-alive connections (the blocking
    # readline for the next request times out) and caps a stalled
    # client's grip on its thread. Streaming responses emit bookmarks
    # far more often than this, so healthy streams never trip it.
    timeout = 75.0

    def log_message(self, format, *args):  # noqa: A002 - stdlib signature
        log.debug("%s %s", self.address_string(), format % args)

    def _environ(self) -> dict:
        import io
        import urllib.parse as _up

        path, _, query = self.path.partition("?")
        try:
            length = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            length = 0
        body = self.rfile.read(length) if length > 0 else b""
        environ = {
            "REQUEST_METHOD": self.command,
            "PATH_INFO": _up.unquote(path),
            "QUERY_STRING": query,
            "CONTENT_LENGTH": str(len(body)),
            "wsgi.input": io.BytesIO(body),
            "REMOTE_ADDR": self.client_address[0],
        }
        for key, value in self.headers.items():
            if key.lower() == "content-type":
                environ["CONTENT_TYPE"] = value
            else:
                environ["HTTP_" + key.upper().replace("-", "_")] = value
        return environ

    def _handle(self) -> None:
        if "chunked" in self.headers.get("Transfer-Encoding", "").lower():
            # Bodies are framed by Content-Length only. Silently ignoring
            # a chunked body would leave its bytes on the persistent
            # connection to be parsed as the NEXT request — the classic
            # desync/smuggling shape keep-alive makes possible (the old
            # per-request server was immune by closing). Refuse it and
            # drop the connection so the unread framing dies with it.
            self.send_response(501)
            self.send_header("Content-Length", "0")
            self.send_header("Connection", "close")
            self.end_headers()
            self.close_connection = True
            return
        server = self.server
        with server.counter_lock:
            server.requests_served += 1
        resp = server.app.handle(Request(self._environ()))
        try:
            if isinstance(resp, StreamResponse):
                self._send_stream(resp)
            else:
                self._send(resp)
        except (BrokenPipeError, ConnectionResetError, TimeoutError):
            # Peer went away mid-response; nothing to salvage.
            self.close_connection = True

    def _send(self, resp: Response) -> None:
        self.send_response(resp.status)
        body = resp.body
        framed = False
        for key, value in resp.headers:
            if key.lower() in ("content-length", "transfer-encoding"):
                framed = True
            self.send_header(key, value)
        # Content-Length is what keeps the connection reusable: without
        # it an HTTP/1.1 peer can only detect end-of-body by close. A
        # handler that set its own framing header keeps it — emitting a
        # second Content-Length (or one beside Transfer-Encoding) gives
        # the two framings a keep-alive peer could disagree on, the
        # request-smuggling shape persistent connections make dangerous.
        if not framed:
            self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if self.command != "HEAD":
            self.wfile.write(body)

    def _send_stream(self, resp: StreamResponse) -> None:
        """Chunked transfer: each produced chunk is framed and flushed as
        it arrives (the watch stream's transport). Chunked framing is
        self-delimiting, so the connection stays reusable after the
        terminal 0-chunk."""
        self.send_response(resp.status)
        for key, value in resp.headers:
            self.send_header(key, value)
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        try:
            for chunk in resp.chunks:
                if not chunk:
                    continue
                self.wfile.write(
                    f"{len(chunk):x}\r\n".encode() + chunk + b"\r\n"
                )
                self.wfile.flush()
            self.wfile.write(b"0\r\n\r\n")
        finally:
            close = getattr(resp.chunks, "close", None)
            if close is not None:
                close()  # generator cleanup runs even on client abort

    do_GET = _handle
    do_POST = _handle
    do_PUT = _handle
    do_DELETE = _handle
    do_PATCH = _handle
    # HEAD/OPTIONS route through the app like every other method (the
    # old WSGI server did the same: routes that don't declare them
    # answer 405, not a transport-level 501). HEAD responses carry the
    # headers + Content-Length with the body suppressed (see _send).
    do_HEAD = _handle
    do_OPTIONS = _handle

    def handle_one_request(self):
        try:
            super().handle_one_request()
        except (ConnectionResetError, BrokenPipeError, TimeoutError):
            # Idle keep-alive reap / mid-request disconnects are routine.
            self.close_connection = True


class _HttpServer(socketserver.ThreadingMixIn, http.server.HTTPServer):
    """Threading server, one thread per CONNECTION (not per request —
    keep-alive means a thread serves its peer's whole request train)."""

    daemon_threads = True
    # Listen backlog. The stdlib default (5) makes any burst of
    # connections — a controller fleet reconnecting after an apiserver
    # restart, a watcher fleet attaching — overflow the accept queue,
    # and the dropped SYNs come back as ~1s retransmit stalls per
    # client. Real servers listen deep (nginx defaults to 511).
    request_queue_size = 128

    def __init__(self, addr, handler, app: App):
        self.app = app
        # Observability for the O(1)-handshakes property: the load test
        # asserts tls_handshakes stays flat while requests_served grows.
        self.tls_handshakes = 0
        self.requests_served = 0
        self.counter_lock = threading.Lock()
        super().__init__(addr, handler)


class _TlsHttpServer(_HttpServer):
    """TLS server whose handshake runs in the per-connection thread, not
    the accept loop: wrap_socket here defers the handshake
    (do_handshake_on_connect=False; it happens transparently on the
    handler's first read) — otherwise one stalled client parks accept()
    and blocks every request including /healthz, the exact failure the
    per-connection-thread design exists to prevent."""

    ssl_context = None

    def get_request(self):
        conn, addr = super().get_request()
        conn = self.ssl_context.wrap_socket(
            conn, server_side=True, do_handshake_on_connect=False
        )
        # One wrapped connection = one handshake (keep-alive then
        # amortizes it over every request the peer sends on it).
        with self.counter_lock:
            self.tls_handshakes += 1
        return conn, addr

    def handle_error(self, request, client_address):
        # Failed handshakes (plaintext probes, wrong CA) are expected
        # noise at a TLS port, not tracebacks worth stderr.
        log.debug("error handling request from %s", client_address,
                  exc_info=True)


def serve(app: App, host: str = "0.0.0.0", port: int = 8080, tls=None):
    """Serve on a background thread; returns (server, thread).

    HTTP/1.1 with keep-alive: a client holding its connection pays one
    TCP (and TLS) handshake for its whole request train. Connections are
    handled on per-connection threads so a stalled client can't block
    /healthz probes. `server.server_port` gives the bound port (use
    port=0 in tests).

    `tls` (a `web.tls.TlsPaths`) serves HTTPS: each accepted connection
    is wrapped server-side (handshake in the connection thread), so a
    plaintext client gets a handshake error — never a served request.
    The secure facade always passes this (bearer tokens must not ride
    cleartext; the reference's only custom listener is TLS-only,
    `admission-webhook/main.go:443`)."""
    server_class = _HttpServer if tls is None else _TlsHttpServer
    server = server_class((host, port), _Http11Handler, app)
    if tls is not None:
        from kubeflow_tpu.web import tls as tlsmod

        server.ssl_context = tlsmod.server_context(tls)
    # Bound accept(): select() can report a pending connection that the
    # peer RESETS before we accept it (a client tearing down right as
    # the server stops — exactly the e2e shutdown sequence), and a
    # blocking accept on a drained queue then parks the serve loop
    # FOREVER — shutdown() never returns. A listener timeout turns that
    # into a retried OSError; accepted connections get their own
    # (handler-level) timeout instead of inheriting the listener's.
    server.socket.settimeout(5.0)
    thread = threading.Thread(
        target=server.serve_forever, name=f"{app.name}-http", daemon=True
    )
    thread.start()
    return server, thread


class TestClient:
    """In-process client: builds a WSGI environ and calls the app."""

    __test__ = False  # not a pytest test class

    def __init__(self, app: App, headers: dict[str, str] | None = None):
        self.app = app
        self.headers = dict(headers or {})

    def request(
        self,
        method: str,
        path: str,
        body: dict | None = None,
        headers: dict[str, str] | None = None,
        raw: bytes | None = None,
        content_type: str = "application/json",
    ) -> Response:
        """`body` is a JSON object; `raw` posts bytes verbatim with
        `content_type` (the binary tensor wire surface in tests)."""
        import io

        path, _, query = path.partition("?")
        if raw is None:
            raw = json.dumps(body).encode() if body is not None else b""
        environ = {
            "REQUEST_METHOD": method,
            "PATH_INFO": path,
            "QUERY_STRING": query,
            "CONTENT_LENGTH": str(len(raw)),
            "CONTENT_TYPE": content_type,
            "wsgi.input": io.BytesIO(raw),
        }
        for key, value in {**self.headers, **(headers or {})}.items():
            environ["HTTP_" + key.upper().replace("-", "_")] = value
        return self.app.handle(Request(environ))

    def get(self, path: str, **kw) -> Response:
        return self.request("GET", path, **kw)

    def post(self, path: str, body: dict | None = None, **kw) -> Response:
        return self.request("POST", path, body=body, **kw)

    def patch(self, path: str, body: dict | None = None, **kw) -> Response:
        return self.request("PATCH", path, body=body, **kw)

    def delete(self, path: str, **kw) -> Response:
        return self.request("DELETE", path, **kw)
