// Unit tests for the native control-plane core: json, workqueue, store.
// Plain assert-style tests (no gtest in the image), run via ctest.

#include <cassert>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include "json.h"
#include "store.h"
#include "wal.h"
#include "workqueue.h"

using kftpu::Json;

#define CHECK(cond)                                                    \
  do {                                                                 \
    if (!(cond)) {                                                     \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__,     \
                   #cond);                                             \
      std::exit(1);                                                    \
    }                                                                  \
  } while (0)

#include <cstdio>
#include <cstdlib>

static void TestJsonRoundtrip() {
  std::string err;
  Json v;
  CHECK(Json::Parse(R"({"a": [1, 2.5, true, null, "xé\n"],
                        "b": {"nested": -3e2}})",
                    &v, &err));
  CHECK(v.is_object());
  CHECK(v.get("a").is_array());
  CHECK(v.get("a").as_array()[1].as_number() == 2.5);
  CHECK(v.get("a").as_array()[4].as_string() == "x\xc3\xa9\n");
  CHECK(v.get("b").get("nested").as_number() == -300.0);
  // Integers serialize without a fraction; keys sort deterministically.
  Json back;
  CHECK(Json::Parse(v.dump(), &back, &err));
  CHECK(back.dump() == v.dump());
  CHECK(Json(int64_t{42}).dump() == "42");
  // Surrogate pair.
  CHECK(Json::Parse(R"("😀")", &v, &err));
  CHECK(v.as_string() == "\xF0\x9F\x98\x80");
  // Malformed inputs fail with a message.
  CHECK(!Json::Parse("{\"a\":}", &v, &err));
  CHECK(!Json::Parse("[1,]", &v, &err));
  CHECK(!Json::Parse("01x", &v, &err));
  CHECK(!Json::Parse("\"unterminated", &v, &err));
}

static void TestWorkqueueBasics() {
  void* q = kftpu_wq_new(10, 1000);
  char buf[64];
  // Dedup: same key added twice dequeues once.
  kftpu_wq_add(q, "a");
  kftpu_wq_add(q, "a");
  kftpu_wq_add(q, "b");
  CHECK(kftpu_wq_len(q) == 2);
  CHECK(kftpu_wq_get(q, buf, sizeof(buf), 0) == 1);
  CHECK(std::strcmp(buf, "a") == 0);
  CHECK(kftpu_wq_get(q, buf, sizeof(buf), 0) == 1);
  CHECK(std::strcmp(buf, "b") == 0);
  CHECK(kftpu_wq_get(q, buf, sizeof(buf), 0) == 0);  // empty poll
  // In-flight re-add lands after done (dirty semantics).
  kftpu_wq_done(q, "b");
  kftpu_wq_add(q, "a");  // "a" still in flight
  CHECK(kftpu_wq_get(q, buf, sizeof(buf), 0) == 0);
  kftpu_wq_done(q, "a");
  CHECK(kftpu_wq_get(q, buf, sizeof(buf), 0) == 1);
  CHECK(std::strcmp(buf, "a") == 0);
  kftpu_wq_done(q, "a");
  kftpu_wq_free(q);
}

static void TestWorkqueueDelayAndBackoff() {
  void* q = kftpu_wq_new(10, 80);
  char buf[64];
  // A delayed key is not ready immediately; a sooner re-add supersedes.
  kftpu_wq_add_after(q, "k", 5000);
  CHECK(kftpu_wq_get(q, buf, sizeof(buf), 0) == 0);
  kftpu_wq_add(q, "k");
  CHECK(kftpu_wq_get(q, buf, sizeof(buf), 0) == 1);
  kftpu_wq_done(q, "k");
  // Exponential backoff: 10, 20, 40, 80, 80 (capped).
  CHECK(kftpu_wq_requeue_error(q, "k") == 10);
  CHECK(kftpu_wq_requeue_error(q, "k") == 20);
  CHECK(kftpu_wq_requeue_error(q, "k") == 40);
  CHECK(kftpu_wq_requeue_error(q, "k") == 80);
  CHECK(kftpu_wq_requeue_error(q, "k") == 80);
  kftpu_wq_forget(q, "k");
  CHECK(kftpu_wq_requeue_error(q, "k") == 10);
  // Blocking get picks the key up when the backoff matures.
  CHECK(kftpu_wq_get(q, buf, sizeof(buf), 2000) == 1);
  CHECK(std::strcmp(buf, "k") == 0);
  kftpu_wq_free(q);
}

static void TestWorkqueueThreaded() {
  void* q = kftpu_wq_new(1, 10);
  const int kKeys = 200;
  std::vector<int> seen(kKeys, 0);
  std::vector<std::thread> workers;
  for (int w = 0; w < 4; ++w) {
    workers.emplace_back([&, q] {
      char buf[64];
      while (kftpu_wq_get(q, buf, sizeof(buf), 200) == 1) {
        int idx = std::atoi(buf + 1);
        __atomic_add_fetch(&seen[idx], 1, __ATOMIC_SEQ_CST);
        kftpu_wq_done(q, buf);
      }
    });
  }
  for (int i = 0; i < kKeys; ++i) {
    char key[64];
    std::snprintf(key, sizeof(key), "k%d", i);
    kftpu_wq_add(q, key);
  }
  for (auto& t : workers) t.join();
  for (int i = 0; i < kKeys; ++i) CHECK(seen[i] >= 1);
  kftpu_wq_shutdown(q);
  kftpu_wq_free(q);
}

static Json MustParse(const char* text) {
  Json v;
  std::string err;
  CHECK(Json::Parse(text, &v, &err));
  return v;
}

static void TestStoreCrud() {
  void* s = kftpu_store_new();
  const char* out = kftpu_store_create(
      s, R"({"kind":"TpuJob","metadata":{"name":"j1","namespace":"ml",
             "labels":{"team":"a"}},"spec":{"workers":4},"status":{}})");
  CHECK(out != nullptr);
  Json stored = MustParse(out);
  CHECK(stored.get("metadata").get_string("uid") == "uid-1");
  CHECK(stored.get("metadata").get("resourceVersion").as_number() == 1);
  CHECK(stored.get("metadata").get("generation").as_number() == 1);
  // Duplicate create fails.
  CHECK(kftpu_store_create(
            s, R"({"kind":"TpuJob","metadata":{"name":"j1","namespace":"ml"}})") ==
        nullptr);
  CHECK(kftpu_store_status() == KFTPU_STORE_ALREADY_EXISTS);
  // Get.
  out = kftpu_store_get(s, "TpuJob", "ml", "j1");
  CHECK(out && MustParse(out).get("spec").get("workers").as_number() == 4);
  CHECK(kftpu_store_get(s, "TpuJob", "ml", "nope") == nullptr);
  CHECK(kftpu_store_status() == KFTPU_STORE_NOT_FOUND);
  // Cluster scope: namespace "" is a real scope of its own (Leases,
  // Nodes, ClusterRoles), NOT an alias for "default" — get/delete must
  // round-trip it exactly (FakeApiServer parity).
  CHECK(kftpu_store_create(
            s, R"({"kind":"Lease","metadata":{"name":"ha","namespace":""}})") !=
        nullptr);
  CHECK(kftpu_store_get(s, "Lease", "", "ha") != nullptr);
  CHECK(kftpu_store_get(s, "Lease", "default", "ha") == nullptr);
  CHECK(kftpu_store_status() == KFTPU_STORE_NOT_FOUND);
  // List: "" selects ONLY cluster scope; nullptr selects everything.
  out = kftpu_store_list(s, "Lease", "", nullptr);
  CHECK(out && MustParse(out).as_array().size() == 1);
  out = kftpu_store_list(s, "TpuJob", "", nullptr);
  CHECK(out && MustParse(out).as_array().size() == 0);
  out = kftpu_store_list(s, "TpuJob", nullptr, nullptr);
  CHECK(out && MustParse(out).as_array().size() == 1);
  CHECK(kftpu_store_delete(s, "Lease", "", "ha") == 0);
  // Spec update bumps generation + rv; stale rv conflicts. Metadata
  // fields are replaced from the incoming object, so labels must ride
  // along (same replace semantics as the Python store).
  out = kftpu_store_update(
      s, R"({"kind":"TpuJob","metadata":{"name":"j1","namespace":"ml",
             "labels":{"team":"a"}},"spec":{"workers":8}})",
      0);
  CHECK(out != nullptr);
  stored = MustParse(out);
  CHECK(stored.get("metadata").get("generation").as_number() == 2);
  CHECK(kftpu_store_update(
            s, R"({"kind":"TpuJob","metadata":{"name":"j1","namespace":"ml",
                   "resourceVersion":1},"spec":{"workers":9}})",
            0) == nullptr);
  CHECK(kftpu_store_status() == KFTPU_STORE_CONFLICT);
  // Status update does not bump generation.
  out = kftpu_store_update(
      s, R"({"kind":"TpuJob","metadata":{"name":"j1","namespace":"ml"},
             "status":{"phase":"Running"}})",
      1);
  CHECK(out != nullptr);
  stored = MustParse(out);
  CHECK(stored.get("metadata").get("generation").as_number() == 2);
  CHECK(stored.get("status").get_string("phase") == "Running");
  CHECK(stored.get("spec").get("workers").as_number() == 8);
  // List with label selector.
  kftpu_store_create(
      s, R"({"kind":"TpuJob","metadata":{"name":"j2","namespace":"ml",
             "labels":{"team":"b"}},"spec":{}})");
  out = kftpu_store_list(s, "TpuJob", "ml", R"({"team":"a"})");
  CHECK(out != nullptr);
  Json listed = MustParse(out);
  CHECK(listed.as_array().size() == 1);
  CHECK(listed.as_array()[0].get("metadata").get_string("name") == "j1");
  out = kftpu_store_list(s, "TpuJob", nullptr, nullptr);
  CHECK(MustParse(out).as_array().size() == 2);
  kftpu_store_free(s);
}

static void TestStoreFinalizersAndCascade() {
  void* s = kftpu_store_new();
  // Owner with finalizer.
  const char* out = kftpu_store_create(
      s, R"({"kind":"Profile","metadata":{"name":"p","namespace":"default",
             "finalizers":["cleanup"]},"spec":{}})");
  CHECK(out != nullptr);
  std::string owner_uid =
      MustParse(out).get("metadata").get_string("uid");
  // Dependent owned by it.
  std::string dep = std::string(
      R"({"kind":"Pod","metadata":{"name":"d","namespace":"default",
          "ownerReferences":[{"kind":"Profile","name":"p","uid":")") +
      owner_uid + R"("}]},"spec":{}})";
  CHECK(kftpu_store_create(s, dep.c_str()) != nullptr);
  // Delete owner: finalizer defers removal (deletionTimestamp set).
  CHECK(kftpu_store_delete(s, "Profile", "default", "p") == KFTPU_STORE_OK);
  out = kftpu_store_get(s, "Profile", "default", "p");
  CHECK(out != nullptr);
  CHECK(MustParse(out).get("metadata").get("deletionTimestamp").is_number());
  CHECK(kftpu_store_len(s) == 2);
  // Clearing the finalizer completes deletion and cascades to the pod.
  out = kftpu_store_update(
      s, R"({"kind":"Profile","metadata":{"name":"p","namespace":"default",
             "finalizers":[]},"spec":{}})",
      0);
  CHECK(out != nullptr);
  CHECK(kftpu_store_len(s) == 0);
  // Journal saw the whole story, ending in both DELETEDs.
  int64_t cursor = 0;
  out = kftpu_store_events(s, 0, &cursor);
  Json events = MustParse(out);
  size_t n = events.as_array().size();
  CHECK(n >= 4);
  int deleted = 0;
  for (const Json& ev : events.as_array())
    if (ev.get_string("type") == "DELETED") deleted++;
  CHECK(deleted == 2);
  // Trim empties the journal.
  kftpu_store_trim(s, cursor);
  int64_t cursor2 = cursor;
  out = kftpu_store_events(s, cursor, &cursor2);
  CHECK(MustParse(out).as_array().empty());
  kftpu_store_free(s);
}

static void TestStoreNamespaceDrain() {
  void* s = kftpu_store_new();
  kftpu_store_create(
      s, R"({"kind":"Namespace","metadata":{"name":"team1","namespace":"default"},"spec":{}})");
  kftpu_store_create(
      s, R"({"kind":"Notebook","metadata":{"name":"nb","namespace":"team1"},"spec":{}})");
  kftpu_store_create(
      s, R"({"kind":"Notebook","metadata":{"name":"nb2","namespace":"other"},"spec":{}})");
  CHECK(kftpu_store_delete(s, "Namespace", "default", "team1") ==
        KFTPU_STORE_OK);
  CHECK(kftpu_store_get(s, "Notebook", "team1", "nb") == nullptr);
  CHECK(kftpu_store_get(s, "Notebook", "other", "nb2") != nullptr);
  kftpu_store_free(s);
}

static void TestWalAppendSnapshotReopen() {
  char dir[] = "/tmp/kftpu_wal_test_XXXXXX";
  CHECK(mkdtemp(dir) != nullptr);
  std::string sub = std::string(dir) + "/state";  // open() must mkdir it
  void* w = kftpu_wal_open(sub.c_str());
  CHECK(w != nullptr);
  CHECK(std::string(kftpu_wal_read_snapshot(w)) == "");
  CHECK(std::string(kftpu_wal_read_journal(w)) == "");
  CHECK(kftpu_wal_append(w, R"({"rv":1,"event":"ADDED"})") == 0);
  CHECK(kftpu_wal_append(w, R"({"rv":2,"event":"MODIFIED"})") == 0);
  CHECK(std::string(kftpu_wal_read_journal(w)) ==
        "{\"rv\":1,\"event\":\"ADDED\"}\n{\"rv\":2,\"event\":\"MODIFIED\"}\n");
  // Snapshot replaces atomically and truncates the WAL.
  CHECK(kftpu_wal_snapshot(w, R"({"format":1,"rv":2})") == 0);
  CHECK(std::string(kftpu_wal_read_snapshot(w)) == R"({"format":1,"rv":2})");
  CHECK(std::string(kftpu_wal_read_journal(w)) == "");
  CHECK(kftpu_wal_append(w, R"({"rv":3,"event":"DELETED"})") == 0);
  kftpu_wal_free(w);
  // A second open (the restarted-apiserver path) sees durable state and
  // appends after the existing tail, not over it.
  void* w2 = kftpu_wal_open(sub.c_str());
  CHECK(w2 != nullptr);
  CHECK(std::string(kftpu_wal_read_snapshot(w2)) == R"({"format":1,"rv":2})");
  CHECK(std::string(kftpu_wal_read_journal(w2)) ==
        "{\"rv\":3,\"event\":\"DELETED\"}\n");
  CHECK(kftpu_wal_append(w2, R"({"rv":4,"event":"ADDED"})") == 0);
  CHECK(std::string(kftpu_wal_read_journal(w2)) ==
        "{\"rv\":3,\"event\":\"DELETED\"}\n{\"rv\":4,\"event\":\"ADDED\"}\n");
  kftpu_wal_free(w2);
  // Unwritable parent fails open with a message, not a crash.
  CHECK(kftpu_wal_open("/proc/nope/state") == nullptr);
  CHECK(std::strlen(kftpu_wal_error()) > 0);
}

int main() {
  TestJsonRoundtrip();
  TestWorkqueueBasics();
  TestWorkqueueDelayAndBackoff();
  TestWorkqueueThreaded();
  TestStoreCrud();
  TestStoreFinalizersAndCascade();
  TestStoreNamespaceDrain();
  TestWalAppendSnapshotReopen();
  std::printf("core_test: all ok\n");
  return 0;
}
