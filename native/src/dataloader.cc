#include "dataloader.h"

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr char kMagic[4] = {'K', 'F', 'T', 'R'};
constexpr uint32_t kVersion = 1;
constexpr size_t kHeaderBytes = 4 + 4 + 8 + 8;

struct Header {
  uint64_t record_bytes;
  uint64_t record_count;
};

bool WriteHeader(std::FILE* f, const Header& h) {
  if (std::fseek(f, 0, SEEK_SET) != 0) return false;
  if (std::fwrite(kMagic, 1, 4, f) != 4) return false;
  uint32_t version = kVersion;
  if (std::fwrite(&version, sizeof(version), 1, f) != 1) return false;
  if (std::fwrite(&h.record_bytes, sizeof(h.record_bytes), 1, f) != 1)
    return false;
  if (std::fwrite(&h.record_count, sizeof(h.record_count), 1, f) != 1)
    return false;
  return true;
}

bool ReadHeader(std::FILE* f, Header* h) {
  char magic[4];
  uint32_t version;
  if (std::fread(magic, 1, 4, f) != 4) return false;
  if (std::memcmp(magic, kMagic, 4) != 0) return false;
  if (std::fread(&version, sizeof(version), 1, f) != 1) return false;
  if (version != kVersion) return false;
  if (std::fread(&h->record_bytes, sizeof(h->record_bytes), 1, f) != 1)
    return false;
  if (std::fread(&h->record_count, sizeof(h->record_count), 1, f) != 1)
    return false;
  return true;
}

// -- writer -----------------------------------------------------------------

struct Writer {
  std::FILE* f;
  Header header;
};

// -- loader -----------------------------------------------------------------

struct FileSpan {
  std::string path;
  uint64_t first;  // global index of this file's record 0
  uint64_t count;
};

struct Batch {
  std::vector<char> data;
  int64_t records;
};

class Loader {
 public:
  Loader(std::vector<FileSpan> files, Header geom, int64_t batch_size,
         int32_t shard_id, int32_t shards, int64_t shuffle_buffer,
         uint64_t seed, int32_t num_threads, int32_t prefetch,
         bool drop_remainder, int32_t loop_epochs)
      : files_(std::move(files)),
        geom_(geom),
        batch_size_(batch_size),
        shard_id_(shard_id),
        shards_(shards),
        shuffle_buffer_(shuffle_buffer),
        seed_(seed),
        prefetch_(std::max(1, prefetch)),
        drop_remainder_(drop_remainder),
        loop_epochs_(loop_epochs) {
    // The shard's record indices: global round-robin by index, so every
    // process's shard interleaves across files (balanced even when files
    // differ in size).
    for (uint64_t g = shard_id_; g < geom_.record_count;
         g += static_cast<uint64_t>(shards_))
      shard_.push_back(g);
    int n = std::max(1, static_cast<int>(num_threads));
    producer_ = std::thread(&Loader::Produce, this, n);
  }

  ~Loader() {
    {
      std::lock_guard<std::mutex> lock(mu_);
      stop_ = true;
      cv_.notify_all();
    }
    if (producer_.joinable()) producer_.join();
  }

  uint64_t record_bytes() const { return geom_.record_bytes; }
  int64_t shard_records() const {
    return static_cast<int64_t>(shard_.size());
  }
  int64_t batches() const { return batches_.load(); }

  int64_t Next(void* out) {
    std::unique_lock<std::mutex> lock(mu_);
    cv_.wait(lock, [&] { return !queue_.empty() || done_ || failed_; });
    if (failed_) return -1;
    if (queue_.empty()) return 0;  // done_
    Batch b = std::move(queue_.front());
    queue_.pop_front();
    cv_.notify_all();
    lock.unlock();
    std::memcpy(out, b.data.data(), b.data.size());
    batches_.fetch_add(1);
    return b.records;
  }

 private:
  // One producer thread orchestrates epochs; it fans record reads out to
  // a per-epoch worker pool (files are read with pread-style seeks, so
  // workers share no file state).
  void Produce(int num_threads) {
    std::mt19937_64 rng(seed_);
    for (int epoch = 0; loop_epochs_ == 0 || epoch < loop_epochs_;
         ++epoch) {
      std::vector<uint64_t> order = shard_;
      if (shuffle_buffer_ > 0) {
        // Buffered shuffle (tf.data semantics): windowed, so huge shards
        // never need a full permutation in memory — but for shards that
        // fit (the common case here) a buffer >= shard is a full shuffle.
        std::mt19937_64 erng(seed_ + epoch + 1);
        size_t buf = std::min<size_t>(shuffle_buffer_, order.size());
        for (size_t i = 0; i < order.size(); ++i) {
          size_t j = i + erng() % std::min(buf, order.size() - i);
          std::swap(order[i], order[j]);
        }
      }
      if (!EmitEpoch(order, num_threads)) return;
    }
    std::lock_guard<std::mutex> lock(mu_);
    done_ = true;
    cv_.notify_all();
  }

  bool EmitEpoch(const std::vector<uint64_t>& order, int num_threads) {
    for (size_t off = 0; off < order.size();
         off += static_cast<size_t>(batch_size_)) {
      size_t n = std::min<size_t>(batch_size_, order.size() - off);
      if (n < static_cast<size_t>(batch_size_) && drop_remainder_) break;
      Batch b;
      b.records = static_cast<int64_t>(n);
      b.data.resize(n * geom_.record_bytes);
      std::atomic<bool> ok{true};
      std::atomic<size_t> next{0};
      auto read_some = [&] {
        size_t i;
        while ((i = next.fetch_add(1)) < n) {
          if (!ReadRecord(order[off + i],
                          b.data.data() + i * geom_.record_bytes))
            ok = false;
        }
      };
      if (n > 1 && num_threads > 1) {
        std::vector<std::thread> pool;
        for (int t = 1; t < num_threads; ++t)
          pool.emplace_back(read_some);
        read_some();
        for (auto& t : pool) t.join();
      } else {
        read_some();
      }
      std::unique_lock<std::mutex> lock(mu_);
      if (!ok) {
        failed_ = true;
        cv_.notify_all();
        return false;
      }
      cv_.wait(lock, [&] {
        return stop_ || queue_.size() < static_cast<size_t>(prefetch_);
      });
      if (stop_) return false;
      queue_.push_back(std::move(b));
      cv_.notify_all();
    }
    return true;
  }

  bool ReadRecord(uint64_t global_index, char* dst) {
    // Locate the file holding this global index.
    const FileSpan* span = nullptr;
    for (const FileSpan& fs : files_)
      if (global_index >= fs.first && global_index < fs.first + fs.count) {
        span = &fs;
        break;
      }
    if (!span) return false;
    std::FILE* f = std::fopen(span->path.c_str(), "rb");
    if (!f) return false;
    uint64_t local = global_index - span->first;
    bool ok =
        std::fseek(f,
                   static_cast<long>(kHeaderBytes +
                                     local * geom_.record_bytes),
                   SEEK_SET) == 0 &&
        std::fread(dst, 1, geom_.record_bytes, f) == geom_.record_bytes;
    std::fclose(f);
    return ok;
  }

  std::vector<FileSpan> files_;
  Header geom_;
  int64_t batch_size_;
  int32_t shard_id_, shards_;
  int64_t shuffle_buffer_;
  uint64_t seed_;
  int32_t prefetch_;
  bool drop_remainder_;
  int32_t loop_epochs_;

  std::vector<uint64_t> shard_;
  std::deque<Batch> queue_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::thread producer_;
  bool stop_ = false;
  bool done_ = false;
  bool failed_ = false;
  std::atomic<int64_t> batches_{0};
};

std::vector<std::string> Split(const std::string& s, char sep) {
  std::vector<std::string> out;
  size_t start = 0;
  while (start <= s.size()) {
    size_t end = s.find(sep, start);
    if (end == std::string::npos) end = s.size();
    if (end > start) out.push_back(s.substr(start, end - start));
    start = end + 1;
  }
  return out;
}

}  // namespace

extern "C" {

void* kftpu_recwriter_open(const char* path, uint64_t record_bytes) {
  if (!path || record_bytes == 0) return nullptr;
  std::FILE* f = std::fopen(path, "wb");
  if (!f) return nullptr;
  auto* w = new Writer{f, {record_bytes, 0}};
  if (!WriteHeader(f, w->header)) {  // placeholder; rewritten on close
    std::fclose(f);
    delete w;
    return nullptr;
  }
  return w;
}

int32_t kftpu_recwriter_append(void* wp, const void* data) {
  auto* w = static_cast<Writer*>(wp);
  if (std::fwrite(data, 1, w->header.record_bytes, w->f) !=
      w->header.record_bytes)
    return -1;
  w->header.record_count++;
  return 0;
}

int64_t kftpu_recwriter_close(void* wp) {
  auto* w = static_cast<Writer*>(wp);
  int64_t count = static_cast<int64_t>(w->header.record_count);
  bool ok = WriteHeader(w->f, w->header);
  ok = (std::fclose(w->f) == 0) && ok;
  delete w;
  return ok ? count : -1;
}

int32_t kftpu_recfile_stat(const char* path, uint64_t* record_bytes,
                           uint64_t* record_count) {
  std::FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  Header h;
  bool ok = ReadHeader(f, &h);
  std::fclose(f);
  if (!ok) return -1;
  if (record_bytes) *record_bytes = h.record_bytes;
  if (record_count) *record_count = h.record_count;
  return 0;
}

void* kftpu_loader_new(const char* paths, int64_t batch_size,
                       int32_t shard_id, int32_t shards,
                       int64_t shuffle_buffer, uint64_t seed,
                       int32_t num_threads, int32_t prefetch,
                       int32_t drop_remainder, int32_t loop_epochs) {
  if (!paths || batch_size < 1 || shards < 1 || shard_id < 0 ||
      shard_id >= shards || loop_epochs < 0)
    return nullptr;
  std::vector<FileSpan> files;
  Header geom{0, 0};
  for (const std::string& path : Split(paths, ';')) {
    std::FILE* f = std::fopen(path.c_str(), "rb");
    if (!f) return nullptr;
    Header h;
    bool ok = ReadHeader(f, &h);
    std::fclose(f);
    if (!ok) return nullptr;
    if (geom.record_bytes == 0) geom.record_bytes = h.record_bytes;
    if (h.record_bytes != geom.record_bytes) return nullptr;
    files.push_back(FileSpan{path, geom.record_count, h.record_count});
    geom.record_count += h.record_count;
  }
  if (files.empty() || geom.record_count == 0) return nullptr;
  return new Loader(std::move(files), geom, batch_size, shard_id, shards,
                    shuffle_buffer, seed, num_threads, prefetch,
                    drop_remainder != 0, loop_epochs);
}

void kftpu_loader_free(void* l) { delete static_cast<Loader*>(l); }

uint64_t kftpu_loader_record_bytes(void* l) {
  return static_cast<Loader*>(l)->record_bytes();
}

int64_t kftpu_loader_shard_records(void* l) {
  return static_cast<Loader*>(l)->shard_records();
}

int64_t kftpu_loader_next(void* l, void* out) {
  return static_cast<Loader*>(l)->Next(out);
}

int64_t kftpu_loader_batches(void* l) {
  return static_cast<Loader*>(l)->batches();
}

}  // extern "C"
