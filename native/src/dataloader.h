// Native input pipeline: record files + a multithreaded prefetching
// loader.
//
// The reference outsources its input path entirely (tf.data inside the
// tf_cnn_benchmarks image; S3 downloads in the openmpi sidecar,
// `controller/controller.py:53-57`). This platform keeps the data plane
// native: a compiled loader reads fixed-size records off disk with a
// thread pool, shuffles with a seeded buffer, assembles batches into
// caller-owned memory (numpy arrays), and prefetches ahead of the
// training step so host IO overlaps device compute. Sharding is
// first-class: process p of n reads only its records, matching the
// TpuJob gang contract (TPUJOB_PROCESS_ID / TPUJOB_NUM_PROCESSES).
//
// File format ("KFTR"): a 24-byte header
//     magic "KFTR" | u32 version | u64 record_bytes | u64 record_count
// followed by record_count raw records of record_bytes each. Fixed-size
// records keep shapes static — exactly what XLA wants — and make random
// access O(1) without an index.
//
// C ABI for ctypes. Thread-safe per handle.

#pragma once
#include <cstdint>

extern "C" {

// -- writing ----------------------------------------------------------------

// Create a record file (truncates). Returns a handle or NULL.
void* kftpu_recwriter_open(const char* path, uint64_t record_bytes);
// Append one record (must be record_bytes long). Returns 0 or -1.
int32_t kftpu_recwriter_append(void* w, const void* data);
// Finalize header and close. Returns record count written, or -1.
int64_t kftpu_recwriter_close(void* w);

// -- inspection -------------------------------------------------------------

// Read a file's header: fills record_bytes/record_count. 0 or -1.
int32_t kftpu_recfile_stat(const char* path, uint64_t* record_bytes,
                           uint64_t* record_count);

// -- loading ----------------------------------------------------------------

// Build a loader over ';'-separated record files (same record_bytes).
//   batch_size      records per batch
//   shard_id/shards shard the record space (round-robin by global index)
//   shuffle_buffer  >0 enables seeded buffered shuffle of that size
//   seed            shuffle seed (per-epoch reseeded as seed+epoch)
//   num_threads     reader threads
//   prefetch        max assembled batches queued ahead
//   drop_remainder  1: only full batches; 0: final short batch allowed
//                   (its record count is returned by next())
//   loop_epochs     0: iterate forever; N>0: stop after N epochs
// Returns handle or NULL (bad args/unreadable file).
void* kftpu_loader_new(const char* paths, int64_t batch_size,
                       int32_t shard_id, int32_t shards,
                       int64_t shuffle_buffer, uint64_t seed,
                       int32_t num_threads, int32_t prefetch,
                       int32_t drop_remainder, int32_t loop_epochs);
void kftpu_loader_free(void* l);

// Loader geometry.
uint64_t kftpu_loader_record_bytes(void* l);
// Records in this loader's shard per epoch.
int64_t kftpu_loader_shard_records(void* l);

// Copy the next batch into out (capacity batch_size*record_bytes).
// Returns the number of records delivered (>0), 0 at end of data
// (loop_epochs exhausted), or -1 on IO error. Blocks while the pipeline
// fills (GIL is released by ctypes).
int64_t kftpu_loader_next(void* l, void* out);

// Batches delivered so far (monitoring).
int64_t kftpu_loader_batches(void* l);

}  // extern "C"
