// Tests for the native record format + prefetching loader.

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <set>
#include <string>
#include <vector>

#include "dataloader.h"

#define CHECK(cond)                                                    \
  do {                                                                 \
    if (!(cond)) {                                                     \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__,     \
                   #cond);                                             \
      std::exit(1);                                                    \
    }                                                                  \
  } while (0)

static std::string WriteFile(const char* name, int first, int count,
                             uint64_t record_bytes) {
  std::string path = std::string("/tmp/kftpu_dl_") + name + ".rec";
  void* w = kftpu_recwriter_open(path.c_str(), record_bytes);
  CHECK(w != nullptr);
  std::vector<char> rec(record_bytes);
  for (int i = 0; i < count; ++i) {
    int value = first + i;
    std::memcpy(rec.data(), &value, sizeof(value));
    CHECK(kftpu_recwriter_append(w, rec.data()) == 0);
  }
  CHECK(kftpu_recwriter_close(w) == count);
  return path;
}

static void TestRoundtripAndStat() {
  std::string p = WriteFile("rt", 100, 7, 32);
  uint64_t rb = 0, rc = 0;
  CHECK(kftpu_recfile_stat(p.c_str(), &rb, &rc) == 0);
  CHECK(rb == 32 && rc == 7);
  CHECK(kftpu_recfile_stat("/tmp/kftpu_dl_missing.rec", &rb, &rc) == -1);
}

static void TestSingleEpochCoversAll() {
  std::string a = WriteFile("a", 0, 10, 16);
  std::string b = WriteFile("b", 10, 6, 16);
  std::string paths = a + ";" + b;
  void* l = kftpu_loader_new(paths.c_str(), 4, 0, 1, /*shuffle=*/100,
                             /*seed=*/7, /*threads=*/2, /*prefetch=*/2,
                             /*drop_remainder=*/0, /*epochs=*/1);
  CHECK(l != nullptr);
  CHECK(kftpu_loader_record_bytes(l) == 16);
  CHECK(kftpu_loader_shard_records(l) == 16);
  std::set<int> seen;
  std::vector<char> buf(4 * 16);
  int64_t n;
  while ((n = kftpu_loader_next(l, buf.data())) > 0) {
    for (int64_t i = 0; i < n; ++i) {
      int v;
      std::memcpy(&v, buf.data() + i * 16, sizeof(v));
      seen.insert(v);
    }
  }
  CHECK(n == 0);  // clean end of data
  CHECK(seen.size() == 16);  // every record exactly once (it's a set,
                             // so also: all 16 distinct values appeared)
  for (int i = 0; i < 16; ++i) CHECK(seen.count(i) == 1);
  kftpu_loader_free(l);
}

static void TestShardingPartitions() {
  std::string p = WriteFile("shard", 0, 20, 8);
  std::set<int> all;
  for (int shard = 0; shard < 2; ++shard) {
    void* l = kftpu_loader_new(p.c_str(), 5, shard, 2, /*shuffle=*/0,
                               0, 1, 1, /*drop_remainder=*/0, 1);
    CHECK(l != nullptr);
    CHECK(kftpu_loader_shard_records(l) == 10);
    std::vector<char> buf(5 * 8);
    int64_t n;
    std::set<int> mine;
    while ((n = kftpu_loader_next(l, buf.data())) > 0)
      for (int64_t i = 0; i < n; ++i) {
        int v;
        std::memcpy(&v, buf.data() + i * 8, sizeof(v));
        mine.insert(v);
      }
    for (int v : mine) {
      CHECK(all.count(v) == 0);  // disjoint shards
      all.insert(v);
    }
    kftpu_loader_free(l);
  }
  CHECK(all.size() == 20);
}

static void TestDropRemainderAndLooping() {
  std::string p = WriteFile("loop", 0, 10, 8);
  // drop_remainder: 10 records / batch 4 -> 2 batches per epoch.
  void* l = kftpu_loader_new(p.c_str(), 4, 0, 1, 0, 0, 1, 1,
                             /*drop_remainder=*/1, /*epochs=*/3);
  CHECK(l != nullptr);
  std::vector<char> buf(4 * 8);
  int batches = 0;
  while (kftpu_loader_next(l, buf.data()) == 4) batches++;
  CHECK(batches == 6);
  kftpu_loader_free(l);
  // loop_epochs=0 streams forever; just check it comfortably exceeds an
  // epoch.
  l = kftpu_loader_new(p.c_str(), 4, 0, 1, 16, 3, 2, 2, 1, 0);
  for (int i = 0; i < 25; ++i)
    CHECK(kftpu_loader_next(l, buf.data()) == 4);
  CHECK(kftpu_loader_batches(l) == 25);
  kftpu_loader_free(l);
}

static void TestShuffleIsSeededAndPerEpoch() {
  std::string p = WriteFile("shuf", 0, 64, 8);
  auto epoch_order = [&](uint64_t seed) {
    void* l = kftpu_loader_new(p.c_str(), 64, 0, 1, 64, seed, 1, 1, 0, 2);
    std::vector<char> buf(64 * 8);
    std::vector<int> e1(64), e2(64);
    CHECK(kftpu_loader_next(l, buf.data()) == 64);
    for (int i = 0; i < 64; ++i)
      std::memcpy(&e1[i], buf.data() + i * 8, sizeof(int));
    CHECK(kftpu_loader_next(l, buf.data()) == 64);
    for (int i = 0; i < 64; ++i)
      std::memcpy(&e2[i], buf.data() + i * 8, sizeof(int));
    kftpu_loader_free(l);
    return std::make_pair(e1, e2);
  };
  auto [a1, a2] = epoch_order(11);
  auto [b1, b2] = epoch_order(11);
  auto [c1, c2] = epoch_order(12);
  CHECK(a1 == b1 && a2 == b2);  // deterministic given a seed
  CHECK(a1 != a2);              // reshuffled across epochs
  CHECK(a1 != c1);              // seed changes the order
}

static void TestBadInputs() {
  CHECK(kftpu_loader_new("/tmp/kftpu_dl_missing.rec", 4, 0, 1, 0, 0, 1, 1,
                         0, 1) == nullptr);
  std::string p = WriteFile("bad", 0, 4, 8);
  CHECK(kftpu_loader_new(p.c_str(), 0, 0, 1, 0, 0, 1, 1, 0, 1) == nullptr);
  CHECK(kftpu_loader_new(p.c_str(), 4, 2, 2, 0, 0, 1, 1, 0, 1) == nullptr);
  // Mixed record sizes refuse to combine.
  std::string q = WriteFile("bad2", 0, 4, 16);
  std::string both = p + ";" + q;
  CHECK(kftpu_loader_new(both.c_str(), 4, 0, 1, 0, 0, 1, 1, 0, 1) ==
        nullptr);
}

int main() {
  TestRoundtripAndStat();
  TestSingleEpochCoversAll();
  TestShardingPartitions();
  TestDropRemainderAndLooping();
  TestShuffleIsSeededAndPerEpoch();
  TestBadInputs();
  std::printf("dataloader_test: all ok\n");
  return 0;
}
