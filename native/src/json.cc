#include "json.h"

#include <cmath>
#include <cstdio>
#include <cstring>

namespace kftpu {

namespace {

const Json kNullJson;

struct Parser {
  const char* p;
  const char* end;
  std::string* err;

  bool Fail(const std::string& what) {
    if (err) {
      char buf[64];
      std::snprintf(buf, sizeof(buf), " at byte %zd",
                    static_cast<size_t>(p - start));
      *err = what + buf;
    }
    return false;
  }

  const char* start;

  void SkipWs() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
      ++p;
  }

  bool Literal(const char* lit) {
    size_t n = std::strlen(lit);
    if (static_cast<size_t>(end - p) < n || std::memcmp(p, lit, n) != 0)
      return Fail("bad literal");
    p += n;
    return true;
  }

  static void AppendUtf8(std::string* s, uint32_t cp) {
    if (cp < 0x80) {
      s->push_back(static_cast<char>(cp));
    } else if (cp < 0x800) {
      s->push_back(static_cast<char>(0xC0 | (cp >> 6)));
      s->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    } else if (cp < 0x10000) {
      s->push_back(static_cast<char>(0xE0 | (cp >> 12)));
      s->push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
      s->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    } else {
      s->push_back(static_cast<char>(0xF0 | (cp >> 18)));
      s->push_back(static_cast<char>(0x80 | ((cp >> 12) & 0x3F)));
      s->push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
      s->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    }
  }

  bool Hex4(uint32_t* out) {
    if (end - p < 4) return Fail("truncated \\u escape");
    uint32_t v = 0;
    for (int i = 0; i < 4; ++i) {
      char c = *p++;
      v <<= 4;
      if (c >= '0' && c <= '9')
        v |= c - '0';
      else if (c >= 'a' && c <= 'f')
        v |= c - 'a' + 10;
      else if (c >= 'A' && c <= 'F')
        v |= c - 'A' + 10;
      else
        return Fail("bad hex digit");
    }
    *out = v;
    return true;
  }

  bool String(std::string* out) {
    ++p;  // opening quote
    while (p < end) {
      unsigned char c = *p;
      if (c == '"') {
        ++p;
        return true;
      }
      if (c == '\\') {
        ++p;
        if (p >= end) return Fail("truncated escape");
        char e = *p++;
        switch (e) {
          case '"': out->push_back('"'); break;
          case '\\': out->push_back('\\'); break;
          case '/': out->push_back('/'); break;
          case 'b': out->push_back('\b'); break;
          case 'f': out->push_back('\f'); break;
          case 'n': out->push_back('\n'); break;
          case 'r': out->push_back('\r'); break;
          case 't': out->push_back('\t'); break;
          case 'u': {
            uint32_t cp;
            if (!Hex4(&cp)) return false;
            if (cp >= 0xD800 && cp <= 0xDBFF) {  // high surrogate
              if (end - p < 2 || p[0] != '\\' || p[1] != 'u')
                return Fail("unpaired surrogate");
              p += 2;
              uint32_t lo;
              if (!Hex4(&lo)) return false;
              if (lo < 0xDC00 || lo > 0xDFFF)
                return Fail("bad low surrogate");
              cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
            }
            AppendUtf8(out, cp);
            break;
          }
          default:
            return Fail("bad escape");
        }
      } else if (c < 0x20) {
        return Fail("control char in string");
      } else {
        out->push_back(static_cast<char>(c));
        ++p;
      }
    }
    return Fail("unterminated string");
  }

  bool Value(Json* out, int depth) {
    if (depth > 256) return Fail("nesting too deep");
    SkipWs();
    if (p >= end) return Fail("unexpected end");
    switch (*p) {
      case 'n':
        if (!Literal("null")) return false;
        *out = Json();
        return true;
      case 't':
        if (!Literal("true")) return false;
        *out = Json(true);
        return true;
      case 'f':
        if (!Literal("false")) return false;
        *out = Json(false);
        return true;
      case '"': {
        std::string s;
        if (!String(&s)) return false;
        *out = Json(std::move(s));
        return true;
      }
      case '[': {
        ++p;
        JsonArray arr;
        SkipWs();
        if (p < end && *p == ']') {
          ++p;
          *out = Json(std::move(arr));
          return true;
        }
        while (true) {
          Json elem;
          if (!Value(&elem, depth + 1)) return false;
          arr.push_back(std::move(elem));
          SkipWs();
          if (p >= end) return Fail("unterminated array");
          if (*p == ',') {
            ++p;
            continue;
          }
          if (*p == ']') {
            ++p;
            *out = Json(std::move(arr));
            return true;
          }
          return Fail("expected ',' or ']'");
        }
      }
      case '{': {
        ++p;
        JsonObject obj;
        SkipWs();
        if (p < end && *p == '}') {
          ++p;
          *out = Json(std::move(obj));
          return true;
        }
        while (true) {
          SkipWs();
          if (p >= end || *p != '"') return Fail("expected object key");
          std::string key;
          if (!String(&key)) return false;
          SkipWs();
          if (p >= end || *p != ':') return Fail("expected ':'");
          ++p;
          Json val;
          if (!Value(&val, depth + 1)) return false;
          obj[std::move(key)] = std::move(val);
          SkipWs();
          if (p >= end) return Fail("unterminated object");
          if (*p == ',') {
            ++p;
            continue;
          }
          if (*p == '}') {
            ++p;
            *out = Json(std::move(obj));
            return true;
          }
          return Fail("expected ',' or '}'");
        }
      }
      default: {
        // number
        const char* num_start = p;
        if (p < end && *p == '-') ++p;
        while (p < end &&
               ((*p >= '0' && *p <= '9') || *p == '.' || *p == 'e' ||
                *p == 'E' || *p == '+' || *p == '-'))
          ++p;
        if (p == num_start) return Fail("unexpected character");
        std::string num(num_start, p - num_start);
        char* parse_end = nullptr;
        double d = std::strtod(num.c_str(), &parse_end);
        if (parse_end != num.c_str() + num.size())
          return Fail("bad number");
        *out = Json(d);
        return true;
      }
    }
  }
};

void DumpString(const std::string& s, std::string* out) {
  out->push_back('"');
  for (unsigned char c : s) {
    switch (c) {
      case '"': *out += "\\\""; break;
      case '\\': *out += "\\\\"; break;
      case '\b': *out += "\\b"; break;
      case '\f': *out += "\\f"; break;
      case '\n': *out += "\\n"; break;
      case '\r': *out += "\\r"; break;
      case '\t': *out += "\\t"; break;
      default:
        if (c < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof(buf), "\\u%04x", c);
          *out += buf;
        } else {
          out->push_back(static_cast<char>(c));  // UTF-8 passthrough
        }
    }
  }
  out->push_back('"');
}

void DumpValue(const Json& j, std::string* out) {
  switch (j.type()) {
    case Json::Type::kNull:
      *out += "null";
      break;
    case Json::Type::kBool:
      *out += j.as_bool() ? "true" : "false";
      break;
    case Json::Type::kNumber: {
      double d = j.as_number();
      if (std::isfinite(d) && d == std::floor(d) && std::fabs(d) < 1e15) {
        char buf[32];
        std::snprintf(buf, sizeof(buf), "%lld",
                      static_cast<long long>(d));
        *out += buf;
      } else {
        char buf[32];
        std::snprintf(buf, sizeof(buf), "%.17g", d);
        *out += buf;
      }
      break;
    }
    case Json::Type::kString:
      DumpString(j.as_string(), out);
      break;
    case Json::Type::kArray: {
      out->push_back('[');
      bool first = true;
      for (const Json& e : j.as_array()) {
        if (!first) out->push_back(',');
        first = false;
        DumpValue(e, out);
      }
      out->push_back(']');
      break;
    }
    case Json::Type::kObject: {
      out->push_back('{');
      bool first = true;
      for (const auto& [k, v] : j.as_object()) {
        if (!first) out->push_back(',');
        first = false;
        DumpString(k, out);
        out->push_back(':');
        DumpValue(v, out);
      }
      out->push_back('}');
      break;
    }
  }
}

}  // namespace

const Json& Json::get(const std::string& key) const {
  if (!is_object()) return kNullJson;
  auto it = as_object().find(key);
  return it == as_object().end() ? kNullJson : it->second;
}

bool Json::has(const std::string& key) const {
  return is_object() && as_object().count(key) > 0;
}

std::string Json::get_string(const std::string& key,
                             const std::string& def) const {
  const Json& v = get(key);
  return v.is_string() ? v.as_string() : def;
}

std::string Json::dump() const {
  std::string out;
  DumpValue(*this, &out);
  return out;
}

bool Json::Parse(const std::string& text, Json* out, std::string* err) {
  Parser parser{text.data(), text.data() + text.size(), err};
  parser.start = text.data();
  if (!parser.Value(out, 0)) return false;
  parser.SkipWs();
  if (parser.p != parser.end) return parser.Fail("trailing garbage");
  return true;
}

}  // namespace kftpu
